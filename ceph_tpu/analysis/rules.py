"""Rule set for the static analyzer.

Two families, both specific to this codebase's hazard classes:

JAX trace-safety (the `@jax.jit` kernels in ops/, ec/, models/):
  trace-side-effect    Python side effects baked in at trace time
  trace-host-sync      implicit device->host syncs inside traced code
  uint8-overflow       narrow-dtype arithmetic in the GF(2^8) paths
  trace-static-hazard  params needing static_argnums/static_argnames
  trace-numpy          bare numpy ops applied to traced values

async/daemon safety (the mon/osd/mds/rgw asyncio daemons):
  async-blocking       event-loop-blocking calls in `async def` bodies
  lock-order           static lock-order cycles (lockdep, at lint time)
  lock-no-await        un-awaited asyncio.Lock acquisition / sync `with`
  sync-encode-in-async direct ec_util.encode* / codec .encode() in
                       `async def` bodies under ceph_tpu/osd/ — the
                       encode runs ON the event loop instead of
                       riding the micro-batching encode service
                       (osd/encode_service.py)
  unhedged-gather      bare asyncio.gather over shard sub-op jobs in
                       ceph_tpu/osd/ outside the hedge primitive
                       (osd/hedge.py) — the fan-out completes at the
                       slowest peer's pace; all-shard write/absence
                       gathers are baselined with justifications
  span-leak            tracer.start(...) whose span is not finished
                       in a finally / context manager on every path —
                       a leaked span never reaches the ring, the
                       critical-path histograms, or the tail
                       exemplars; use `async with tracer.span(...)`
                       (common/tracing.py) or finish in a finally

EC dispatch discipline:
  jit-bypass-plan      direct jax.jit on shape-polymorphic EC entry
                       points that bypass the ExecPlan cache
                       (ceph_tpu/ec/plan.py): every shape retraces and
                       the compile is invisible to plan.stats()
  unguarded-device-dispatch
                       raw device dispatch (backend.matmul /
                       gf.gf_matmul_tpu / the pallas word kernels) in
                       ec/, ops/, osd/ outside the breaker guard
                       (common/circuit.py device_call): a wedged or
                       faulting accelerator surfaces as a raised
                       exception instead of degrading to the
                       bit-exact host path
  unplanned-mesh-dispatch
                       raw shard_map/pjit in ec/, osd/, parallel/
                       bypassing the plan cache (ec/plan.py
                       tracked_jit / mesh plan kinds) or the breaker
                       guard: the compile is invisible to
                       plan.stats(), binds a device set no health
                       shrink can retire, and dispatches without
                       watchdog or sick-chip attribution
  unplanned-compute-dispatch
                       raw coded-compute kernel invocation
                       (compute.kernels.device_eval) in compute/,
                       osd/ outside the plan cache (ec/plan.py
                       compute_eval) or circuit.device_call: the
                       compile is invisible to plan.stats() and the
                       dispatch has no watchdog or bit-exact host
                       degradation
  unscheduled-bitmatrix-xor
                       naive row-walk XOR loops (bitwise_xor.reduce /
                       subscripted ^= accumulation inside a loop) in
                       ec/ outside ec/xsched.py + ec/plan.py: the XOR
                       program bypasses the schedule compiler's CSE,
                       memoization and stats — execute a compiled
                       schedule (xsched.compile_matrix +
                       execute_host) instead; pure-GF multiply loops
                       (wide-word fields) are not XOR walks and are
                       exempt
  raw-process-group    jax.distributed.initialize/shutdown outside
                       the parallel/multihost.py bootstrap seam: a
                       process group joined elsewhere skips the gloo
                       CPU-collectives config, the host-topology
                       map, the plan keys' process-topology element,
                       and the collective-safe membership agreement
                       — host loss would wedge a collective instead
                       of reading as a timeout

store durability discipline:
  commit-before-durability
                       `on_commit`/ack callbacks in ceph_tpu/os/
                       reachable before the store's durability point
                       (block fsync / sync KV batch): the acked
                       transaction can vanish on power loss — the
                       invariant the crash sweep (os/faultstore.py)
                       checks dynamically, enforced here at lint time

inference serving discipline:
  unbudgeted-approx-result
                       an approximate combine (least-squares solve of
                       missing shard contributions feeding combined
                       scores) in ceph_tpu/inference/ returned without
                       consulting the error-budget gate
                       (inference/fisher.py check_budget): a result
                       whose estimated error nobody priced against the
                       caller's budget — every approximate serving
                       result must pass check_budget or yield to the
                       exact full-decode fallback

loadgen/bench discipline:
  unbounded-latency-buffer
                       appending per-op latency samples to a plain
                       list inside a loadgen/bench loop: an open-loop
                       sweep offers ops at a fixed rate regardless of
                       completions, so the buffer grows with offered
                       load times duration — stream into the bounded
                       log-bucket histogram
                       (ceph_tpu/loadgen/stats.py) instead

Every rule walks its own scope only (nested defs are analyzed as their
own traced/async functions), so findings never double-report.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional, Set

from ceph_tpu.analysis.core import (
    Analyzer, _is_jit_expr, dotted, dynamic_names_in,
)

# numpy/stdlib call classification ------------------------------------

_BLOCKING_CALLS = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.getoutput", "subprocess.getstatusoutput",
    "os.system", "os.popen", "os.wait", "os.waitpid",
    "urllib.request.urlopen", "socket.create_connection",
}
_BLOCKING_PREFIXES = ("requests.",)
_NUMPY_ALIASES = {"np", "numpy"}
_NARROW_DTYPES = {"uint8", "int8"}
# numpy attrs that are fine on traced values (metadata / dtype ctors)
_NUMPY_SAFE_ATTRS = {
    "shape", "ndim", "dtype", "uint8", "int8", "uint16", "int16",
    "uint32", "int32", "uint64", "int64", "float16", "float32",
    "float64", "bool_", "newaxis", "pi", "e", "inf", "nan",
}
# host-sync builtins on a traced value.  len() is NOT here: on a
# traced array it reads the static leading dim (shape metadata), no
# sync and no trace error.
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}


def walk_scope(root: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs or
    classes (lambdas ARE included: they trace/run in this scope)."""
    stack = [c for c in ast.iter_child_nodes(root)]
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


def _resolved_callee(mod, node: ast.Call) -> str:
    """Dotted callee with the import table applied to the head, so
    `import subprocess as sp; sp.run` still reads 'subprocess.run'."""
    name = dotted(node.func)
    if not name:
        return ""
    head, _, rest = name.partition(".")
    src = mod.imports.get(head)
    if src is not None:
        src_mod, attr = src
        base = src_mod if attr is None else f"{src_mod}.{attr}"
        return f"{base}.{rest}" if rest else base
    return name


def _is_numpy_call(mod, node: ast.Call) -> Optional[str]:
    """Return the numpy attr name if this is a np.<attr>(...) call."""
    name = dotted(node.func)
    if not name:
        return None
    head, _, rest = name.partition(".")
    if not rest:
        return None
    src = mod.imports.get(head)
    base = head if src is None else src[0]
    if base in _NUMPY_ALIASES or base == "numpy":
        return rest.split(".")[0] if "." in rest else rest
    return None


def _args_tainted(node: ast.Call, tainted: Set[str]) -> bool:
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        if dynamic_names_in(arg) & tainted:
            return True
    return False


# ---------------------------------------------------------------------
# trace-side-effect
# ---------------------------------------------------------------------

def rule_trace_side_effect(a: Analyzer) -> None:
    for fi in a.project.traced_functions().values():
        mod = fi.module
        for node in walk_scope(fi.node):
            if isinstance(node, ast.Global):
                a.emit("trace-side-effect", mod, node,
                       f"`global {', '.join(node.names)}` inside traced "
                       f"`{fi.qualname}`: the mutation runs once at "
                       "trace time, not per call",
                       symbol=fi.qualname, scope_line=fi.lineno)
            elif isinstance(node, ast.Call):
                callee = _resolved_callee(mod, node)
                if callee == "print":
                    a.emit("trace-side-effect", mod, node,
                           f"print() inside traced `{fi.qualname}` fires "
                           "at trace time only (use jax.debug.print)",
                           symbol=fi.qualname, scope_line=fi.lineno)
                elif callee.startswith("time."):
                    a.emit("trace-side-effect", mod, node,
                           f"{callee}() inside traced `{fi.qualname}` is "
                           "evaluated once at trace time and baked into "
                           "the kernel",
                           symbol=fi.qualname, scope_line=fi.lineno)
                elif (callee.startswith(("numpy.random.", "random."))
                      or _is_numpy_call(mod, node) == "random"
                      or (_is_numpy_call(mod, node) or "").startswith(
                          "random")):
                    a.emit("trace-side-effect", mod, node,
                           f"host RNG inside traced `{fi.qualname}`: the "
                           "draw is frozen at trace time (thread "
                           "jax.random keys instead)",
                           symbol=fi.qualname, scope_line=fi.lineno)


# ---------------------------------------------------------------------
# trace-host-sync
# ---------------------------------------------------------------------

def rule_trace_host_sync(a: Analyzer) -> None:
    for fi in a.project.traced_functions().values():
        mod = fi.module
        tainted = a.project.tainted_locals(fi)
        for node in walk_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "item":
                if dynamic_names_in(f.value) & tainted:
                    a.emit("trace-host-sync", mod, node,
                           f".item() on a traced value in "
                           f"`{fi.qualname}` forces a device->host sync "
                           "(trace error under jit)",
                           symbol=fi.qualname, scope_line=fi.lineno)
            elif isinstance(f, ast.Name) and f.id in _SYNC_BUILTINS \
                    and node.args and _args_tainted(node, tainted):
                a.emit("trace-host-sync", mod, node,
                       f"{f.id}() on a traced value in `{fi.qualname}` "
                       "concretizes the tracer (host sync / trace "
                       "error)",
                       symbol=fi.qualname, scope_line=fi.lineno)
            else:
                np_attr = _is_numpy_call(mod, node)
                if np_attr in ("asarray", "array") and \
                        _args_tainted(node, tainted):
                    a.emit("trace-host-sync", mod, node,
                           f"np.{np_attr}() on a traced value in "
                           f"`{fi.qualname}` pulls the array to host "
                           "mid-trace (use jnp)",
                           symbol=fi.qualname, scope_line=fi.lineno)


# ---------------------------------------------------------------------
# uint8-overflow
# ---------------------------------------------------------------------

_OVERFLOW_OPS = {
    ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.LShift: "<<",
    ast.Pow: "**",
}


def _scope_nodes(root: ast.AST) -> Iterator[ast.AST]:
    """Child nodes of one variable scope: descends classes but stops
    at nested function boundaries (each function in mod.functions gets
    its own scope pass)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def _dtype_is_narrow(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return node.value in _NARROW_DTYPES
    name = dotted(node)
    return bool(name) and name.split(".")[-1] in _NARROW_DTYPES


class _NarrowTracker(ast.NodeVisitor):
    """Heuristic per-module dtype tracker: an expression is 'narrow'
    (uint8/int8) if it is built by an explicit narrow construction —
    jnp.uint8(x), .astype(np.uint8), dtype=np.uint8 kwargs — or derives
    from a local known to be narrow."""

    def __init__(self) -> None:
        self.narrow_names: Set[str] = set()

    def is_narrow(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            tail = name.split(".")[-1]
            if tail in _NARROW_DTYPES:
                return True
            if tail == "astype" and node.args and \
                    _dtype_is_narrow(node.args[0]):
                return True
            if tail == "view" and node.args and \
                    _dtype_is_narrow(node.args[0]):
                return True
            for kw in node.keywords:
                if kw.arg == "dtype" and _dtype_is_narrow(kw.value):
                    return True
            return False
        if isinstance(node, ast.Name):
            return node.id in self.narrow_names
        if isinstance(node, ast.Subscript):
            return self.is_narrow(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_narrow(node.left) or \
                self.is_narrow(node.right)
        if isinstance(node, (ast.UnaryOp,)):
            return self.is_narrow(node.operand)
        return False

    def feed_assign(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign) and self.is_narrow(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.narrow_names.add(tgt.id)


def rule_uint8_overflow(a: Analyzer) -> None:
    patterns = a.config.get("dtype_paths", ("ops/gf", "ec/"))
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if patterns and not any(p in rel for p in patterns):
            continue
        # narrow-name tracking is scoped per function (plus module
        # scope) so a uint8 local in one function can't taint a
        # same-named name elsewhere
        module_tracker = _NarrowTracker()
        for node in _scope_nodes(mod.tree):
            module_tracker.feed_assign(node)

        def check_scope(root: ast.AST) -> None:
            tracker = _NarrowTracker()
            tracker.narrow_names = set(module_tracker.narrow_names)
            nodes = list(_scope_nodes(root))
            for node in nodes:  # learn locals first, then flag
                tracker.feed_assign(node)
            for node in nodes:
                if isinstance(node, ast.BinOp) and \
                        type(node.op) in _OVERFLOW_OPS and (
                            tracker.is_narrow(node.left)
                            or tracker.is_narrow(node.right)):
                    sym = _enclosing_qualname(mod, node)
                    a.emit(
                        "uint8-overflow", mod, node,
                        f"uint8/int8 `{_OVERFLOW_OPS[type(node.op)]}` "
                        "wraps silently at 256; promote an operand "
                        "(.astype(jnp.int32)) or justify in the "
                        "baseline", severity="warning",
                        symbol=sym, scope_line=_scope_line(mod, node))

        check_scope(mod.tree)
        for fi in mod.functions.values():
            check_scope(fi.node)


def _enclosing_qualname(mod, node: ast.AST) -> str:
    cur = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for q, fi in mod.functions.items():
                if fi.node is cur:
                    return q
            return cur.name
    return "<module>"


def _scope_line(mod, node: ast.AST) -> int:
    cur = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.lineno
    return 0


# ---------------------------------------------------------------------
# trace-static-hazard
# ---------------------------------------------------------------------

def rule_trace_static_hazard(a: Analyzer) -> None:
    shape_ctors = {"zeros", "ones", "full", "empty", "arange",
                   "linspace", "eye", "broadcast_to"}
    for fi in a.project.traced_functions().values():
        if not fi.jit_decorated:
            continue
        mod = fi.module
        dynamic = set(fi.params) - fi.static_params - {"self"}
        names_in = dynamic_names_in

        for node in walk_scope(fi.node):
            hits: Set[str] = set()
            what = ""
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "range" and node.args:
                hits = set().union(*(names_in(x) for x in node.args)) \
                    & dynamic
                what = "range() bound"
            elif isinstance(node, (ast.If, ast.While)):
                hits = names_in(node.test) & dynamic
                what = f"`{type(node).__name__.lower()}` condition"
            elif isinstance(node, ast.Call):
                tail = (dotted(node.func) or "").split(".")[-1]
                if tail in shape_ctors and node.args:
                    hits = names_in(node.args[0]) & dynamic
                    what = f"{tail}() shape"
            if hits:
                names = ", ".join(sorted(hits))
                a.emit("trace-static-hazard", mod, node,
                       f"param(s) {names} of jit'd `{fi.qualname}` "
                       f"drive a {what}: mark static_argnums/"
                       "static_argnames or every new value recompiles "
                       "(traced values here even error)",
                       severity="warning", symbol=fi.qualname,
                       scope_line=fi.lineno)


# ---------------------------------------------------------------------
# trace-numpy
# ---------------------------------------------------------------------

def rule_trace_numpy(a: Analyzer) -> None:
    for fi in a.project.traced_functions().values():
        mod = fi.module
        tainted = a.project.tainted_locals(fi)
        for node in walk_scope(fi.node):
            if not isinstance(node, ast.Call):
                continue
            np_attr = _is_numpy_call(mod, node)
            if np_attr is None or np_attr in _NUMPY_SAFE_ATTRS or \
                    np_attr in ("asarray", "array", "random"):
                continue  # asarray/array: rule trace-host-sync's beat
            if _args_tainted(node, tainted):
                a.emit("trace-numpy", mod, node,
                       f"np.{np_attr}() applied to a traced value in "
                       f"`{fi.qualname}`: numpy can't trace — use the "
                       "jnp equivalent", severity="warning",
                       symbol=fi.qualname, scope_line=fi.lineno)


# ---------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------

def rule_async_blocking(a: Analyzer) -> None:
    for mod in a.project.modules.values():
        for fi in mod.functions.values():
            if not fi.is_async:
                continue
            for node in walk_scope(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = _resolved_callee(mod, node)
                blocking = (
                    callee in _BLOCKING_CALLS
                    or callee.startswith(_BLOCKING_PREFIXES))
                if callee == "open" and not _inside_lambda(mod, node):
                    a.emit("async-blocking", mod, node,
                           f"sync file I/O (open) in `async def "
                           f"{fi.qualname}` blocks the daemon's event "
                           "loop (asyncio.to_thread it)",
                           symbol=fi.qualname, scope_line=fi.lineno)
                elif blocking and not _inside_lambda(mod, node):
                    a.emit("async-blocking", mod, node,
                           f"{callee}() in `async def {fi.qualname}` "
                           "blocks the event loop for every task on "
                           "this daemon (await an async equivalent or "
                           "asyncio.to_thread)",
                           symbol=fi.qualname, scope_line=fi.lineno)


def _inside_lambda(mod, node: ast.AST) -> bool:
    """Calls inside a lambda run later (often shipped to an executor);
    the lambda boundary gets the benefit of the doubt."""
    cur = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, ast.Lambda):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


# ---------------------------------------------------------------------
# jit-bypass-plan
# ---------------------------------------------------------------------

# EC dispatch modules where jit compiles must route through the
# ExecPlan cache (ceph_tpu/ec/plan.py `tracked_jit` / a plan kind);
# the plan module itself is the one legitimate jit site.
_PLAN_PATHS = ("ec/", "ops/gf.py", "parallel/striped.py")
_PLAN_EXEMPT = ("ec/plan.py",)


def rule_jit_bypass_plan(a: Analyzer) -> None:
    """Direct jax.jit/pjit in the EC dispatch layers: every new shape
    pays a silent retrace outside the plan cache's bucketing, counters
    and LRU.  Route through ceph_tpu.ec.plan (tracked_jit or a plan
    kind), or baseline with a justification."""
    paths = a.config.get("plan_paths", _PLAN_PATHS)
    exempt = a.config.get("plan_exempt", _PLAN_EXEMPT)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        if any(e in rel for e in exempt):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_jit_expr(node.func):
                a.emit("jit-bypass-plan", mod, node,
                       "direct jax.jit in the EC dispatch layer "
                       "bypasses the ExecPlan cache: every new shape "
                       "retraces unseen by plan.stats() — use "
                       "ceph_tpu.ec.plan.tracked_jit or a plan kind",
                       severity="warning",
                       symbol=_enclosing_qualname(mod, node),
                       scope_line=_scope_line(mod, node))
        for fi in mod.functions.values():
            for dec in fi.node.decorator_list:
                direct = _is_jit_expr(dec)
                via_partial = (
                    isinstance(dec, ast.Call)
                    and (dotted(dec.func) or "").split(".")[-1]
                    == "partial" and dec.args
                    and _is_jit_expr(dec.args[0]))
                if direct or via_partial:
                    a.emit("jit-bypass-plan", mod, dec,
                           f"`{fi.qualname}` is jit-decorated in the "
                           "EC dispatch layer, bypassing the ExecPlan "
                           "cache (shape-polymorphic entry points "
                           "retrace per shape) — route through "
                           "ceph_tpu.ec.plan",
                           severity="warning", symbol=fi.qualname,
                           scope_line=fi.lineno)


# ---------------------------------------------------------------------
# unguarded-device-dispatch
# ---------------------------------------------------------------------

# modules whose device dispatches must route through the breaker guard
# (ceph_tpu/common/circuit.py device_call): ec/, ops/ and osd/ host
# the production data path — a raw dispatch there turns a device fault
# into a client-visible error instead of a host-path degrade
_DEVICE_DISPATCH_PATHS = ("ceph_tpu/ec/", "ceph_tpu/ops/",
                          "ceph_tpu/osd/")
# callee identities that ARE device dispatches: the mesh pipeline
# entry, the single-device XLA kernel, and the pallas word kernels
_DEVICE_ENTRY_TAILS = {"gf_matmul_tpu", "gf_matmul_words",
                       "gf_matmul_words_runtime"}
_DEVICE_ENTRY_SUFFIXES = (".backend.matmul",)


def _inside_device_call(mod, node: ast.AST) -> bool:
    """True when the call is lexically inside an argument of a
    `device_call(...)` invocation (the guard receives it as the
    supervised body) — that IS the guarded form."""
    cur = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, ast.Call) and \
                (dotted(cur.func) or "").split(".")[-1] == \
                "device_call":
            return True
    return False


def rule_unguarded_device_dispatch(a: Analyzer) -> None:
    """Raw device dispatch outside circuit.device_call in the data-
    path modules: no watchdog, no breaker accounting, no injection
    seam, and a device exception propagates to the caller.  Route the
    call through the guard (or baseline with a justification — the
    guard's own internals legitimately dispatch raw)."""
    paths = a.config.get("device_paths", _DEVICE_DISPATCH_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolved_callee(mod, node)
            if not callee:
                continue
            hit = (callee.split(".")[-1] in _DEVICE_ENTRY_TAILS
                   or callee.endswith(_DEVICE_ENTRY_SUFFIXES))
            if hit and not _inside_device_call(mod, node):
                a.emit("unguarded-device-dispatch", mod, node,
                       f"raw device dispatch `{callee}` outside the "
                       "breaker guard: a wedged/faulting accelerator "
                       "raises here instead of degrading to the host "
                       "path — route through "
                       "ceph_tpu.common.circuit.device_call",
                       severity="warning",
                       symbol=_enclosing_qualname(mod, node),
                       scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# unplanned-mesh-dispatch
# ---------------------------------------------------------------------

# modules whose multi-chip compiles must ride the plan cache: a raw
# shard_map/pjit in the data path compiles outside plan.stats()
# (retraces invisible), binds whatever device set exists at build
# time (a dead chip's mesh is never retired), and dispatches outside
# the breaker guard (no watchdog, no sick-chip attribution)
_MESH_DISPATCH_PATHS = ("ceph_tpu/ec/", "ceph_tpu/osd/",
                        "ceph_tpu/parallel/")
_MESH_ENTRY_TAILS = {"shard_map", "pjit"}


def _inside_tracked_jit(mod, node: ast.AST) -> bool:
    """True when the call is lexically inside an argument of a
    `tracked_jit(...)` invocation — the compile lands in the plan
    cache's retrace counters, that IS the planned form."""
    cur = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, ast.Call) and \
                (dotted(cur.func) or "").split(".")[-1] == \
                "tracked_jit":
            return True
    return False


def rule_unplanned_mesh_dispatch(a: Analyzer) -> None:
    """Raw shard_map/pjit in ec/, osd/, parallel/ bypassing the plan
    cache and the breaker guard: route the compiled callable through
    plan.tracked_jit (or a plan kind keyed on the mesh signature, so
    a shrunken healthy set retires the stale executable), and the
    dispatch through circuit.device_call.  The striped.py internals
    that legitimately sit UNDER the plan builders are baselined with
    justifications."""
    paths = a.config.get("mesh_paths", _MESH_DISPATCH_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolved_callee(mod, node)
            if not callee or \
                    callee.split(".")[-1] not in _MESH_ENTRY_TAILS:
                continue
            if _inside_tracked_jit(mod, node) or \
                    _inside_device_call(mod, node):
                continue
            a.emit("unplanned-mesh-dispatch", mod, node,
                   f"raw mesh compile `{callee}` outside the plan "
                   "cache: the XLA trace is invisible to "
                   "plan.stats(), the executable binds a device set "
                   "no health shrink can retire, and the dispatch "
                   "skips the breaker guard — wrap with "
                   "ceph_tpu.ec.plan.tracked_jit (or a mesh plan "
                   "kind) and dispatch via circuit.device_call",
                   severity="warning",
                   symbol=_enclosing_qualname(mod, node),
                   scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# unplanned-compute-dispatch
# ---------------------------------------------------------------------

# modules whose coded-compute kernel evaluations must ride the plan
# cache: `compute.kernels.make_device_eval` builds the one traced
# kernel body, and a raw invocation compiles outside plan.stats()
# (retraces invisible) and dispatches outside the breaker guard (no
# watchdog, no host fallback — a wedged accelerator stalls the scan
# instead of degrading it)
_COMPUTE_DISPATCH_PATHS = ("ceph_tpu/compute/", "ceph_tpu/osd/")
_COMPUTE_ENTRY_TAILS = {"device_eval", "make_device_eval"}


def rule_unplanned_compute_dispatch(a: Analyzer) -> None:
    """Raw compute-kernel device invocation in compute//osd/ outside
    the plan cache / breaker guard: route wave evaluations through
    ceph_tpu.ec.plan.compute_eval (the `compute` plan kind —
    tracked_jit + quarantine + the `compute` breaker family) or wrap
    the dispatch in circuit.device_call.  The bit-exact numpy twin
    (`host_eval`) is the legitimate raw path."""
    paths = a.config.get("compute_paths", _COMPUTE_DISPATCH_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolved_callee(mod, node) or \
                dotted(node.func) or ""
            if callee.split(".")[-1] not in _COMPUTE_ENTRY_TAILS:
                continue
            if _inside_tracked_jit(mod, node) or \
                    _inside_device_call(mod, node):
                continue
            a.emit("unplanned-compute-dispatch", mod, node,
                   f"raw compute-kernel dispatch `{callee}` outside "
                   "the plan cache: the XLA trace is invisible to "
                   "plan.stats() and the dispatch skips the breaker "
                   "guard (no watchdog, no bit-exact host "
                   "degradation) — route through "
                   "ceph_tpu.ec.plan.compute_eval or wrap with "
                   "circuit.device_call",
                   severity="warning",
                   symbol=_enclosing_qualname(mod, node),
                   scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# unscheduled-bitmatrix-xor
# ---------------------------------------------------------------------

# modules whose XOR region programs must ride the schedule compiler
# (ceph_tpu/ec/xsched.py): a hand-rolled row walk pays the naive XOR
# count (no CSE), compiles nothing (no memoization), never reaches
# the native fused-tape executor (xsched.execute_native) and is
# invisible to plan.stats()["xsched"].  The OSD data path
# (ceph_tpu/osd/) is covered too — its encode/recovery folds are the
# hot small-op band that the native executor exists for.  xsched.py
# holds the kill-switch naive walk itself and plan.py the device
# lowering — the two legitimate homes; osdmap.py XORs scalar state
# flag words, not byte regions.
_XSCHED_PATHS = ("ceph_tpu/ec/", "ceph_tpu/osd/")
_XSCHED_EXEMPT = ("ec/xsched.py", "ec/plan.py", "osd/osdmap.py")
# GF-multiply callee tails: a loop that MULTIPLIES (the wide-word
# GF(2^16/32) host matmul) is field math, not a schedulable pure-XOR
# walk
_GF_MUL_TAILS = {"mul", "mul_vec", "gf_mul", "gf_mul_jax"}


def _enclosing_loops(mod, node: ast.AST) -> list:
    out = []
    cur = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
            out.append(cur)
    return out


def _loop_multiplies(loops: list) -> bool:
    for loop in loops:
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Call) and \
                    (dotted(sub.func) or "").split(".")[-1] in \
                    _GF_MUL_TAILS:
                return True
    return False


def rule_unscheduled_bitmatrix_xor(a: Analyzer) -> None:
    """Naive bitmatrix row-walk in ec/ or osd/ outside xsched/plan:
    a loop XOR-folding byte regions (`np.bitwise_xor.reduce(...)` or
    a subscripted `^=` accumulate) re-pays the naive XOR count on
    every call and never reaches the native fused tape — compile the
    matrix once (xsched.compile_matrix, memoized by sha256
    signature) and run the schedule through the execute seam
    (xsched.execute, which picks execute_native when the runtime is
    built and falls back to execute_host; or the xor_sched plan
    kind).  Pure-XOR loops only: loops that also GF-multiply
    (wide-word fields) are exempt."""
    paths = a.config.get("xsched_paths", _XSCHED_PATHS)
    exempt = a.config.get("xsched_exempt", _XSCHED_EXEMPT)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        if any(e in rel for e in exempt):
            continue
        for node in ast.walk(mod.tree):
            what = None
            if isinstance(node, ast.Call) and \
                    (dotted(node.func) or "").endswith(
                        "bitwise_xor.reduce"):
                what = "np.bitwise_xor.reduce row-fold"
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.op, ast.BitXor) and \
                    isinstance(node.target, ast.Subscript):
                what = "subscripted ^= XOR accumulation"
            if what is None:
                continue
            loops = _enclosing_loops(mod, node)
            if not loops or _loop_multiplies(loops):
                continue
            a.emit("unscheduled-bitmatrix-xor", mod, node,
                   f"{what} inside a loop: a naive row walk pays "
                   "the unoptimized XOR count on every call, "
                   "compiles nothing and bypasses the native fused "
                   "tape — compile the bit matrix once "
                   "(ceph_tpu.ec.xsched.compile_matrix, memoized by "
                   "signature) and run it through the execute seam "
                   "(xsched.execute: native single-dispatch tape "
                   "when built, execute_host fallback; or the "
                   "xor_sched plan kind)",
                   severity="warning",
                   symbol=_enclosing_qualname(mod, node),
                   scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# raw-process-group
# ---------------------------------------------------------------------

# the bootstrap seam: the ONE module allowed to join or configure the
# jax.distributed process group (it selects the CPU collectives, owns
# the host-topology map, and keeps membership agreement
# collective-safe); everywhere else a raw initialize builds a group
# the failure-domain machinery cannot see
_PROCGROUP_EXEMPT = ("parallel/multihost.py",)
_PROCGROUP_TAILS = {"initialize", "shutdown"}


def rule_raw_process_group(a: Analyzer) -> None:
    """Raw ``jax.distributed.initialize`` / process-group setup
    outside the parallel/multihost.py bootstrap seam.  The seam is
    load-bearing: it configures the CPU collectives BEFORE backend
    init, feeds the host failure-domain topology (``host:<id>``
    breakers, the plan keys' process-topology element), and keeps
    membership agreement on the coordinator KV store instead of a
    collective a dead host would wedge.  Route group setup through
    ``multihost.initialize()`` / ``bootstrap_from_env()``."""
    exempt = a.config.get("procgroup_exempt", _PROCGROUP_EXEMPT)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if any(p in rel for p in exempt):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolved_callee(mod, node) or \
                dotted(node.func) or ""
            parts = callee.split(".")
            if len(parts) >= 2 and parts[-2] == "distributed" \
                    and parts[-1] in _PROCGROUP_TAILS:
                a.emit("raw-process-group", mod, node,
                       f"raw process-group setup `{callee}` outside "
                       "the parallel/multihost.py bootstrap seam: "
                       "the group skips the collectives config, the "
                       "host-topology map, topology-aware plan keys "
                       "and collective-safe membership agreement — "
                       "call ceph_tpu.parallel.multihost.initialize"
                       "() instead",
                       severity="warning",
                       symbol=_enclosing_qualname(mod, node),
                       scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# unhedged-gather
# ---------------------------------------------------------------------

# OSD modules whose sub-read/sub-write fan-outs are judged; the hedge
# primitive itself legitimately gathers (its cancellation drain)
_GATHER_PATHS = ("ceph_tpu/osd/",)
_GATHER_EXEMPT = ("osd/hedge.py",)
# names that mark a function as fanning out shard sub-ops: the
# sub-read job maker, the request primitive, and the sub-op messages
_SUBOP_MARKERS = {"_read_candidates", "_request", "MOSDSubRead",
                  "MOSDSubWrite"}


def _scope_subop_markers(mod, root: ast.AST) -> bool:
    for node in walk_scope(root):
        if isinstance(node, ast.Name) and node.id in _SUBOP_MARKERS:
            return True
        if isinstance(node, ast.Attribute) and \
                node.attr in _SUBOP_MARKERS:
            return True
    return False


def rule_unhedged_gather(a: Analyzer) -> None:
    """Bare `asyncio.gather` over shard sub-op jobs under ceph_tpu/osd/
    outside the hedge primitive (osd/hedge.py HedgeTracker.gather):
    the gather inherits the SLOWEST peer's latency — one degraded OSD
    sets p99 for every read through it — and its tasks are neither
    ranked by the per-peer EWMAs nor cancellation-managed.  Read-side
    fan-outs route through `self.hedge.gather`; write-path and
    absence-proof gathers that MUST stay all-shard (every shard must
    ack / every source must answer) are baselined with
    justifications."""
    paths = a.config.get("gather_paths", _GATHER_PATHS)
    exempt = a.config.get("gather_exempt", _GATHER_EXEMPT)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        if any(e in rel for e in exempt):
            continue
        for fi in mod.functions.values():
            if not fi.is_async:
                continue
            if not _scope_subop_markers(mod, fi.node):
                continue
            for node in walk_scope(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                if _resolved_callee(mod, node) != "asyncio.gather":
                    continue
                a.emit("unhedged-gather", mod, node,
                       f"bare asyncio.gather over shard sub-ops in "
                       f"`{fi.qualname}` completes at the SLOWEST "
                       "peer's pace and leaves tasks unmanaged — "
                       "route read fan-outs through the hedged "
                       "first-k primitive (osd/hedge.py "
                       "HedgeTracker.gather), or baseline all-shard "
                       "write/absence gathers with a justification",
                       severity="warning", symbol=fi.qualname,
                       scope_line=fi.lineno)


# ---------------------------------------------------------------------
# span-leak
# ---------------------------------------------------------------------


def _span_finally_names(fi_node: ast.AST) -> Set[str]:
    """Names referenced anywhere in a try/finally's finalbody within
    this function: a span passed (or receiver'd) there is finished on
    every path — `self.tracer.finish(span)`, `span.finish()`, and
    helper calls like `self._finish_op_span(span, op)` all count."""
    names: Set[str] = set()
    for node in walk_scope(fi_node):
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
    return names


def rule_span_leak(a: Analyzer) -> None:
    """`<...>.tracer.start(...)` (or bare `tracer.start(...)`) whose
    span does not provably finish on every path: the span must either
    be passed straight into a `.finish(...)` call, or be bound to a
    name that a try/finally in the same function references.  A leaked
    span is invisible — it never reaches the dump_traces ring, the
    critical-path stage histograms, or the tail-exemplar retention —
    and on an exception path it silently drops the one op most worth
    explaining.  The idiomatic fix is the context-manager surface:
    `async with tracer.span(...)` / `tracing.child_span(...)`."""
    for mod in a.project.modules.values():
        for fi in mod.functions.values():
            finally_names: Optional[Set[str]] = None
            parents: Optional[Dict[ast.AST, ast.AST]] = None
            for node in walk_scope(fi.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "start"):
                    continue
                recv = node.func.value
                if not ((isinstance(recv, ast.Name)
                         and recv.id == "tracer")
                        or (isinstance(recv, ast.Attribute)
                            and recv.attr == "tracer")):
                    continue
                if parents is None:
                    parents = {c: p for p in ast.walk(fi.node)
                               for c in ast.iter_child_nodes(p)}
                # walk up: directly consumed by a .finish(...) call?
                # bound to a name?  (conditional expressions and
                # boolop fallbacks still resolve to their Assign)
                cur = node
                bound: Optional[str] = None
                safe = False
                while cur in parents:
                    up = parents[cur]
                    if isinstance(up, ast.Call) and \
                            isinstance(up.func, ast.Attribute) and \
                            up.func.attr == "finish" and \
                            cur in up.args:
                        safe = True  # t.finish(t.start(...))
                        break
                    if isinstance(up, ast.Assign) and \
                            len(up.targets) == 1 and \
                            isinstance(up.targets[0], ast.Name):
                        bound = up.targets[0].id
                        break
                    if isinstance(up, (ast.stmt, ast.ExceptHandler)):
                        break
                    cur = up
                if safe:
                    continue
                if bound is not None:
                    if finally_names is None:
                        finally_names = _span_finally_names(fi.node)
                    if bound in finally_names:
                        continue
                a.emit("span-leak", mod, node,
                       f"span started in `{fi.qualname}` is not"
                       " finished in a finally/context-manager on"
                       " every path — an exception (or early return)"
                       " leaks it out of the trace ring, the stage"
                       " histograms and the tail exemplars; use"
                       " `async with tracer.span(...)` /"
                       " `tracing.child_span(...)`, or finish the"
                       " bound span in a try/finally",
                       severity="warning",
                       symbol=fi.qualname,
                       scope_line=fi.lineno)


# ---------------------------------------------------------------------
# sync-encode-in-async
# ---------------------------------------------------------------------

# OSD daemon modules whose async bodies must route EC encodes through
# the awaited encode service (osd/encode_service.py): a direct call
# blocks the event loop for the whole dispatch AND forfeits the
# micro-batching that folds concurrent writes into one device call.
_ENCODE_PATHS = ("ceph_tpu/osd/",)
# receiver names that denote an erasure codec in this codebase (the
# heuristic keeps str.encode()/json encode noise out of the findings)
_CODEC_RECEIVERS = {"codec", "ec_impl"}
_CODEC_ENCODE_ATTRS = {"encode", "encode_chunks", "encode_batch",
                       "encode_batch_with_crc", "encode_many",
                       "encode_many_with_crc"}


def rule_sync_encode_in_async(a: Analyzer) -> None:
    """Direct `ec_util.encode*` (or `codec.encode*(...)`) inside an
    `async def` under ceph_tpu/osd/: the EC encode runs synchronously
    on the daemon's event loop instead of awaiting the batching
    encode service.  Intentional inline fallbacks (the service's own
    degraded path) are baselined with justifications."""
    paths = a.config.get("encode_paths", _ENCODE_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for fi in mod.functions.values():
            if not fi.is_async:
                continue
            for node in walk_scope(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = _resolved_callee(mod, node)
                util_encode = ".ec_util.encode" in f".{callee}"
                codec_encode = (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CODEC_ENCODE_ATTRS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in _CODEC_RECEIVERS)
                if util_encode or codec_encode:
                    what = callee if util_encode else \
                        f"{node.func.value.id}.{node.func.attr}"
                    a.emit("sync-encode-in-async", mod, node,
                           f"synchronous EC encode `{what}` in "
                           f"`async def {fi.qualname}` runs on the "
                           "event loop and bypasses the micro-"
                           "batching encode service — await "
                           "self.encode_service instead "
                           "(osd/encode_service.py)",
                           symbol=fi.qualname, scope_line=fi.lineno)


# ---------------------------------------------------------------------
# unbounded-latency-buffer
# ---------------------------------------------------------------------

# modules whose measurement loops are judged: the loadgen subsystem
# and the CLI bench tools (the paths where per-op sample buffers grow
# with offered load x duration)
_LATENCY_PATHS = ("ceph_tpu/loadgen/", "ceph_tpu/tools/")
# receiver names that denote a latency sample buffer
_LATENCY_NAME_RE = re.compile(
    r"lat|latenc|rtt|elapsed|duration|timing|sample")
# clock reads whose difference is a latency sample
_CLOCK_CALLS = {
    "time.monotonic", "time.perf_counter", "time.time",
    "time.monotonic_ns", "time.perf_counter_ns", "time.time_ns",
}


def _inside_loop(mod, node: ast.AST) -> bool:
    """True when the node sits inside a for/while of the SAME
    function scope (a nested def resets the judgment)."""
    cur = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return False
    return False


def _has_clock_call(mod, node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and \
                _resolved_callee(mod, sub) in _CLOCK_CALLS:
            return True
    return False


def rule_unbounded_latency_buffer(a: Analyzer) -> None:
    """`<buffer>.append(<per-op sample>)` inside a loadgen/bench
    loop: the list grows without bound under open-loop load (offered
    rate x duration samples, regardless of completions).  Stream the
    sample into ceph_tpu.loadgen.stats.LatencyHistogram (constant
    memory, same percentiles) or baseline a deliberately-bounded
    buffer with a justification."""
    paths = a.config.get("latency_paths", _LATENCY_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                    and node.args):
                continue
            recv = node.func.value
            recv_name = recv.id if isinstance(recv, ast.Name) else (
                recv.attr if isinstance(recv, ast.Attribute) else "")
            looks_latency = bool(
                _LATENCY_NAME_RE.search(recv_name.lower())) or \
                _has_clock_call(mod, node.args[0])
            if looks_latency and _inside_loop(mod, node):
                a.emit("unbounded-latency-buffer", mod, node,
                       f"per-op latency sample appended to "
                       f"`{recv_name or '<expr>'}` inside a bench "
                       "loop: under open-loop load this list grows "
                       "with offered rate x duration — stream into "
                       "ceph_tpu.loadgen.stats.LatencyHistogram "
                       "(bounded log buckets) instead",
                       severity="warning",
                       symbol=_enclosing_qualname(mod, node),
                       scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# unbudgeted-approx-result
# ---------------------------------------------------------------------

# modules whose approximate-combine returns must ride the error-budget
# gate (ceph_tpu/inference/fisher.py check_budget)
_APPROX_PATHS = ("ceph_tpu/inference/", "ceph_tpu/osd/inference")
# callee tails of the approximate step: solving missing shard
# contributions from fused results is what makes the output an
# ESTIMATE rather than the exact forward
_APPROX_SOLVER_TAILS = {"lstsq", "pinv", "solve"}
# callee tails / name fragments that synthesize final combined scores
_APPROX_COMBINE_TAILS = {"combine", "combine_contributions"}
_BUDGET_GATE = "check_budget"


def rule_unbudgeted_approx_result(a: Analyzer) -> None:
    """A function in the inference paths that both SOLVES missing
    shard contributions (lstsq/pinv — the approximate step) and
    synthesizes combined scores, yet returns without ever consulting
    fisher.check_budget: the result's estimated error was never
    priced against the caller's budget, so an out-of-budget
    approximation serves silently instead of falling back to the
    exact full-decode path.  Pure solver helpers (no combine) and
    exact paths (no solve) are not findings."""
    paths = a.config.get("approx_paths", _APPROX_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for fi in mod.functions.values():
            tails: Set[str] = set()
            for node in _scope_nodes(fi.node):
                if isinstance(node, ast.Call):
                    callee = _resolved_callee(mod, node) or \
                        dotted(node.func) or ""
                    tails.add(callee.split(".")[-1])
            if not tails & _APPROX_SOLVER_TAILS:
                continue
            combines = bool(tails & _APPROX_COMBINE_TAILS) or \
                "combine" in fi.node.name.lower() or \
                "approx" in fi.node.name.lower()
            if not combines or _BUDGET_GATE in tails:
                continue
            for node in _scope_nodes(fi.node):
                if not isinstance(node, ast.Return) or \
                        node.value is None or \
                        (isinstance(node.value, ast.Constant)
                         and node.value.value is None):
                    continue
                a.emit("unbudgeted-approx-result", mod, node,
                       f"`{fi.qualname}` returns an approximate "
                       "combine (least-squares solve of missing "
                       "shard contributions) without consulting "
                       "ceph_tpu.inference.fisher.check_budget: the "
                       "estimated error was never priced against "
                       "the caller's budget — gate the return on "
                       "check_budget(est, budget) or fall back to "
                       "the exact full-decode path",
                       severity="warning", symbol=fi.qualname,
                       scope_line=fi.lineno)


# ---------------------------------------------------------------------
# lock-no-await
# ---------------------------------------------------------------------

def _class_lock_attrs(project) -> Dict[str, Set[str]]:
    """class name -> asyncio-lock attrs it assigns, across modules."""
    out: Dict[str, Set[str]] = {}
    for mod in project.modules.values():
        for cls, attrs in mod.lock_attrs.items():
            out.setdefault(cls, set()).update(attrs)
    return out


def _is_lock_attr(mod, node: ast.AST, attr: str,
                  by_class: Dict[str, Set[str]]) -> bool:
    """True when `self.<attr>` resolves to an asyncio lock of the
    ENCLOSING class.  Name-keyed project-wide matching would turn a
    same-named threading.Lock in an unrelated class into a finding, so
    only `self.` accesses bindable to their class are judged."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        cur = mod.parents.get(cur)
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for fi in mod.functions.values():
                if fi.node is cur:
                    return bool(fi.parent_class) and \
                        attr in by_class.get(fi.parent_class, ())
            return False
    return False


def rule_lock_no_await(a: Analyzer) -> None:
    by_class = _class_lock_attrs(a.project)
    for mod in a.project.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire":
                base = node.func.value
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self" and \
                        _is_lock_attr(mod, node, base.attr, by_class) \
                        and not isinstance(
                            mod.parents.get(node), ast.Await):
                    sym = _enclosing_qualname(mod, node)
                    a.emit("lock-no-await", mod, node,
                           f"asyncio.Lock `{base.attr}`.acquire() "
                           "without await: returns a coroutine, the "
                           "lock is never taken",
                           symbol=sym,
                           scope_line=_scope_line(mod, node))
            elif isinstance(node, ast.With):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Attribute) and \
                            isinstance(expr.value, ast.Name) and \
                            expr.value.id == "self" and \
                            _is_lock_attr(mod, node, expr.attr,
                                          by_class):
                        sym = _enclosing_qualname(mod, node)
                        a.emit("lock-no-await", mod, node,
                               f"sync `with` on asyncio.Lock "
                               f"`{expr.attr}`: needs `async with`",
                               symbol=sym,
                               scope_line=_scope_line(mod, node))


# ---------------------------------------------------------------------
# commit-before-durability
# ---------------------------------------------------------------------

# store modules whose commit callbacks are judged: firing `on_commit`
# before the durability point (block fsync / sync KV batch) acks a
# write a power cut can still lose — the one failure QoS, breakers and
# hedging cannot paper over
_DURABILITY_PATHS = ("ceph_tpu/os/",)
# calls that establish durability for everything before them
_DURABILITY_FSYNCS = {"os.fsync", "os.fdatasync"}
_DURABILITY_ATTRS = {"fsync", "fdatasync", "submit_transaction_sync",
                     "_block_sync"}


def _is_durability_call(mod, node: ast.Call) -> bool:
    if _resolved_callee(mod, node) in _DURABILITY_FSYNCS:
        return True
    return isinstance(node.func, ast.Attribute) and \
        node.func.attr in _DURABILITY_ATTRS


def rule_commit_before_durability(a: Analyzer) -> None:
    """`on_commit`/ack callbacks reachable before the store's
    durability point in ceph_tpu/os/: a `for cb in txn.on_commit:
    cb()` loop with no fsync / `submit_transaction_sync` /
    `_block_sync` lexically ahead of it acks a transaction that a
    power cut can still erase.  The MemStore no-durability path is
    intentional and baselined with a justification."""
    paths = a.config.get("durability_paths", _DURABILITY_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if not any(p in rel for p in paths):
            continue
        for fi in mod.functions.values():
            durable_lines = [
                node.lineno for node in walk_scope(fi.node)
                if isinstance(node, ast.Call)
                and _is_durability_call(mod, node)]
            for node in walk_scope(fi.node):
                if not isinstance(node, ast.For):
                    continue
                # `for cb in <expr>.on_commit:` (incl. list(...) wraps)
                iter_attrs = {sub.attr for sub in ast.walk(node.iter)
                              if isinstance(sub, ast.Attribute)}
                if "on_commit" not in iter_attrs or \
                        not isinstance(node.target, ast.Name):
                    continue
                cb = node.target.id
                for sub in ast.walk(node):
                    if not (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Name)
                            and sub.func.id == cb):
                        continue
                    if not any(dl < sub.lineno
                               for dl in durable_lines):
                        a.emit(
                            "commit-before-durability", mod, sub,
                            f"`{fi.qualname}` fires on_commit with no"
                            " durability point (fsync /"
                            " submit_transaction_sync / _block_sync)"
                            " ahead of it — the acked transaction can"
                            " vanish on power loss; commit the KV"
                            " batch sync (or fsync the data) before"
                            " acking, or baseline an intentional"
                            " no-durability store with a"
                            " justification",
                            severity="error", symbol=fi.qualname,
                            scope_line=fi.lineno)



# ---------------------------------------------------------------------
# unregistered-kill-switch
# ---------------------------------------------------------------------

# the one module allowed to touch os.environ with CEPH_TPU_ literals:
# the kill-switch registry itself
_KILL_SWITCH_REGISTRY_PATHS = ("common/flags.py",)
# environ accessors whose literal first argument is a flag read/write
_ENVIRON_METHODS = {"get", "getenv", "setdefault", "pop"}


def _mentions_environ(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "environ":
            return True
        if isinstance(sub, ast.Name) and sub.id == "environ":
            return True
    return False


def _kill_switch_key(node: ast.AST) -> Optional[str]:
    """The CEPH_TPU_* literal this node reads/writes straight off the
    process environment, or None."""

    def lit(e):
        if isinstance(e, ast.Constant) and isinstance(e.value, str) \
                and e.value.startswith("CEPH_TPU_"):
            return e.value
        return None

    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and node.args:
        # os.environ.get/setdefault/pop("CEPH_TPU_X"), os.getenv(...)
        if node.func.attr == "getenv" or (
                node.func.attr in _ENVIRON_METHODS
                and _mentions_environ(node.func.value)):
            return lit(node.args[0])
    if isinstance(node, ast.Subscript) and \
            _mentions_environ(node.value):
        # os.environ["CEPH_TPU_X"] — read or assignment
        return lit(node.slice)
    if isinstance(node, ast.Compare) and \
            isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
            _mentions_environ(node.comparators[0]):
        # "CEPH_TPU_X" in os.environ
        return lit(node.left)
    return None


def rule_unregistered_kill_switch(a: Analyzer) -> None:
    """Raw ``os.environ`` access with a ``CEPH_TPU_*`` literal outside
    ``common/flags.py``: the switch is invisible to the registry — no
    declared default/scope, no live-flip hook, no audit trail for the
    chaos engine's kill-switch hazard — and its per-site default
    string can drift.  Route reads through ``flags.get`` /
    ``flags.enabled`` / ``flags.flag_float`` / ``flags.flag_int`` and
    writes through ``flags.set_flag`` / ``flags.clear`` /
    ``flags.setdefault``, registering the flag in the table."""
    exempt = a.config.get("kill_switch_registry_paths",
                          _KILL_SWITCH_REGISTRY_PATHS)
    for mod in a.project.modules.values():
        rel = mod.relpath.replace("\\", "/")
        if any(p in rel for p in exempt):
            continue
        for node in ast.walk(mod.tree):
            key = _kill_switch_key(node)
            if key is None:
                continue
            a.emit(
                "unregistered-kill-switch", mod, node,
                f"raw os.environ access of `{key}` bypasses the "
                "kill-switch registry (ceph_tpu/common/flags.py): "
                "no declared default/scope, no live-flip hook, no "
                "audit for chaos kill-switch flips — use "
                "flags.get/enabled/flag_float/flag_int (reads) or "
                "flags.set_flag/clear/setdefault (writes) and "
                "register the flag",
                severity="error",
                symbol=_enclosing_qualname(mod, node),
                scope_line=_scope_line(mod, node))


def default_rules() -> Dict[str, object]:
    # lock-order lives in lockgraph.py (it needs the whole-project
    # graph) and the interprocedural async rules in rules_async.py
    # (they need the callgraph.py layer); imported here to keep one
    # registry.  unused-suppression MUST run last: it audits the
    # suppression-hit ledger every earlier rule's emit() fills.
    from ceph_tpu.analysis.lockgraph import rule_lock_order
    from ceph_tpu.analysis.rules_async import (
        rule_await_atomicity, rule_cancellation_unsafe_acquire,
        rule_hot_path_copy, rule_transitive_blocking_call,
        rule_unused_suppression,
    )
    from ceph_tpu.analysis.rules_spmd import (
        rule_collective_order, rule_divergent_collective,
        rule_topology_stale_state, rule_unguarded_collective_timeout,
    )
    return {
        "trace-side-effect": rule_trace_side_effect,
        "trace-host-sync": rule_trace_host_sync,
        "uint8-overflow": rule_uint8_overflow,
        "trace-static-hazard": rule_trace_static_hazard,
        "trace-numpy": rule_trace_numpy,
        "jit-bypass-plan": rule_jit_bypass_plan,
        "unguarded-device-dispatch": rule_unguarded_device_dispatch,
        "unplanned-mesh-dispatch": rule_unplanned_mesh_dispatch,
        "unplanned-compute-dispatch": rule_unplanned_compute_dispatch,
        "unscheduled-bitmatrix-xor": rule_unscheduled_bitmatrix_xor,
        "raw-process-group": rule_raw_process_group,
        "unhedged-gather": rule_unhedged_gather,
        "span-leak": rule_span_leak,
        "unbounded-latency-buffer": rule_unbounded_latency_buffer,
        "unbudgeted-approx-result": rule_unbudgeted_approx_result,
        "commit-before-durability": rule_commit_before_durability,
        "unregistered-kill-switch": rule_unregistered_kill_switch,
        "async-blocking": rule_async_blocking,
        "sync-encode-in-async": rule_sync_encode_in_async,
        "lock-order": rule_lock_order,
        "lock-no-await": rule_lock_no_await,
        "await-atomicity": rule_await_atomicity,
        "cancellation-unsafe-acquire": rule_cancellation_unsafe_acquire,
        "transitive-blocking-call": rule_transitive_blocking_call,
        "hot-path-copy": rule_hot_path_copy,
        "divergent-collective": rule_divergent_collective,
        "collective-order": rule_collective_order,
        "unguarded-collective-timeout":
            rule_unguarded_collective_timeout,
        "topology-stale-state": rule_topology_stale_state,
        "unused-suppression": rule_unused_suppression,
    }

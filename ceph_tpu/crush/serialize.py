"""CrushMap <-> JSON container.

The reference's compiled crushmap is its C wire encoding
(crush/CrushWrapper encode/decode); this framework's compiled container
is JSON with the same information content: tunables, devices (+classes),
types, buckets, rules, choose_args, and the class shadow-bucket table.
The text format (ceph_tpu.crush.compiler) is the interchange surface with
reference tooling.
"""

from __future__ import annotations

from typing import Any, Dict

from ceph_tpu.crush.map import Bucket, ChooseArg, CrushMap, Rule, RuleStep

TUNABLE_FIELDS = (
    "choose_local_tries", "choose_local_fallback_tries",
    "choose_total_tries", "chooseleaf_descend_once", "chooseleaf_vary_r",
    "chooseleaf_stable", "straw_calc_version", "allowed_bucket_algs",
)


def to_json(cmap: CrushMap) -> Dict[str, Any]:
    return {
        "tunables": {name: getattr(cmap, name) for name in TUNABLE_FIELDS},
        "devices": [
            {"id": dev_id, "name": cmap.device_names[dev_id],
             **({"class": cmap.device_classes[dev_id]}
                if dev_id in cmap.device_classes else {})}
            for dev_id in sorted(cmap.device_names)],
        "max_devices": cmap.max_devices,
        "types": {str(tid): name for tid, name in cmap.types.items()},
        "buckets": [
            {"id": b.id, "name": cmap.bucket_names[b.id], "type": b.type,
             "alg": b.alg, "hash": b.hash,
             "items": list(b.items), "weights": list(b.weights)}
            for b in cmap.buckets.values()],
        "rules": [
            {"name": r.name, "type": r.rule_type, "min_size": r.min_size,
             "max_size": r.max_size,
             "steps": [[s.op, s.arg1, s.arg2] for s in r.steps]}
            for r in cmap.rules],
        "class_bucket": [
            {"bucket": bid, "class": cls, "shadow": sid}
            for (bid, cls), sid in sorted(cmap.class_bucket.items())],
        "choose_args": {
            str(bid): {"weight_set": ca.weight_set, "ids": ca.ids}
            for bid, ca in cmap.choose_args.items()},
        "choose_args_maps": {
            name: {str(bid): {"weight_set": ca.weight_set, "ids": ca.ids}
                   for bid, ca in args.items()}
            for name, args in cmap.choose_args_maps.items()},
    }


def from_json(data: Dict[str, Any]) -> CrushMap:
    cmap = CrushMap()
    for name, val in data.get("tunables", {}).items():
        if name in TUNABLE_FIELDS:
            setattr(cmap, name, int(val))
    cmap.types = {int(tid): name
                  for tid, name in data.get("types", {}).items()}
    for dev in data.get("devices", []):
        cmap.add_device(int(dev["id"]), dev["name"],
                        device_class=dev.get("class", ""))
    cmap.max_devices = max(cmap.max_devices,
                           int(data.get("max_devices", 0)))
    for bj in data.get("buckets", []):
        b = Bucket(id=int(bj["id"]), type=int(bj["type"]),
                   alg=int(bj["alg"]), hash=int(bj["hash"]),
                   items=[int(i) for i in bj["items"]],
                   weights=[int(w) for w in bj["weights"]])
        cmap.buckets[b.id] = b
        cmap.bucket_names[b.id] = bj["name"]
    for rj in data.get("rules", []):
        cmap.rules.append(Rule(
            rj["name"],
            [RuleStep(*[int(v) for v in s]) for s in rj["steps"]],
            rule_type=int(rj["type"]), min_size=int(rj["min_size"]),
            max_size=int(rj["max_size"])))
    for entry in data.get("class_bucket", []):
        cmap.class_bucket[(int(entry["bucket"]), entry["class"])] = int(
            entry["shadow"])
    for bid, ca in data.get("choose_args", {}).items():
        cmap.choose_args[int(bid)] = ChooseArg(
            weight_set=ca.get("weight_set"), ids=ca.get("ids"))
    for name, args in data.get("choose_args_maps", {}).items():
        cmap.choose_args_maps[name] = {
            int(bid): ChooseArg(weight_set=ca.get("weight_set"),
                                ids=ca.get("ids"))
            for bid, ca in args.items()}
    return cmap

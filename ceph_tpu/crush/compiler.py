"""CRUSH map text compiler / decompiler.

Reference parity: CrushCompiler
(/root/reference/src/crush/CrushCompiler.cc) — the `crushtool -c/-d` text
format: tunable lines, `device N osd.N [class c]`, `type N name`, bucket
blocks (id/alg/hash/item lines), rule blocks (take/choose/chooseleaf/
emit/set_* steps, `take <root> class <c>` resolved through the per-class
shadow hierarchy).

Deviation: the reference's *binary* crushmap is its C wire encoding; this
framework's compiled container is JSON (ceph_tpu.crush.serialize) — the
text format is the interchange surface.
"""

from __future__ import annotations

import re
import shlex
from typing import Dict, List, Optional

from ceph_tpu.crush.map import (
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
    CrushMap,
    Rule,
    RuleStep,
)

ALG_NAMES = {CRUSH_BUCKET_UNIFORM: "uniform", CRUSH_BUCKET_LIST: "list",
             CRUSH_BUCKET_TREE: "tree", CRUSH_BUCKET_STRAW: "straw",
             CRUSH_BUCKET_STRAW2: "straw2"}
ALG_IDS = {v: k for k, v in ALG_NAMES.items()}

TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
            "choose_total_tries", "chooseleaf_descend_once",
            "chooseleaf_vary_r", "chooseleaf_stable", "straw_calc_version",
            "allowed_bucket_algs")

_SET_STEPS = {
    "set_choose_tries": CRUSH_RULE_SET_CHOOSE_TRIES,
    "set_chooseleaf_tries": CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    "set_choose_local_tries": CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries":
        CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_vary_r": CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": CRUSH_RULE_SET_CHOOSELEAF_STABLE,
}
_SET_NAMES = {v: k for k, v in _SET_STEPS.items()}

RULE_TYPE_NAMES = {1: "replicated", 3: "erasure"}


class CompileError(ValueError):
    pass


def compile_text(text: str) -> CrushMap:
    """Parse crushtool text format into a CrushMap."""
    cmap = CrushMap()
    cmap.types = {}
    lines: List[List[str]] = []
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if line:
            lines.append(shlex.split(line))

    i = 0
    pending_rule_ids: Dict[int, Rule] = {}
    while i < len(lines):
        tok = lines[i]
        head = tok[0]
        if head == "tunable":
            if len(tok) != 3 or tok[1] not in TUNABLES:
                raise CompileError(f"bad tunable line: {' '.join(tok)}")
            setattr(cmap, tok[1], int(tok[2]))
            i += 1
        elif head == "device":
            # device N osd.N [class c]
            dev_id = int(tok[1])
            name = tok[2]
            cls = ""
            if len(tok) >= 5 and tok[3] == "class":
                cls = tok[4]
            if not name.startswith("device"):  # "deviceN" = deleted marker
                cmap.add_device(dev_id, name, device_class=cls)
            else:
                cmap.max_devices = max(cmap.max_devices, dev_id + 1)
            i += 1
        elif head == "type":
            cmap.types[int(tok[1])] = tok[2]
            i += 1
        elif head == "rule":
            i = _parse_rule(cmap, lines, i)
        elif head == "choose_args":
            i = _parse_choose_args(cmap, lines, i)
        elif len(tok) >= 2 and tok[-1] == "{":
            i = _parse_bucket(cmap, lines, i)
        else:
            raise CompileError(f"unparsable line: {' '.join(tok)}")
    return cmap


def _parse_bucket(cmap: CrushMap, lines: List[List[str]], i: int) -> int:
    head = lines[i]
    type_name, name = head[0], head[1]
    try:
        type_id = cmap.type_id(type_name)
    except KeyError:
        raise CompileError(f"unknown bucket type {type_name!r}")
    i += 1
    bucket_id: Optional[int] = None
    class_ids: Dict[str, int] = {}
    alg = CRUSH_BUCKET_STRAW2
    hash_ = 0
    items: List[tuple] = []
    while i < len(lines) and lines[i][0] != "}":
        tok = lines[i]
        if tok[0] == "id":
            if len(tok) >= 4 and tok[2] == "class":
                class_ids[tok[3]] = int(tok[1])
            else:
                bucket_id = int(tok[1])
        elif tok[0] == "alg":
            if tok[1] not in ALG_IDS:
                raise CompileError(f"unknown bucket alg {tok[1]!r}")
            alg = ALG_IDS[tok[1]]
        elif tok[0] == "hash":
            hash_ = int(tok[1])
        elif tok[0] == "weight":
            pass  # informational
        elif tok[0] == "item":
            item_name = tok[1]
            weight = 0x10000
            for j in range(2, len(tok) - 1, 2):
                if tok[j] == "weight":
                    weight = int(round(float(tok[j + 1]) * 0x10000))
            items.append((item_name, weight))
        else:
            raise CompileError(
                f"unparsable bucket line: {' '.join(tok)}")
        i += 1
    if i >= len(lines):
        raise CompileError(f"unterminated bucket {name!r}")
    b = cmap.add_bucket(bucket_id, type_id, name, alg=alg)
    b.hash = hash_
    for item_name, weight in items:
        b.add_item(cmap.name_to_item(item_name), weight)
    # class ids pre-declare shadow bucket ids; recorded for decompile parity
    for cls, cid in class_ids.items():
        cmap.class_bucket[(b.id, cls)] = cmap.class_bucket.get(
            (b.id, cls), cid)
    return i + 1


def _parse_choose_args(cmap: CrushMap, lines: List[List[str]], i: int) -> int:
    """`choose_args <name> { { bucket_id -N weight_set [...] ids [...] } }`
    (CrushCompiler::parse_choose_args / decompile_choose_args layout)."""
    from ceph_tpu.crush.map import ChooseArg

    name = lines[i][1]
    i += 1
    args: Dict[int, "ChooseArg"] = {}
    while i < len(lines) and lines[i][0] != "}":
        if lines[i] != ["{"]:
            raise CompileError(
                f"expected '{{' in choose_args, got {' '.join(lines[i])}")
        i += 1
        bucket_id: Optional[int] = None
        weight_set: Optional[List[List[int]]] = None
        ids: Optional[List[int]] = None
        while i < len(lines) and lines[i][0] != "}":
            tok = lines[i]
            if tok[0] == "bucket_id":
                bucket_id = int(tok[1])
            elif tok[0] == "weight_set":
                weight_set = []
                i += 1
                while i < len(lines) and lines[i][0] != "]":
                    row = lines[i]
                    if row[0] != "[" or row[-1] != "]":
                        raise CompileError(
                            f"bad weight_set row: {' '.join(row)}")
                    weight_set.append([
                        int(round(float(w) * 0x10000)) for w in row[1:-1]])
                    i += 1
                if i >= len(lines):
                    raise CompileError("unterminated weight_set")
            elif tok[0] == "ids":
                if tok[1] != "[" or tok[-1] != "]":
                    raise CompileError(f"bad ids line: {' '.join(tok)}")
                ids = [int(v) for v in tok[2:-1]]
            else:
                raise CompileError(
                    f"unparsable choose_args line: {' '.join(tok)}")
            i += 1
        if i >= len(lines):
            raise CompileError("unterminated choose_args entry")
        i += 1  # closing } of the entry
        if bucket_id is None:
            raise CompileError("choose_args entry without bucket_id")
        args[bucket_id] = ChooseArg(weight_set=weight_set, ids=ids)
    if i >= len(lines):
        raise CompileError(f"unterminated choose_args {name!r}")
    cmap.choose_args_maps[name] = args
    if not cmap.choose_args:  # first/only map also drives the mapper
        cmap.choose_args = args
    return i + 1


def _parse_rule(cmap: CrushMap, lines: List[List[str]], i: int) -> int:
    head = lines[i]
    name = head[1] if len(head) > 2 else head[1].rstrip("{")
    i += 1
    rule_type = 1
    min_size, max_size = 1, 10
    steps: List[RuleStep] = []
    while i < len(lines) and lines[i][0] != "}":
        tok = lines[i]
        if tok[0] == "id" or tok[0] == "ruleset":
            pass  # rule position is its id in this model
        elif tok[0] == "type":
            names = {v: k for k, v in RULE_TYPE_NAMES.items()}
            if tok[1] not in names:
                raise CompileError(f"unknown rule type {tok[1]!r}")
            rule_type = names[tok[1]]
        elif tok[0] == "min_size":
            min_size = int(tok[1])
        elif tok[0] == "max_size":
            max_size = int(tok[1])
        elif tok[0] == "step":
            steps.append(_parse_step(cmap, tok[1:]))
        else:
            raise CompileError(f"unparsable rule line: {' '.join(tok)}")
        i += 1
    if i >= len(lines):
        raise CompileError(f"unterminated rule {name!r}")
    cmap.add_rule(Rule(name, steps, rule_type=rule_type,
                       min_size=min_size, max_size=max_size))
    return i + 1


def _parse_step(cmap: CrushMap, tok: List[str]) -> RuleStep:
    op = tok[0]
    if op == "take":
        item = cmap.name_to_item(tok[1])
        if len(tok) >= 4 and tok[2] == "class":
            item = cmap.class_shadow_id(item, tok[3])
        return RuleStep(CRUSH_RULE_TAKE, item)
    if op == "emit":
        return RuleStep(CRUSH_RULE_EMIT)
    if op in _SET_STEPS:
        return RuleStep(_SET_STEPS[op], int(tok[1]))
    if op in ("choose", "chooseleaf"):
        mode = tok[1]  # firstn | indep
        num = int(tok[2])
        if len(tok) < 5 or tok[3] != "type":
            raise CompileError(f"bad step: step {' '.join(tok)}")
        type_id = cmap.type_id(tok[4])
        ops = {("choose", "firstn"): CRUSH_RULE_CHOOSE_FIRSTN,
               ("choose", "indep"): CRUSH_RULE_CHOOSE_INDEP,
               ("chooseleaf", "firstn"): CRUSH_RULE_CHOOSELEAF_FIRSTN,
               ("chooseleaf", "indep"): CRUSH_RULE_CHOOSELEAF_INDEP}
        if (op, mode) not in ops:
            raise CompileError(f"bad choose mode {mode!r}")
        return RuleStep(ops[(op, mode)], num, type_id)
    raise CompileError(f"unknown step op {op!r}")


def decompile(cmap: CrushMap) -> str:
    """Emit crushtool text format (CrushCompiler::decompile layout)."""
    out: List[str] = ["# begin crush map"]
    for tun in TUNABLES:
        default = {"choose_total_tries": 50, "chooseleaf_descend_once": 1,
                   "chooseleaf_vary_r": 1, "chooseleaf_stable": 1,
                   "straw_calc_version": 1}.get(tun)
        val = getattr(cmap, tun)
        if tun == "allowed_bucket_algs":
            continue  # emitted only when non-default in the reference
        if val != default or tun in ("choose_local_tries",
                                     "choose_local_fallback_tries",
                                     "choose_total_tries"):
            out.append(f"tunable {tun} {val}")

    out.append("\n# devices")
    for dev_id in range(cmap.max_devices):
        name = cmap.device_names.get(dev_id, f"device{dev_id}")
        cls = cmap.device_classes.get(dev_id)
        line = f"device {dev_id} {name}"
        if cls:
            line += f" class {cls}"
        out.append(line)

    out.append("\n# types")
    for tid in sorted(cmap.types):
        out.append(f"type {tid} {cmap.types[tid]}")

    out.append("\n# buckets")
    shadow_ids = set(cmap.class_bucket.values())
    # emit children before parents (reference emits leaves-first)
    emitted = set()

    def emit_bucket(bid: int) -> None:
        if bid in emitted or bid in shadow_ids:
            return
        b = cmap.buckets[bid]
        for item in b.items:
            if item < 0:
                emit_bucket(item)
        emitted.add(bid)
        type_name = cmap.types.get(b.type, str(b.type))
        out.append(f"{type_name} {cmap.bucket_names[bid]} {{")
        out.append(f"\tid {bid}\t\t# do not change unnecessarily")
        for (obid, cls), sid in sorted(cmap.class_bucket.items()):
            if obid == bid:
                out.append(f"\tid {sid} class {cls}"
                           "\t\t# do not change unnecessarily")
        out.append(f"\t# weight {b.weight / 0x10000:.5f}")
        out.append(f"\talg {ALG_NAMES[b.alg]}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for item, weight in zip(b.items, b.weights):
            iname = (cmap.device_names.get(item, f"osd.{item}")
                     if item >= 0 else cmap.bucket_names[item])
            out.append(f"\titem {iname} weight {weight / 0x10000:.5f}")
        out.append("}")

    for bid in sorted(cmap.buckets, reverse=True):
        emit_bucket(bid)

    out.append("\n# rules")
    shadow_to_class = {sid: (obid, cls)
                       for (obid, cls), sid in cmap.class_bucket.items()}
    for ruleno, rule in enumerate(cmap.rules):
        out.append(f"rule {rule.name} {{")
        out.append(f"\tid {ruleno}")
        out.append(f"\ttype {RULE_TYPE_NAMES.get(rule.rule_type, 'replicated')}")
        out.append(f"\tmin_size {rule.min_size}")
        out.append(f"\tmax_size {rule.max_size}")
        for step in rule.steps:
            if step.op == CRUSH_RULE_TAKE:
                if step.arg1 in shadow_to_class:
                    obid, cls = shadow_to_class[step.arg1]
                    out.append(f"\tstep take {cmap.bucket_names[obid]}"
                               f" class {cls}")
                else:
                    out.append(f"\tstep take {cmap.bucket_names[step.arg1]}")
            elif step.op == CRUSH_RULE_EMIT:
                out.append("\tstep emit")
            elif step.op in _SET_NAMES:
                out.append(f"\tstep {_SET_NAMES[step.op]} {step.arg1}")
            else:
                names = {CRUSH_RULE_CHOOSE_FIRSTN: ("choose", "firstn"),
                         CRUSH_RULE_CHOOSE_INDEP: ("choose", "indep"),
                         CRUSH_RULE_CHOOSELEAF_FIRSTN:
                             ("chooseleaf", "firstn"),
                         CRUSH_RULE_CHOOSELEAF_INDEP:
                             ("chooseleaf", "indep")}
                op, mode = names[step.op]
                type_name = cmap.types.get(step.arg2, str(step.arg2))
                out.append(f"\tstep {op} {mode} {step.arg1}"
                           f" type {type_name}")
        out.append("}")

    if cmap.choose_args_maps or cmap.choose_args:
        out.append("\n# choose_args")
        maps = cmap.choose_args_maps or {"0": cmap.choose_args}
        for name, args in maps.items():
            out.append(f"choose_args {name} {{")
            for bid, ca in sorted(args.items(), reverse=True):
                out.append("  {")
                out.append(f"    bucket_id {bid}")
                if ca.weight_set:
                    out.append("    weight_set [")
                    for row in ca.weight_set:
                        vals = " ".join(f"{w / 0x10000:.5f}" for w in row)
                        out.append(f"      [ {vals} ]")
                    out.append("    ]")
                if ca.ids:
                    out.append("    ids [ " +
                               " ".join(str(v) for v in ca.ids) + " ]")
                out.append("  }")
            out.append("}")

    out.append("\n# end crush map")
    return "\n".join(out) + "\n"

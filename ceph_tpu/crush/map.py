"""CRUSH map data model + builder.

Reference: the C data model in /root/reference/src/crush/crush.h (buckets,
rules, tunables) and the builder/façade in builder.c / CrushWrapper
(/root/reference/src/crush/CrushWrapper.h).  This is a clean host-side
model — the placement kernels (mapper.py exact host path, kernel.py vmapped
TPU path) both consume it.

Conventions preserved from the reference:
- devices have ids >= 0; buckets have ids < 0 (bucket b is buckets[-1-id]);
- weights are 16.16 fixed point (0x10000 == 1.0);
- rule steps are (op, arg1, arg2) triples;
- tunables default to the modern profile (choose_total_tries=50,
  chooseleaf_descend_once/vary_r/stable=1, straw_calc_version=1 — the
  "jewel" defaults in crush.h).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

# bucket algorithms (crush.h crush_algorithm)
CRUSH_BUCKET_UNIFORM = 1
CRUSH_BUCKET_LIST = 2
CRUSH_BUCKET_TREE = 3
CRUSH_BUCKET_STRAW = 4
CRUSH_BUCKET_STRAW2 = 5

CRUSH_HASH_RJENKINS1 = 0

# rule step ops (crush.h crush_opcodes)
CRUSH_RULE_NOOP = 0
CRUSH_RULE_TAKE = 1
CRUSH_RULE_CHOOSE_FIRSTN = 2
CRUSH_RULE_CHOOSE_INDEP = 3
CRUSH_RULE_EMIT = 4
CRUSH_RULE_CHOOSELEAF_FIRSTN = 6
CRUSH_RULE_CHOOSELEAF_INDEP = 7
CRUSH_RULE_SET_CHOOSE_TRIES = 8
CRUSH_RULE_SET_CHOOSELEAF_TRIES = 9
CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES = 10
CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
CRUSH_RULE_SET_CHOOSELEAF_VARY_R = 12
CRUSH_RULE_SET_CHOOSELEAF_STABLE = 13

CRUSH_ITEM_UNDEF = -0x7FFFFFFF
CRUSH_ITEM_NONE = -0x80000000


@dataclass
class Bucket:
    id: int  # < 0
    type: int  # type id (e.g. host=1, rack=3, root=10)
    alg: int = CRUSH_BUCKET_STRAW2
    hash: int = CRUSH_HASH_RJENKINS1
    items: List[int] = field(default_factory=list)
    weights: List[int] = field(default_factory=list)  # 16.16 fixed per item

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.weights)

    def add_item(self, item: int, weight: int) -> None:
        self.items.append(item)
        self.weights.append(weight)


@dataclass
class RuleStep:
    op: int
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    name: str
    steps: List[RuleStep]
    rule_type: int = 1  # pg_pool_t TYPE_REPLICATED=1 / TYPE_ERASURE=3
    min_size: int = 1
    max_size: int = 10


@dataclass
class ChooseArg:
    """Per-bucket weight_set/ids overrides (balancer; mapper.c:309-326)."""

    weight_set: Optional[List[List[int]]] = None  # positions x items
    ids: Optional[List[int]] = None


class CrushMap:
    def __init__(self) -> None:
        self.buckets: Dict[int, Bucket] = {}  # by id (< 0)
        self.rules: List[Rule] = []
        self.types: Dict[int, str] = {0: "osd", 1: "host", 2: "chassis",
                                      3: "rack", 4: "row", 5: "pdu", 6: "pod",
                                      7: "room", 8: "datacenter", 9: "zone",
                                      10: "region", 11: "root"}
        self.bucket_names: Dict[int, str] = {}
        self.device_names: Dict[int, str] = {}
        self.device_classes: Dict[int, str] = {}
        self.max_devices = 0
        self.choose_args: Dict[int, ChooseArg] = {}
        # named choose_args maps (text format: `choose_args <name> {...}`);
        # the mapper consumes one map (self.choose_args) at a time
        self.choose_args_maps: Dict[str, Dict[int, ChooseArg]] = {}
        # tunables — modern/default profile (crush.h defaults as set by
        # CrushWrapper::set_tunables_default)
        self.choose_local_tries = 0
        self.choose_local_fallback_tries = 0
        self.choose_total_tries = 50
        self.chooseleaf_descend_once = 1
        self.chooseleaf_vary_r = 1
        self.chooseleaf_stable = 1
        self.straw_calc_version = 1
        self.allowed_bucket_algs = ((1 << CRUSH_BUCKET_UNIFORM) |
                                    (1 << CRUSH_BUCKET_LIST) |
                                    (1 << CRUSH_BUCKET_STRAW) |
                                    (1 << CRUSH_BUCKET_STRAW2))
        # per-class shadow hierarchies: (bucket_id, class) -> shadow id
        # (CrushWrapper::populate_classes / class_bucket)
        self.class_bucket: Dict[tuple, int] = {}

    # -- construction -----------------------------------------------------

    def add_bucket(self, bucket_id: Optional[int], type_: int, name: str,
                   alg: int = CRUSH_BUCKET_STRAW2) -> Bucket:
        if bucket_id is None:
            bucket_id = min(self.buckets, default=0) - 1
        assert bucket_id < 0 and bucket_id not in self.buckets
        b = Bucket(id=bucket_id, type=type_, alg=alg)
        self.buckets[bucket_id] = b
        self.bucket_names[bucket_id] = name
        return b

    def add_device(self, dev_id: int, name: Optional[str] = None,
                   device_class: str = "") -> None:
        self.max_devices = max(self.max_devices, dev_id + 1)
        self.device_names[dev_id] = name or f"osd.{dev_id}"
        if device_class:
            self.device_classes[dev_id] = device_class

    def name_to_item(self, name: str) -> int:
        for bid, n in self.bucket_names.items():
            if n == name:
                return bid
        for did, n in self.device_names.items():
            if n == name:
                return did
        raise KeyError(name)

    def type_id(self, name: str) -> int:
        for tid, n in self.types.items():
            if n == name:
                return tid
        raise KeyError(name)

    def bucket(self, item_id: int) -> Bucket:
        return self.buckets[item_id]

    def populate_class_shadow(self, device_class: str) -> None:
        """Build the per-class shadow hierarchy
        (CrushWrapper::populate_classes / device_class_clone): for every
        bucket that transitively contains devices of `device_class`, a
        shadow bucket holding only those devices (and shadow children).
        `step take <root> class <c>` then resolves to the shadow root.

        A text map may pre-declare shadow ids (`id -12 class hdd` lines);
        those ids are honored when the shadow bucket is materialized."""

        def clone(bid: int) -> Optional[int]:
            key = (bid, device_class)
            declared = self.class_bucket.get(key)
            if declared is not None and declared in self.buckets:
                return declared
            orig = self.buckets[bid]
            items: List[int] = []
            weights: List[int] = []
            for item, weight in zip(orig.items, orig.weights):
                if item >= 0:
                    if self.device_classes.get(item) == device_class:
                        items.append(item)
                        weights.append(weight)
                else:
                    shadow = clone(item)
                    if shadow is not None:
                        items.append(shadow)
                        weights.append(self.buckets[shadow].weight)
            if not items:
                return None
            sb = self.add_bucket(
                declared, orig.type,
                f"{self.bucket_names[bid]}~{device_class}", alg=orig.alg)
            sb.hash = orig.hash
            for item, weight in zip(items, weights):
                sb.add_item(item, weight)
            self.class_bucket[key] = sb.id
            return sb.id

        for bid in sorted(self.buckets, reverse=True):
            if "~" not in self.bucket_names[bid]:
                clone(bid)

    def class_shadow_id(self, bucket_id: int, device_class: str) -> int:
        key = (bucket_id, device_class)
        sid = self.class_bucket.get(key)
        if sid is None or sid not in self.buckets:
            # key may hold a pre-declared id from a text map whose shadow
            # bucket hasn't been materialized yet — build the hierarchy
            self.populate_class_shadow(device_class)
            sid = self.class_bucket.get(key)
        if sid is None or sid not in self.buckets:
            raise KeyError(
                f"bucket {self.bucket_names.get(bucket_id)} has no devices"
                f" of class {device_class}")
        return sid

    def add_rule(self, rule: Rule) -> int:
        self.rules.append(rule)
        return len(self.rules) - 1

    def find_rule_by_name(self, name: str) -> int:
        for i, r in enumerate(self.rules):
            if r.name == name:
                return i
        return -1

    def add_simple_rule(self, name: str, root_name: str, failure_domain: str,
                        device_class: str = "", mode: str = "firstn",
                        pool_type: str = "replicated") -> int:
        """CrushWrapper::add_simple_rule — TAKE root / CHOOSELEAF n domain /
        EMIT."""
        if self.find_rule_by_name(name) >= 0:
            return -17
        root = self.name_to_item(root_name)
        domain_type = self.type_id(failure_domain) if failure_domain else 0
        steps = [RuleStep(CRUSH_RULE_TAKE, root)]
        choose_op = (CRUSH_RULE_CHOOSELEAF_FIRSTN if mode == "firstn"
                     else CRUSH_RULE_CHOOSELEAF_INDEP)
        if domain_type == 0:
            choose_op = (CRUSH_RULE_CHOOSE_FIRSTN if mode == "firstn"
                         else CRUSH_RULE_CHOOSE_INDEP)
        steps.append(RuleStep(choose_op, 0, domain_type))
        steps.append(RuleStep(CRUSH_RULE_EMIT))
        rule_type = 3 if pool_type == "erasure" else 1
        return self.add_rule(Rule(name, steps, rule_type=rule_type))

    # -- weights ----------------------------------------------------------

    def full_weight_vector(self) -> List[int]:
        """Per-device 16.16 in/out weights — the OSDMap weight vector fed to
        crush_do_rule (all-in by default)."""
        return [0x10000] * self.max_devices


def build_flat_cluster(num_osds: int, osds_per_host: int = 4,
                       hosts_per_rack: int = 0,
                       osd_weight: float = 1.0) -> CrushMap:
    """Convenience builder: root -> (racks ->) hosts -> osds, straw2.

    The shape CrushTester/osdmaptool exercise with --num-osds.
    """
    cm = CrushMap()
    w = int(osd_weight * 0x10000)
    num_hosts = -(-num_osds // osds_per_host)
    root = cm.add_bucket(-1, cm.type_id("root"), "default")
    rack = None
    racks = []
    if hosts_per_rack:
        num_racks = -(-num_hosts // hosts_per_rack)
        for r in range(num_racks):
            racks.append(cm.add_bucket(None, cm.type_id("rack"), f"rack{r}"))
            root.add_item(racks[-1].id, 0)
    dev = 0
    for h in range(num_hosts):
        host = cm.add_bucket(None, cm.type_id("host"), f"host{h}")
        for _ in range(osds_per_host):
            if dev >= num_osds:
                break
            cm.add_device(dev)
            host.add_item(dev, w)
            dev += 1
        if hosts_per_rack:
            rack = racks[h // hosts_per_rack]
            rack.add_item(host.id, host.weight)
        else:
            root.add_item(host.id, host.weight)
    if hosts_per_rack:
        for i, r in enumerate(racks):
            root.weights[i] = r.weight
    return cm

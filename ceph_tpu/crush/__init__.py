"""CRUSH placement: map model, exact host mapper, vmapped TPU kernel."""

from ceph_tpu.crush.map import CrushMap, Bucket, Rule  # noqa: F401
from ceph_tpu.crush.mapper import crush_do_rule  # noqa: F401

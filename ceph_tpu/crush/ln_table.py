"""Fixed-point log2 lookup tables for straw2 (crush_ln).

The reference ships precomputed tables (/root/reference/src/crush/
crush_ln_table.h) with the generating formulas in comments:

  RH_LH_tbl[2k]   = 2^48 / (1 + k/128)        (reciprocal, high part)
  RH_LH_tbl[2k+1] = 2^48 * log2(1 + k/128)    (log, high part)
  LL_tbl[j]       = 2^48 * log2(1 + j/2^15)   (log, low part)

We *generate* the tables from those formulas rather than embedding 258+256
magic numbers.  Empirically-determined rounding of the reference generator
(verified entry-by-entry against the shipped header):

  - RH entries round *up* (ceil);
  - LH and LL entries round down (floor);
  - LH[k=128] is clamped to 0xffff00000000 (never indexed by crush_ln —
    x>>8 <= 255 — but matched for table equality);
  - LL entries 2..254 carry a constant +0x147700000 bias over the exact
    floor — an artifact of the original generator that is part of the
    de-facto wire behavior (the Linux kernel ships the same values), so we
    reproduce it as a protocol constant.

Exactness here is what makes `placement diff = 0` against reference
crushtool possible (BASELINE.md config #4).
"""

from __future__ import annotations

import math
from fractions import Fraction

import numpy as np

_LL_INTERIOR_BIAS = 0x147700000


def _ceil_frac(f: Fraction) -> int:
    return -((-f.numerator) // f.denominator)


def build_rh_lh_table() -> np.ndarray:
    out = np.zeros(258, dtype=np.int64)
    for k in range(129):
        out[2 * k] = _ceil_frac(Fraction(2**48 * 128, 128 + k))
        if k == 0:
            lh = 0
        elif k == 128:
            lh = 0xFFFF00000000
        else:
            lh = math.floor(Fraction(2**48) * Fraction(math.log2(1 + k / 128.0)))
        out[2 * k + 1] = lh
    return out


def build_ll_table() -> np.ndarray:
    out = np.zeros(256, dtype=np.int64)
    for j in range(256):
        v = math.floor(Fraction(2**48) * Fraction(math.log2(1 + j / 2**15)))
        if 2 <= j <= 254:
            v += _LL_INTERIOR_BIAS
        out[j] = v
    return out


RH_LH_TBL = build_rh_lh_table()
LL_TBL = build_ll_table()


def crush_ln(xin: int) -> int:
    """2^44 * log2(xin + 1), the straw2 fixed-point log (mapper.c:248-290)."""
    x = (int(xin) + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        bits = 16 - (x & 0x1FFFF).bit_length()
        x <<= bits
        iexpon = 15 - bits
    index1 = (x >> 8) << 1
    rh = int(RH_LH_TBL[index1 - 256])
    lh = int(RH_LH_TBL[index1 + 1 - 256])
    xl64 = (x * rh) >> 48
    result = iexpon << 44
    index2 = xl64 & 0xFF
    lh = lh + int(LL_TBL[index2])
    result += lh >> 4
    return result


def straw2_draws(u16: np.ndarray, weights: np.ndarray) -> np.ndarray:
    """Vectorized numpy draw: ln(u)/weight with S64_MIN for zero weights.

    u16: uint32 array of 16-bit hash values; weights: uint32 16.16 fixed.
    Mirrors generate_exponential_distribution (mapper.c:334-359).
    """
    lns = np.array([crush_ln(int(u)) for u in u16.ravel()],
                   dtype=np.int64).reshape(u16.shape)
    ln = lns - 0x1000000000000
    w = weights.astype(np.int64)
    draws = np.where(w > 0, _div64(ln, w), np.int64(-(2**63)))
    return draws


def _div64(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """C-style truncating signed 64-bit division (div64_s64)."""
    q = np.abs(a) // np.abs(b)
    return np.where((a < 0) != (b < 0), -q, q).astype(np.int64)

"""Vmapped CRUSH placement kernel (JAX) — bulk straw2 rule evaluation.

The reference computes placements one input at a time (crush_do_rule,
/root/reference/src/crush/mapper.c:900) and scales by threading
(ParallelPGMapper, /root/reference/src/osd/OSDMapMapping.h:18) or forked
batches (CrushTester.h:361).  On TPU the natural shape is data-parallel:
flatten the map into dense arrays, express one input's rule evaluation with
`lax.while_loop`/unrolled replica steps, and `vmap` over millions of inputs
in a single dispatch — hash, fixed-point log, and argmax are all int lane
ops.

Scope (the modern hot path): straw2 buckets, rules of the form
TAKE / CHOOSE(LEAF)_FIRSTN / CHOOSE(LEAF)_INDEP / SET_*_TRIES / EMIT, modern
tunables (choose_local_tries=0, local_fallback=0; descend_once, vary_r,
stable as set on the map).  Legacy bucket algs, local-retry tunables, and
chained choose steps stay on the exact host mapper (ceph_tpu.crush.mapper),
which this kernel is tested to match placement-for-placement (and the host
mapper is itself oracle-tested against the reference's compiled mapper.c).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)  # straw2 draws are int64 fixed-point

from ceph_tpu.crush import ln_table
from ceph_tpu.crush.map import (
    CRUSH_BUCKET_STRAW2, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE, CrushMap,
)
from ceph_tpu.ops import rjenkins

S64_MIN = jnp.int64(-(2**63))
UNDEF = jnp.int32(-0x7FFFFFFF)
NONE = jnp.int32(-0x80000000)


@dataclass
class DenseMap:
    """CrushMap flattened to device arrays; bucket row = -1 - bucket_id."""

    items: jnp.ndarray      # (NB, MS) int32, padded with 0
    weights: jnp.ndarray    # (NB, MS) int64 16.16, padded with 0
    sizes: jnp.ndarray      # (NB,) int32
    types: jnp.ndarray      # (NB,) int32
    dev_weight: jnp.ndarray  # (max_devices,) int64 16.16 in/out vector
    max_devices: int
    max_depth: int

    @classmethod
    def from_crush_map(cls, cmap: CrushMap,
                       weight: List[int] | None = None) -> "DenseMap":
        nb = max(-bid for bid in cmap.buckets)
        ms = max((b.size for b in cmap.buckets.values()), default=1) or 1
        items = np.zeros((nb, ms), dtype=np.int32)
        weights = np.zeros((nb, ms), dtype=np.int64)
        sizes = np.zeros(nb, dtype=np.int32)
        types = np.zeros(nb, dtype=np.int32)
        for bid, b in cmap.buckets.items():
            if b.alg != CRUSH_BUCKET_STRAW2:
                raise NotImplementedError(
                    "TPU kernel supports straw2 buckets; use the host mapper")
            row = -1 - bid
            items[row, : b.size] = b.items
            weights[row, : b.size] = b.weights
            sizes[row] = b.size
            types[row] = b.type
        depth = {}

        def bucket_depth(bid: int) -> int:
            if bid in depth:
                return depth[bid]
            b = cmap.buckets[bid]
            d = 1 + max((bucket_depth(i) for i in b.items if i < 0), default=0)
            depth[bid] = d
            return d

        max_depth = max((bucket_depth(b) for b in cmap.buckets), default=1)
        w = weight if weight is not None else cmap.full_weight_vector()
        return cls(items=jnp.asarray(items), weights=jnp.asarray(weights),
                   sizes=jnp.asarray(sizes), types=jnp.asarray(types),
                   dev_weight=jnp.asarray(np.asarray(w, dtype=np.int64)),
                   max_devices=cmap.max_devices, max_depth=max_depth)


def crush_ln_jax(u):
    """Vectorized crush_ln (int64 in/out); u in [0, 0xffff]."""
    x = u.astype(jnp.int64) + 1
    bl = 32 - jax.lax.clz(x.astype(jnp.int32)).astype(jnp.int64)
    shift = jnp.where((x & 0x18000) != 0, 0, 16 - bl)
    x = x << shift
    iexpon = 15 - shift
    index1 = (x >> 8) << 1
    rh = jnp.asarray(ln_table.RH_LH_TBL)[index1 - 256]
    lh = jnp.asarray(ln_table.RH_LH_TBL)[index1 + 1 - 256]
    xl64 = ((x.astype(jnp.uint64) * rh.astype(jnp.uint64))
            >> jnp.uint64(48)).astype(jnp.int64)
    index2 = xl64 & 0xFF
    lh = lh + jnp.asarray(ln_table.LL_TBL)[index2]
    return (iexpon << 44) + (lh >> 4)


def _straw2_row(dm: DenseMap, row, x, r):
    """Choose one item from bucket row by straw2 argmax (first max wins)."""
    ids = dm.items[row]
    ws = dm.weights[row]
    ms = ids.shape[0]
    mask = jnp.arange(ms) < dm.sizes[row]
    u = rjenkins.hash32_3(x.astype(jnp.uint32), ids.astype(jnp.uint32),
                          jnp.uint32(r & 0xFFFFFFFF), xp=jnp)
    u = (u & jnp.uint32(0xFFFF)).astype(jnp.int64)
    ln = crush_ln_jax(u) - jnp.int64(0x1000000000000)
    draws = jnp.where(mask & (ws > 0), -((-ln) // jnp.maximum(ws, 1)), S64_MIN)
    return ids[jnp.argmax(draws)]


def _descend(dm: DenseMap, start_item, x, r, target_type):
    """Walk from start_item down to an item of target_type.

    Returns (item, empty_bad, type_bad):
    - empty_bad: hit an empty bucket (the reference rejects and retries);
    - type_bad: dead-ended on a wrong type / invalid id (the reference
      gives up on the replica: skip_rep in firstn, NONE in indep).
    """

    def step(carry):
        item, empty, depth = carry
        row = jnp.clip(-1 - item, 0, dm.sizes.shape[0] - 1)
        is_empty = dm.sizes[row] == 0
        nxt = _straw2_row(dm, row, x, r)
        item2 = jnp.where(is_empty, item, nxt)
        return item2, empty | is_empty, depth + 1

    def cond(carry):
        item, empty, depth = carry
        row = jnp.clip(-1 - item, 0, dm.sizes.shape[0] - 1)
        is_bucket = item < 0
        at_type = jnp.where(is_bucket, dm.types[row] == target_type,
                            target_type == 0)
        return (~empty) & is_bucket & (~at_type) & (depth < dm.max_depth + 1)

    item, empty_bad, _ = jax.lax.while_loop(
        cond, step, (start_item, jnp.bool_(False), jnp.int32(0)))
    row = jnp.clip(-1 - item, 0, dm.sizes.shape[0] - 1)
    ok_type = jnp.where(item < 0, dm.types[row] == target_type,
                        target_type == 0)
    type_bad = (~empty_bad) & (~ok_type | (item >= dm.max_devices))
    return item, empty_bad, type_bad


def _is_out(dm: DenseMap, item, x):
    """Weight-vector rejection (mapper.c is_out)."""
    idx = jnp.clip(item, 0, dm.dev_weight.shape[0] - 1)
    w = dm.dev_weight[idx]
    u = (rjenkins.hash32_2(x.astype(jnp.uint32), item.astype(jnp.uint32),
                           xp=jnp) & jnp.uint32(0xFFFF)).astype(jnp.int64)
    out_of_range = item >= dm.dev_weight.shape[0]
    return out_of_range | (w == 0) | ((w < 0x10000) & (u >= w))


def _leaf_choose(dm: DenseMap, domain, x, rep_base, parent_r, r_stride,
                 leaf_tries, out2, collide_limit):
    """The chooseleaf recursion: pick one device under `domain`.

    firstn: r' = rep_base + parent_r + ftotal' (stride 1), collisions checked
    against out2[:collide_limit] (mapper.c:573-591).
    indep:  r' = rep_base + parent_r + numrep*ftotal' (stride numrep), no
    collision check (mapper.c:785-796).
    Returns (leaf, failed).
    """

    def body(carry):
        ftotal, leaf, done = carry
        r = rep_base + parent_r + r_stride * ftotal
        cand, empty_bad, type_bad = _descend(dm, domain, x, r, jnp.int32(0))
        collide = jnp.any((jnp.arange(out2.shape[0]) < collide_limit)
                          & (out2 == cand))
        rejected = empty_bad | type_bad | collide | _is_out(dm, cand, x)
        leaf2 = jnp.where(rejected, leaf, cand)
        return ftotal + 1, leaf2, done | ~rejected

    def cond(carry):
        ftotal, _, done = carry
        return (~done) & (ftotal < leaf_tries)

    _, leaf, done = jax.lax.while_loop(
        cond, body, (jnp.int32(0), NONE, jnp.bool_(False)))
    return leaf, ~done


def _choose_firstn_jax(dm: DenseMap, root, x, numrep, target_type, tries,
                       leaf_tries, recurse_to_leaf, vary_r, stable,
                       result_max):
    out = jnp.full((result_max,), NONE, dtype=jnp.int32)
    out2 = jnp.full((result_max,), NONE, dtype=jnp.int32)
    outpos = jnp.int32(0)
    # status codes inside the retry loop: 0 trying, 1 placed, 2 skip_rep
    for rep in range(numrep):

        def body(carry, rep=rep):
            ftotal, item, leaf, status = carry
            r = jnp.int32(rep) + ftotal
            cand, empty_bad, type_bad = _descend(dm, root, x, r, target_type)
            collide = jnp.any((jnp.arange(result_max) < outpos)
                              & (out == cand))
            sub_r = jnp.where(vary_r > 0, r >> jnp.maximum(vary_r - 1, 0),
                              jnp.int32(0))
            rep_base = jnp.where(stable > 0, jnp.int32(0), outpos)
            lf, lfail = _leaf_choose(dm, cand, x, rep_base, sub_r,
                                     jnp.int32(1), leaf_tries, out2, outpos)
            leaf_reject = recurse_to_leaf & lfail
            dev_reject = (target_type == 0) & _is_out(dm, cand, x)
            reject = empty_bad | collide | leaf_reject | dev_reject
            placed = (~type_bad) & (~reject)
            status2 = jnp.where(type_bad, jnp.int32(2),
                                jnp.where(placed, jnp.int32(1), jnp.int32(0)))
            item2 = jnp.where(placed, cand, item)
            leaf2 = jnp.where(placed, lf, leaf)
            return ftotal + 1, item2, leaf2, status2

        def cond(carry):
            ftotal, _, _, status = carry
            return (status == 0) & (ftotal < tries)

        _, item, leaf, status = jax.lax.while_loop(
            cond, body, (jnp.int32(0), NONE, NONE, jnp.int32(0)))
        placed = status == 1
        out = out.at[outpos].set(jnp.where(placed, item, out[outpos]))
        out2 = out2.at[outpos].set(jnp.where(placed, leaf, out2[outpos]))
        outpos = outpos + placed.astype(jnp.int32)
    result = jnp.where(recurse_to_leaf, out2, out)
    return result, outpos


def _choose_indep_jax(dm: DenseMap, root, x, left0, numrep, target_type,
                      tries, leaf_tries, recurse_to_leaf, result_max):
    """left0 = clamped output count; numrep = unclamped arg for r-mixing."""
    out = jnp.full((result_max,), NONE, dtype=jnp.int32)
    out2 = jnp.full((result_max,), NONE, dtype=jnp.int32)
    out = out.at[:left0].set(UNDEF)
    out2 = out2.at[:left0].set(UNDEF)
    n = jnp.int32(numrep)

    def round_body(carry):
        ftotal, out, out2, left = carry

        def rep_step(rep, state):
            out, out2, left = state
            undef = out[rep] == UNDEF
            r = rep + n * ftotal
            cand, empty_bad, type_bad = _descend(dm, root, x, r, target_type)
            collide = jnp.any(out[:left0] == cand)
            leaf, lfail = _leaf_choose(dm, cand, x, rep, r, n, leaf_tries,
                                       out2, jnp.int32(0))
            leaf_fail = recurse_to_leaf & lfail
            dev_out = (target_type == 0) & _is_out(dm, cand, x)
            # type_bad permanently assigns NONE; other rejects leave UNDEF
            make_none = undef & type_bad
            place = undef & ~type_bad & ~empty_bad & ~collide & ~leaf_fail \
                & ~dev_out
            newval = jnp.where(place, cand,
                               jnp.where(make_none, NONE, out[rep]))
            out = out.at[rep].set(newval)
            new2 = jnp.where(place & recurse_to_leaf, leaf,
                             jnp.where(make_none, NONE, out2[rep]))
            out2 = out2.at[rep].set(new2)
            left = left - (place | make_none).astype(jnp.int32)
            return out, out2, left

        out, out2, left = jax.lax.fori_loop(0, left0, rep_step,
                                            (out, out2, left))
        return ftotal + 1, out, out2, left

    def round_cond(carry):
        ftotal, _, _, left = carry
        return (left > 0) & (ftotal < tries)

    _, out, out2, _ = jax.lax.while_loop(
        round_cond, round_body, (jnp.int32(0), out, out2, jnp.int32(left0)))
    out = jnp.where(out == UNDEF, NONE, out)
    out2 = jnp.where(out2 == UNDEF, NONE, out2)
    result = jnp.where(recurse_to_leaf, out2, out)
    return result, jnp.int32(left0)


def compile_rule(cmap: CrushMap, ruleno: int, result_max: int,
                 weight: List[int] | None = None):
    """Build a jitted bulk evaluator for one rule: xs (N,) -> (N, result_max).

    Unplaced firstn slots hold CRUSH_ITEM_NONE at the tail; indep holds NONE
    in place, mirroring crush_do_rule's output contract.
    """
    dm = DenseMap.from_crush_map(cmap, weight)
    rule = cmap.rules[ruleno]
    if cmap.choose_local_tries or cmap.choose_local_fallback_tries:
        raise NotImplementedError("legacy local tries: use the host mapper")
    n_chooses = sum(1 for s in rule.steps
                    if s.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                CRUSH_RULE_CHOOSE_INDEP,
                                CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                CRUSH_RULE_CHOOSELEAF_INDEP))
    takes = sum(1 for s in rule.steps if s.op == CRUSH_RULE_TAKE)
    if n_chooses != takes:
        raise NotImplementedError(
            "chained choose steps: use the host mapper")

    def one(x):
        x = x.astype(jnp.int32)
        choose_tries = cmap.choose_total_tries + 1
        choose_leaf_tries = 0
        w_item = None
        results = []
        emitted = 0
        for step in rule.steps:
            if step.op == CRUSH_RULE_TAKE:
                w_item = jnp.int32(step.arg1)
            elif step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
                if step.arg1 > 0:
                    choose_tries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
                if step.arg1 > 0:
                    choose_leaf_tries = step.arg1
            elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                             CRUSH_RULE_CHOOSELEAF_FIRSTN,
                             CRUSH_RULE_CHOOSE_INDEP,
                             CRUSH_RULE_CHOOSELEAF_INDEP):
                assert w_item is not None, "rule has no TAKE before CHOOSE"
                firstn = step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                     CRUSH_RULE_CHOOSELEAF_FIRSTN)
                recurse = step.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                      CRUSH_RULE_CHOOSELEAF_INDEP)
                numrep = step.arg1 if step.arg1 > 0 else (
                    step.arg1 + result_max)
                if firstn:
                    if choose_leaf_tries:
                        leaf_tries = choose_leaf_tries
                    elif cmap.chooseleaf_descend_once:
                        leaf_tries = 1
                    else:
                        leaf_tries = choose_tries
                    res, cnt = _choose_firstn_jax(
                        dm, w_item, x, min(numrep, result_max - emitted),
                        jnp.int32(step.arg2), jnp.int32(choose_tries),
                        jnp.int32(leaf_tries), jnp.bool_(recurse),
                        jnp.int32(cmap.chooseleaf_vary_r),
                        jnp.int32(cmap.chooseleaf_stable), result_max)
                else:
                    leaf_tries = choose_leaf_tries if choose_leaf_tries else 1
                    res, cnt = _choose_indep_jax(
                        dm, w_item, x, min(numrep, result_max - emitted),
                        numrep, jnp.int32(step.arg2),
                        jnp.int32(choose_tries), jnp.int32(leaf_tries),
                        jnp.bool_(recurse), result_max)
                results.append((res, cnt))
                emitted += min(numrep, result_max - emitted)
                w_item = None
            elif step.op == CRUSH_RULE_EMIT:
                pass
        if not results:
            return jnp.full((result_max,), NONE, dtype=jnp.int32)
        if len(results) == 1:
            return results[0][0]
        return jnp.concatenate([r for r, _ in results])[:result_max]

    batched = jax.jit(jax.vmap(one))

    def run(xs) -> np.ndarray:
        return np.asarray(batched(jnp.asarray(xs, dtype=jnp.int32)))

    run.dense_map = dm
    run.trace_one = one  # traceable single-x evaluator for shard_map/pjit use
    run.result_max = result_max
    return run

"""Exact host implementation of the CRUSH placement kernel.

Behavioral twin of /root/reference/src/crush/mapper.c (crush_do_rule,
crush_choose_firstn :460, crush_choose_indep :655, bucket_straw2_choose :361,
bucket_perm_choose :73, is_out :424) written in Python/numpy.  Per-bucket
draws are vectorized over items (the hash and fixed-point log are numpy int
ops), so even 10k-device buckets evaluate in a few array passes; the
fully-batched path over millions of inputs is ceph_tpu.crush.kernel (JAX).

This module is the correctness oracle: kernel.py must agree with it exactly,
and it must agree with the reference's crushtool (same hash, same ln tables,
same retry semantics).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ceph_tpu.crush import ln_table
from ceph_tpu.crush.map import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM, CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R, CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE, Bucket, ChooseArg, CrushMap,
)
from ceph_tpu.ops import rjenkins


def crush_ln_vec(u: np.ndarray) -> np.ndarray:
    """Vectorized crush_ln over uint16 inputs (mapper.c:248-290)."""
    x = u.astype(np.int64) + 1
    _, exp = np.frexp(x.astype(np.float64))  # exact bit_length for x < 2^53
    bl = exp.astype(np.int64)
    shift = np.where(x & 0x18000, 0, 16 - bl)
    x = x << shift
    iexpon = 15 - shift
    index1 = (x >> 8) << 1
    rh = ln_table.RH_LH_TBL[index1 - 256]
    lh = ln_table.RH_LH_TBL[index1 + 1 - 256]
    xl64 = ((x.astype(np.uint64) * rh.astype(np.uint64)) >> np.uint64(48)).astype(np.int64)
    index2 = xl64 & 0xFF
    lh = lh + ln_table.LL_TBL[index2]
    return (iexpon << 44) + (lh >> 4)


def _straw2_choose(bucket: Bucket, x: int, r: int,
                   arg: Optional[ChooseArg], position: int) -> int:
    """bucket_straw2_choose: argmax over ln(hash16)/weight draws."""
    weights = np.asarray(bucket.weights, dtype=np.int64)
    ids = np.asarray(bucket.items, dtype=np.int64)
    if arg is not None:
        if arg.weight_set is not None:
            pos = min(position, len(arg.weight_set) - 1)
            weights = np.asarray(arg.weight_set[pos], dtype=np.int64)
        if arg.ids is not None:
            ids = np.asarray(arg.ids, dtype=np.int64)
    u = rjenkins.hash32_3(np.uint32(x), ids.astype(np.uint32), np.uint32(r),
                          xp=np)
    u = u.astype(np.int64) & 0xFFFF
    ln = crush_ln_vec(u) - 0x1000000000000
    # div64_s64 truncates toward zero; ln <= 0 and weights > 0 so
    # -((-ln) // w) is exact truncation.
    draws = np.where(weights > 0, -((-ln) // np.maximum(weights, 1)),
                     np.int64(-(2**63)))
    high = int(np.argmax(draws))  # first max wins, like the C loop
    return bucket.items[high]


def _straw_choose(bucket: Bucket, x: int, r: int) -> int:
    """Legacy straw (bucket_straw_choose): straws precomputed as weights here
    are 16.16 — the reference precomputes scaling factors; we use the same
    draw = hash16 * straw with straws supplied in bucket.weights."""
    ids = np.asarray(bucket.items, dtype=np.uint32)
    u = rjenkins.hash32_3(np.uint32(x), ids, np.uint32(r),
                          xp=np).astype(np.uint64) & np.uint64(0xFFFF)
    draws = u * np.asarray(bucket.weights, dtype=np.uint64)
    return bucket.items[int(np.argmax(draws))]


def _list_choose(bucket: Bucket, x: int, r: int) -> int:
    sums = np.cumsum(bucket.weights).tolist()
    for i in range(bucket.size - 1, -1, -1):
        w = int(rjenkins.hash32_4(np.uint32(x), np.uint32(bucket.items[i]),
                                  np.uint32(r), np.uint32(bucket.id & 0xFFFFFFFF), xp=np))
        w &= 0xFFFF
        w = (w * sums[i]) >> 16
        if w < bucket.weights[i]:
            return bucket.items[i]
    return bucket.items[0]


class _PermState:
    __slots__ = ("perm_x", "perm_n", "perm")

    def __init__(self, size: int):
        self.perm_x = 0
        self.perm_n = 0
        self.perm = list(range(size))


def _perm_choose(bucket: Bucket, work: _PermState, x: int, r: int) -> int:
    """bucket_perm_choose — uniform buckets' cached pseudorandom permutation."""
    pr = r % bucket.size
    if work.perm_x != (x & 0xFFFFFFFF) or work.perm_n == 0:
        work.perm_x = x & 0xFFFFFFFF
        if pr == 0:
            s = int(rjenkins.hash32_3(np.uint32(x), np.uint32(bucket.id & 0xFFFFFFFF),
                                      np.uint32(0), xp=np)) % bucket.size
            work.perm[0] = s
            work.perm_n = 0xFFFF
            return bucket.items[s]
        work.perm = list(range(bucket.size))
        work.perm_n = 0
    elif work.perm_n == 0xFFFF:
        for i in range(1, bucket.size):
            work.perm[i] = i
        work.perm[work.perm[0]] = 0
        work.perm_n = 1
    while work.perm_n <= pr:
        p = work.perm_n
        if p < bucket.size - 1:
            i = int(rjenkins.hash32_3(np.uint32(x), np.uint32(bucket.id & 0xFFFFFFFF),
                                      np.uint32(p), xp=np)) % (bucket.size - p)
            if i:
                work.perm[p + i], work.perm[p] = work.perm[p], work.perm[p + i]
        work.perm_n += 1
    return bucket.items[work.perm[pr]]


class _Work:
    def __init__(self) -> None:
        self.perm: Dict[int, _PermState] = {}

    def for_bucket(self, b: Bucket) -> _PermState:
        st = self.perm.get(b.id)
        if st is None:
            st = _PermState(b.size)
            self.perm[b.id] = st
        return st


def _bucket_choose(cmap: CrushMap, bucket: Bucket, work: _Work, x: int,
                   r: int, arg: Optional[ChooseArg], position: int) -> int:
    if bucket.alg == CRUSH_BUCKET_UNIFORM:
        return _perm_choose(bucket, work.for_bucket(bucket), x, r)
    if bucket.alg == CRUSH_BUCKET_LIST:
        return _list_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW:
        return _straw_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW2:
        return _straw2_choose(bucket, x, r, arg, position)
    if bucket.alg == CRUSH_BUCKET_TREE:
        raise NotImplementedError("tree buckets are legacy; use straw2")
    return bucket.items[0]


def _is_out(cmap: CrushMap, weight: List[int], item: int, x: int) -> bool:
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    u = int(rjenkins.hash32_2(np.uint32(x), np.uint32(item), xp=np)) & 0xFFFF
    return u >= w


def _choose_firstn(cmap: CrushMap, work: _Work, bucket: Bucket,
                   weight: List[int], x: int, numrep: int, type_: int,
                   out: List[int], outpos: int, out_size: int,
                   tries: int, recurse_tries: int, local_retries: int,
                   local_fallback_retries: int, recurse_to_leaf: bool,
                   vary_r: int, stable: int, out2: Optional[List[int]],
                   parent_r: int, choose_args: Dict[int, ChooseArg]) -> int:
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_b = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                collide = False
                r = rep + parent_r + ftotal
                if in_b.size == 0:
                    reject = True
                else:
                    if (local_fallback_retries > 0
                            and flocal >= (in_b.size >> 1)
                            and flocal > local_fallback_retries):
                        item = _perm_choose(in_b, work.for_bucket(in_b), x, r)
                    else:
                        item = _bucket_choose(cmap, in_b, work, x, r,
                                              choose_args.get(in_b.id), outpos)
                    if item >= cmap.max_devices:
                        skip_rep = True
                        break
                    if item >= 0:
                        itemtype = 0
                    elif item in cmap.buckets:
                        itemtype = cmap.buckets[item].type
                    else:
                        skip_rep = True
                        break
                    if itemtype != type_:
                        if item >= 0 or item not in cmap.buckets:
                            skip_rep = True
                            break
                        in_b = cmap.buckets[item]
                        retry_bucket = True
                        continue
                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            got = _choose_firstn(
                                cmap, work, cmap.buckets[item], weight, x,
                                1 if stable else outpos + 1, 0,
                                out2, outpos, count,
                                recurse_tries, 0, local_retries,
                                local_fallback_retries, False,
                                vary_r, stable, None, sub_r, choose_args)
                            if got <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject and not collide and itemtype == 0:
                        reject = _is_out(cmap, weight, item, x)
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0
                          and flocal <= in_b.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
        if not skip_rep:
            out[outpos] = item
            outpos += 1
            count -= 1
        rep += 1
    return outpos


def _choose_indep(cmap: CrushMap, work: _Work, bucket: Bucket,
                  weight: List[int], x: int, left: int, numrep: int,
                  type_: int, out: List[int], outpos: int, tries: int,
                  recurse_tries: int, recurse_to_leaf: bool,
                  out2: Optional[List[int]], parent_r: int,
                  choose_args: Dict[int, ChooseArg]) -> None:
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_b = bucket
            while True:
                r = rep + parent_r
                if (in_b.alg == CRUSH_BUCKET_UNIFORM
                        and in_b.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_b.size == 0:
                    break
                item = _bucket_choose(cmap, in_b, work, x, r,
                                      choose_args.get(in_b.id), outpos)
                if item >= cmap.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                if item >= 0:
                    itemtype = 0
                elif item in cmap.buckets:
                    itemtype = cmap.buckets[item].type
                else:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                if itemtype != type_:
                    if item >= 0 or item not in cmap.buckets:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_b = cmap.buckets[item]
                    continue
                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(cmap, work, cmap.buckets[item], weight,
                                      x, 1, numrep, 0, out2, rep,
                                      recurse_tries, 0, False, None, r,
                                      choose_args)
                        if out2[rep] == CRUSH_ITEM_NONE:
                            break
                    elif out2 is not None:
                        out2[rep] = item
                if itemtype == 0 and _is_out(cmap, weight, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE


def crush_do_rule(cmap: CrushMap, ruleno: int, x: int, result_max: int,
                  weight: Optional[List[int]] = None,
                  choose_args: Optional[Dict[int, ChooseArg]] = None,
                  ) -> List[int]:
    """Interpret a rule's steps for input x (mapper.c:900-1100)."""
    if ruleno >= len(cmap.rules):
        return []
    if weight is None:
        weight = cmap.full_weight_vector()
    if choose_args is None:
        choose_args = cmap.choose_args
    rule = cmap.rules[ruleno]
    work = _Work()

    choose_tries = cmap.choose_total_tries + 1
    choose_leaf_tries = 0
    choose_local_retries = cmap.choose_local_tries
    choose_local_fallback_retries = cmap.choose_local_fallback_tries
    vary_r = cmap.chooseleaf_vary_r
    stable = cmap.chooseleaf_stable

    result: List[int] = []
    w: List[int] = []
    for step in rule.steps:
        if step.op == CRUSH_RULE_TAKE:
            ok = (0 <= step.arg1 < cmap.max_devices) or step.arg1 in cmap.buckets
            if ok:
                w = [step.arg1]
        elif step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if step.arg1 >= 0:
                choose_local_retries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if step.arg1 >= 0:
                choose_local_fallback_retries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if step.arg1 >= 0:
                vary_r = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if step.arg1 >= 0:
                stable = step.arg1
        elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                         CRUSH_RULE_CHOOSELEAF_FIRSTN,
                         CRUSH_RULE_CHOOSELEAF_INDEP):
            if not w:
                continue
            firstn = step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                 CRUSH_RULE_CHOOSELEAF_FIRSTN)
            recurse_to_leaf = step.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                          CRUSH_RULE_CHOOSELEAF_INDEP)
            o = [0] * result_max
            c = [0] * result_max
            osize = 0
            for wi in w:
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                if wi >= 0 or wi not in cmap.buckets:
                    continue  # probably CRUSH_ITEM_NONE
                bucket = cmap.buckets[wi]
                # The reference passes o+osize / c+osize with outpos 0, so
                # collision scans are per-TAKE-item; emulate the pointer
                # offset with scratch slices.
                avail = result_max - osize
                o_off = [0] * avail
                c_off = [0] * avail
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif cmap.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    placed = _choose_firstn(
                        cmap, work, bucket, weight, x, numrep, step.arg2,
                        o_off, 0, avail, choose_tries,
                        recurse_tries, choose_local_retries,
                        choose_local_fallback_retries, recurse_to_leaf,
                        vary_r, stable, c_off, 0, choose_args)
                else:
                    placed = min(numrep, avail)
                    _choose_indep(
                        cmap, work, bucket, weight, x, placed, numrep,
                        step.arg2, o_off, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, c_off, 0, choose_args)
                o[osize : osize + placed] = o_off[:placed]
                c[osize : osize + placed] = c_off[:placed]
                osize += placed
            if recurse_to_leaf:
                o[:osize] = c[:osize]
            w = o[:osize]
        elif step.op == CRUSH_RULE_EMIT:
            for item in w:
                if len(result) < result_max:
                    result.append(item)
            w = []
    return result

"""Built-in compressor plugins.

Reference: /root/reference/src/compressor/{zlib,lz4,snappy,zstd,brotli}/ —
each a thin Compressor subclass plus a CompressionPlugin registration.
Here zlib uses the Python stdlib (the reference links zlib/isa-l),
lz4/snappy use the from-spec native C++ block codecs in
ceph_tpu/native/src/compress.cc, and zstd/brotli bind the system
shared libraries directly via ctypes (the reference vendors/links
libzstd and libbrotli the same way — ZstdCompressor.h wraps the
streaming API, BrotliCompressor.cc the one-shot API).  Any codec whose
library is absent simply doesn't register, like a reference build
without HAVE_LZ4/HAVE_BROTLI.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import zlib as _zlib
from typing import Optional, Tuple

import numpy as np

from ceph_tpu import native
from ceph_tpu.compressor import (
    COMP_ALG_BROTLI,
    COMP_ALG_LZ4,
    COMP_ALG_SNAPPY,
    COMP_ALG_ZLIB,
    COMP_ALG_ZSTD,
    CompressionPlugin,
    Compressor,
)

_U8P = ctypes.POINTER(ctypes.c_uint8)


def _u8(data: bytes) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8)


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(_U8P)


class ZlibCompressor(Compressor):
    """Deflate via stdlib zlib.

    The reference's compressor_message carries the zlib window bits used at
    compress time (ZlibCompressor.cc); same here.
    """

    WINDOW_BITS = -15  # raw deflate, matching the reference's isal/zlib path

    def __init__(self, level: int = 5):
        super().__init__(COMP_ALG_ZLIB, "zlib")
        self.level = level

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        c = _zlib.compressobj(self.level, _zlib.DEFLATED, self.WINDOW_BITS)
        return c.compress(data) + c.flush(), self.WINDOW_BITS

    # deflate expands at most ~1032x; cap output vs input size so a crafted
    # stream can't balloon a small blob into a multi-GiB allocation
    MAX_EXPANSION = 1100

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        wbits = (compressor_message if compressor_message is not None
                 else self.WINDOW_BITS)
        d = _zlib.decompressobj(wbits)
        cap = len(data) * self.MAX_EXPANSION + 1024
        out = d.decompress(data, cap)
        if d.unconsumed_tail:
            raise ValueError(
                f"zlib: implausible expansion beyond {cap} bytes")
        return out + d.flush()


class _NativeBlockCompressor(Compressor):
    """Shared driver for the native C++ block codecs."""

    _prefix = ""

    def __init__(self, alg: int, type_name: str):
        super().__init__(alg, type_name)
        self._lib = native.get_lib()
        if self._lib is None:  # pragma: no cover - broken toolchain only
            raise RuntimeError(
                f"native codecs unavailable: {native.build_error()}")

    def _fn(self, op: str):
        return getattr(self._lib, f"ceph_tpu_{self._prefix}_{op}")

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        src = _u8(data)
        cap = int(self._fn("compress_bound")(len(data)))
        dst = np.empty(cap, dtype=np.uint8)
        n = int(self._fn("compress")(_ptr(src), len(data), _ptr(dst), cap))
        if n < 0:
            raise RuntimeError(f"{self.type_name} compress failed")
        # uncompressed length header for decompress sizing (the reference
        # stores it in the blob metadata; snappy has it in-format)
        return dst[:n].tobytes(), None

    # both block formats expand at most ~255x (length-extension bytes add up
    # to 255 output bytes each); anything claiming more is corrupt — reject
    # before allocating a multi-GiB buffer from a few untrusted header bytes
    MAX_EXPANSION = 256

    def _decompress_raw(self, data: bytes, out_cap: int) -> bytes:
        if out_cap > len(data) * self.MAX_EXPANSION + 1024:
            raise ValueError(
                f"{self.type_name}: implausible uncompressed length"
                f" {out_cap} for {len(data)} compressed bytes")
        src = _u8(data)
        dst = np.empty(out_cap, dtype=np.uint8)
        n = int(self._fn("decompress")(_ptr(src), len(data), _ptr(dst), out_cap))
        if n < 0:
            raise ValueError(f"{self.type_name}: malformed compressed data")
        return dst[:n].tobytes()


class Lz4Compressor(_NativeBlockCompressor):
    """LZ4 block format (native C++ codec).

    The reference prefixes each lz4-compressed blob with the uncompressed
    segment lengths (LZ4Compressor.h compress framing); here a single
    4-byte LE uncompressed length plays that role.
    """

    _prefix = "lz4"

    def __init__(self):
        super().__init__(COMP_ALG_LZ4, "lz4")

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        if len(data) >= 1 << 32:  # 4-byte length header limit
            raise RuntimeError("lz4: input too large (>= 4 GiB)")
        payload, msg = super().compress(data)
        return len(data).to_bytes(4, "little") + payload, msg

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        if len(data) < 4:
            raise ValueError("lz4: truncated header")
        want = int.from_bytes(data[:4], "little")
        out = self._decompress_raw(data[4:], want)
        if len(out) != want:
            raise ValueError("lz4: length mismatch")
        return out


class SnappyCompressor(_NativeBlockCompressor):
    """Snappy format (native C++ codec); length rides in-format."""

    _prefix = "snappy"

    def __init__(self):
        super().__init__(COMP_ALG_SNAPPY, "snappy")

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        src = _u8(data)
        want = int(self._lib.ceph_tpu_snappy_uncompressed_length(
            _ptr(src), len(data)))
        if want < 0:
            raise ValueError("snappy: malformed length header")
        return self._decompress_raw(data, want)


def _load_shared(name: str) -> Optional[ctypes.CDLL]:
    """dlopen a system library by soname candidates; None if absent."""
    for cand in (ctypes.util.find_library(name), f"lib{name}.so.1",
                 f"lib{name}.so"):
        if not cand:
            continue
        try:
            return ctypes.CDLL(cand)
        except OSError:
            continue
    return None


class ZstdCompressor(Compressor):
    """zstd via the system libzstd one-shot API
    (ZSTD_compress/ZSTD_decompress — the simple-API tier of the
    streaming path the reference wraps in ZstdCompressor.h).  The
    uncompressed length rides in the zstd frame header, so decompress
    needs no side-channel."""

    _lib: Optional[ctypes.CDLL] = None

    @classmethod
    def lib(cls) -> Optional[ctypes.CDLL]:
        if cls._lib is None:
            lz = _load_shared("zstd")
            if lz is not None:
                lz.ZSTD_compressBound.restype = ctypes.c_size_t
                lz.ZSTD_compress.restype = ctypes.c_size_t
                lz.ZSTD_decompress.restype = ctypes.c_size_t
                lz.ZSTD_isError.restype = ctypes.c_uint
                lz.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
            cls._lib = lz
        return cls._lib

    def __init__(self, level: int = 1):
        super().__init__(COMP_ALG_ZSTD, "zstd")
        # the reference's compressor_zstd_level default is 1
        self.level = level

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        lz = self.lib()
        src = _u8(data)
        cap = int(lz.ZSTD_compressBound(ctypes.c_size_t(len(data))))
        dst = np.empty(cap, dtype=np.uint8)
        n = int(lz.ZSTD_compress(_ptr(dst), ctypes.c_size_t(cap),
                                 _ptr(src), ctypes.c_size_t(len(data)),
                                 ctypes.c_int(self.level)))
        if lz.ZSTD_isError(ctypes.c_size_t(n)):
            raise RuntimeError("zstd compress failed")
        return dst[:n].tobytes(), None

    # ruler-constant data compresses ~20000:1 per zstd block; cap what a
    # frame header may claim so corrupt metadata can't force a huge alloc
    MAX_EXPANSION = 1 << 17

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        lz = self.lib()
        src = _u8(data)
        want = int(lz.ZSTD_getFrameContentSize(
            _ptr(src), ctypes.c_size_t(len(data))))
        # ZSTD_CONTENTSIZE_UNKNOWN / _ERROR are 2**64-1 / 2**64-2
        # (the restype is unsigned, so they arrive as huge positives)
        if want >= (1 << 64) - 2 or \
                want > len(data) * self.MAX_EXPANSION + 1024:
            raise ValueError("zstd: malformed/implausible frame header")
        dst = np.empty(max(want, 1), dtype=np.uint8)
        n = int(lz.ZSTD_decompress(_ptr(dst), ctypes.c_size_t(want),
                                   _ptr(src),
                                   ctypes.c_size_t(len(data))))
        if lz.ZSTD_isError(ctypes.c_size_t(n)) or n != want:
            raise ValueError("zstd: malformed compressed data")
        return dst[:n].tobytes()


class BrotliCompressor(Compressor):
    """brotli via the system libbrotlienc/dec one-shot API
    (BrotliEncoderCompress/BrotliDecoderDecompress; the reference's
    BrotliCompressor.cc uses the same pair).  Brotli's format carries
    no length, so a 4-byte LE header plays the blob-metadata role."""

    _enc: Optional[ctypes.CDLL] = None
    _dec: Optional[ctypes.CDLL] = None

    @classmethod
    def libs(cls):
        if cls._enc is None:
            cls._enc = _load_shared("brotlienc")
            cls._dec = _load_shared("brotlidec")
            if cls._enc is not None:
                cls._enc.BrotliEncoderCompress.restype = ctypes.c_int
                cls._enc.BrotliEncoderMaxCompressedSize.restype = \
                    ctypes.c_size_t
            if cls._dec is not None:
                cls._dec.BrotliDecoderDecompress.restype = ctypes.c_int
        return cls._enc, cls._dec

    def __init__(self, quality: int = 5):
        super().__init__(COMP_ALG_BROTLI, "brotli")
        self.quality = quality

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        if len(data) >= 1 << 32:
            raise RuntimeError("brotli: input too large (>= 4 GiB)")
        enc, _dec = self.libs()
        src = _u8(data)
        cap = int(enc.BrotliEncoderMaxCompressedSize(
            ctypes.c_size_t(len(data)))) or len(data) + 1024
        dst = np.empty(cap, dtype=np.uint8)
        out_len = ctypes.c_size_t(cap)
        ok = enc.BrotliEncoderCompress(
            ctypes.c_int(self.quality), ctypes.c_int(22),  # lgwin default
            ctypes.c_int(0),  # mode: generic
            ctypes.c_size_t(len(data)), _ptr(src),
            ctypes.byref(out_len), _ptr(dst))
        if not ok:
            raise RuntimeError("brotli compress failed")
        return (len(data).to_bytes(4, "little")
                + dst[:out_len.value].tobytes()), None

    MAX_EXPANSION = 1 << 17  # window-sized back-references: huge ratios

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        if len(data) < 4:
            raise ValueError("brotli: truncated header")
        _enc, dec = self.libs()
        want = int.from_bytes(data[:4], "little")
        if want > (len(data) - 4) * self.MAX_EXPANSION + 1024:
            raise ValueError("brotli: implausible uncompressed length")
        src = _u8(data[4:])
        dst = np.empty(max(want, 1), dtype=np.uint8)
        out_len = ctypes.c_size_t(want)
        rc = dec.BrotliDecoderDecompress(
            ctypes.c_size_t(len(src)), _ptr(src),
            ctypes.byref(out_len), _ptr(dst))
        if rc != 1 or out_len.value != want:  # BROTLI_DECODER_RESULT_SUCCESS
            raise ValueError("brotli: malformed compressed data")
        return dst[:out_len.value].tobytes()


def register_all(registry) -> None:
    registry.add("compressor", "zlib",
                 CompressionPlugin("zlib", ZlibCompressor))
    lib = native.get_lib()
    if lib is not None and hasattr(lib, "ceph_tpu_lz4_compress"):
        registry.add("compressor", "lz4",
                     CompressionPlugin("lz4", Lz4Compressor))
        registry.add("compressor", "snappy",
                     CompressionPlugin("snappy", SnappyCompressor))
    # zstd / brotli register only when the system libraries resolve,
    # mirroring a reference build without HAVE_LZ4/HAVE_BROTLI
    if ZstdCompressor.lib() is not None:
        registry.add("compressor", "zstd",
                     CompressionPlugin("zstd", ZstdCompressor))
    if all(BrotliCompressor.libs()):
        registry.add("compressor", "brotli",
                     CompressionPlugin("brotli", BrotliCompressor))

"""Built-in compressor plugins.

Reference: /root/reference/src/compressor/{zlib,lz4,snappy,zstd,brotli}/ —
each a thin Compressor subclass plus a CompressionPlugin registration.
Here zlib uses the Python stdlib (the reference links zlib/isa-l), and
lz4/snappy use the from-spec native C++ block codecs in
ceph_tpu/native/src/compress.cc.  zstd and brotli have no codec in this
image, so — like a reference build without HAVE_LZ4 — they simply don't
register, and `Compressor.create("zstd")` returns None.
"""

from __future__ import annotations

import ctypes
import zlib as _zlib
from typing import Optional, Tuple

import numpy as np

from ceph_tpu import native
from ceph_tpu.compressor import (
    COMP_ALG_LZ4,
    COMP_ALG_SNAPPY,
    COMP_ALG_ZLIB,
    CompressionPlugin,
    Compressor,
)

_U8P = ctypes.POINTER(ctypes.c_uint8)


def _u8(data: bytes) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8)


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(_U8P)


class ZlibCompressor(Compressor):
    """Deflate via stdlib zlib.

    The reference's compressor_message carries the zlib window bits used at
    compress time (ZlibCompressor.cc); same here.
    """

    WINDOW_BITS = -15  # raw deflate, matching the reference's isal/zlib path

    def __init__(self, level: int = 5):
        super().__init__(COMP_ALG_ZLIB, "zlib")
        self.level = level

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        c = _zlib.compressobj(self.level, _zlib.DEFLATED, self.WINDOW_BITS)
        return c.compress(data) + c.flush(), self.WINDOW_BITS

    # deflate expands at most ~1032x; cap output vs input size so a crafted
    # stream can't balloon a small blob into a multi-GiB allocation
    MAX_EXPANSION = 1100

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        wbits = (compressor_message if compressor_message is not None
                 else self.WINDOW_BITS)
        d = _zlib.decompressobj(wbits)
        cap = len(data) * self.MAX_EXPANSION + 1024
        out = d.decompress(data, cap)
        if d.unconsumed_tail:
            raise ValueError(
                f"zlib: implausible expansion beyond {cap} bytes")
        return out + d.flush()


class _NativeBlockCompressor(Compressor):
    """Shared driver for the native C++ block codecs."""

    _prefix = ""

    def __init__(self, alg: int, type_name: str):
        super().__init__(alg, type_name)
        self._lib = native.get_lib()
        if self._lib is None:  # pragma: no cover - broken toolchain only
            raise RuntimeError(
                f"native codecs unavailable: {native.build_error()}")

    def _fn(self, op: str):
        return getattr(self._lib, f"ceph_tpu_{self._prefix}_{op}")

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        src = _u8(data)
        cap = int(self._fn("compress_bound")(len(data)))
        dst = np.empty(cap, dtype=np.uint8)
        n = int(self._fn("compress")(_ptr(src), len(data), _ptr(dst), cap))
        if n < 0:
            raise RuntimeError(f"{self.type_name} compress failed")
        # uncompressed length header for decompress sizing (the reference
        # stores it in the blob metadata; snappy has it in-format)
        return dst[:n].tobytes(), None

    # both block formats expand at most ~255x (length-extension bytes add up
    # to 255 output bytes each); anything claiming more is corrupt — reject
    # before allocating a multi-GiB buffer from a few untrusted header bytes
    MAX_EXPANSION = 256

    def _decompress_raw(self, data: bytes, out_cap: int) -> bytes:
        if out_cap > len(data) * self.MAX_EXPANSION + 1024:
            raise ValueError(
                f"{self.type_name}: implausible uncompressed length"
                f" {out_cap} for {len(data)} compressed bytes")
        src = _u8(data)
        dst = np.empty(out_cap, dtype=np.uint8)
        n = int(self._fn("decompress")(_ptr(src), len(data), _ptr(dst), out_cap))
        if n < 0:
            raise ValueError(f"{self.type_name}: malformed compressed data")
        return dst[:n].tobytes()


class Lz4Compressor(_NativeBlockCompressor):
    """LZ4 block format (native C++ codec).

    The reference prefixes each lz4-compressed blob with the uncompressed
    segment lengths (LZ4Compressor.h compress framing); here a single
    4-byte LE uncompressed length plays that role.
    """

    _prefix = "lz4"

    def __init__(self):
        super().__init__(COMP_ALG_LZ4, "lz4")

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        if len(data) >= 1 << 32:  # 4-byte length header limit
            raise RuntimeError("lz4: input too large (>= 4 GiB)")
        payload, msg = super().compress(data)
        return len(data).to_bytes(4, "little") + payload, msg

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        if len(data) < 4:
            raise ValueError("lz4: truncated header")
        want = int.from_bytes(data[:4], "little")
        out = self._decompress_raw(data[4:], want)
        if len(out) != want:
            raise ValueError("lz4: length mismatch")
        return out


class SnappyCompressor(_NativeBlockCompressor):
    """Snappy format (native C++ codec); length rides in-format."""

    _prefix = "snappy"

    def __init__(self):
        super().__init__(COMP_ALG_SNAPPY, "snappy")

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        src = _u8(data)
        want = int(self._lib.ceph_tpu_snappy_uncompressed_length(
            _ptr(src), len(data)))
        if want < 0:
            raise ValueError("snappy: malformed length header")
        return self._decompress_raw(data, want)


def register_all(registry) -> None:
    registry.add("compressor", "zlib",
                 CompressionPlugin("zlib", ZlibCompressor))
    lib = native.get_lib()
    if lib is not None and hasattr(lib, "ceph_tpu_lz4_compress"):
        registry.add("compressor", "lz4",
                     CompressionPlugin("lz4", Lz4Compressor))
        registry.add("compressor", "snappy",
                     CompressionPlugin("snappy", SnappyCompressor))
    # zstd / brotli: no codec in this image — intentionally unregistered,
    # mirroring a reference build without HAVE_LZ4/HAVE_BROTLI.

"""Compression framework.

Reference seam: /root/reference/src/compressor/Compressor.h — the
`Compressor` ABC (algorithms none/snappy/zlib/zstd/lz4/brotli, pool modes
none/passive/aggressive/force, `compress`/`decompress`, factory by name via
the generic PluginRegistry at Compressor.cc:69-102, including the "random"
teuthology algorithm :72-78).

TPU-first addition: batched compressibility scoring
(ceph_tpu.compressor.scoring) runs a byte-histogram entropy estimate on the
accelerator so the BlueStore-style write path can decide compress-vs-skip
for thousands of blobs per dispatch before spending host CPU on the codec.
"""

from __future__ import annotations

import random as _random
from typing import Dict, List, Optional, Tuple

from ceph_tpu.common.plugin_registry import PluginRegistry

# algorithm ids, matching the reference enum values (Compressor.h:35-47)
COMP_ALG_NONE = 0
COMP_ALG_SNAPPY = 1
COMP_ALG_ZLIB = 2
COMP_ALG_ZSTD = 3
COMP_ALG_LZ4 = 4
COMP_ALG_BROTLI = 5

COMPRESSION_ALGORITHMS: List[Tuple[str, int]] = [
    ("none", COMP_ALG_NONE),
    ("snappy", COMP_ALG_SNAPPY),
    ("zlib", COMP_ALG_ZLIB),
    ("zstd", COMP_ALG_ZSTD),
    ("lz4", COMP_ALG_LZ4),
    ("brotli", COMP_ALG_BROTLI),
]

# pool compression modes (Compressor.h:64-69)
COMP_NONE = 0        # compress never
COMP_PASSIVE = 1     # compress if hinted COMPRESSIBLE
COMP_AGGRESSIVE = 2  # compress unless hinted INCOMPRESSIBLE
COMP_FORCE = 3       # compress always

_MODE_NAMES = {COMP_NONE: "none", COMP_PASSIVE: "passive",
               COMP_AGGRESSIVE: "aggressive", COMP_FORCE: "force"}

# alloc-hint flags relevant to compression (os/ObjectStore.h alloc hints)
ALLOC_HINT_COMPRESSIBLE = 1
ALLOC_HINT_INCOMPRESSIBLE = 2


def get_comp_alg_name(alg: int) -> str:
    for name, a in COMPRESSION_ALGORITHMS:
        if a == alg:
            return name
    return "???"


def get_comp_alg_type(name: str) -> Optional[int]:
    for n, a in COMPRESSION_ALGORITHMS:
        if n == name:
            return a
    return None


def get_comp_mode_name(mode: int) -> str:
    return _MODE_NAMES.get(mode, "???")


def get_comp_mode_type(name: str) -> Optional[int]:
    for mode, n in _MODE_NAMES.items():
        if n == name:
            return mode
    return None


class Compressor:
    """Abstract codec: bytes in, bytes out.

    The reference's `compressor_message` side-channel (an optional int32
    rides the blob metadata, e.g. zlib window bits) is kept: `compress`
    returns (payload, message) and `decompress` takes the message back.
    """

    def __init__(self, alg: int, type_name: str):
        self.alg = alg
        self.type_name = type_name

    def get_type_name(self) -> str:
        return self.type_name

    def get_type(self) -> int:
        return self.alg

    def compress(self, data: bytes) -> Tuple[bytes, Optional[int]]:
        raise NotImplementedError

    def decompress(self, data: bytes,
                   compressor_message: Optional[int] = None) -> bytes:
        raise NotImplementedError

    # -- factory ----------------------------------------------------------

    @staticmethod
    def create(type_name: str) -> Optional["Compressor"]:
        """Factory by algorithm name; None if unknown/unavailable.

        Mirrors Compressor::create (Compressor.cc:69-102), including
        "random" which picks a real algorithm per instance.
        """
        _ensure_builtin_plugins()
        if type_name == "random":
            candidates = [n for n, _ in COMPRESSION_ALGORITHMS
                          if n != "none" and
                          PluginRegistry.instance().get("compressor", n)]
            type_name = _random.choice(candidates)
        if not any(n == type_name for n, _ in COMPRESSION_ALGORITHMS):
            return None
        if type_name == "none":
            return None  # reference returns nullptr for "none" too
        plugin = PluginRegistry.instance().get_or_load("compressor", type_name)
        if plugin is None:
            return None
        return plugin.factory()

    @staticmethod
    def create_by_alg(alg: int) -> Optional["Compressor"]:
        return Compressor.create(get_comp_alg_name(alg))


class CompressionPlugin:
    """Named factory (reference: CompressionPlugin.h)."""

    def __init__(self, name: str, factory):
        self.name = name
        self.factory = factory


_builtins_loaded = False


def _ensure_builtin_plugins() -> None:
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from ceph_tpu.compressor import plugins

    plugins.register_all(PluginRegistry.instance())


def available_algorithms() -> List[str]:
    """Names with a working codec in this build (zstd/brotli are gated)."""
    _ensure_builtin_plugins()
    reg = PluginRegistry.instance()
    return [n for n, _ in COMPRESSION_ALGORITHMS
            if n != "none" and reg.get("compressor", n) is not None]

"""Compress-or-not policy, mirroring BlueStore's write-path gate.

Reference: BlueStore::_do_alloc_write (BlueStore.cc:13459-13606) —
per-pool mode/algorithm overrides, alloc-hint interaction
(COMPRESSIBLE/INCOMPRESSIBLE), and the required-ratio accept test
(`result_len <= want_len` where want = raw * required_ratio, :13545-13585);
the compression header carries algorithm + original length
(bluestore_compression_header_t).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from ceph_tpu.compressor import (
    ALLOC_HINT_COMPRESSIBLE,
    ALLOC_HINT_INCOMPRESSIBLE,
    COMP_AGGRESSIVE,
    COMP_FORCE,
    COMP_NONE,
    COMP_PASSIVE,
    Compressor,
)

DEFAULT_REQUIRED_RATIO = 0.875  # bluestore_compression_required_ratio


@dataclass
class CompressionHeader:
    """bluestore_compression_header_t analog: rides ahead of the payload."""

    alg: int
    original_length: int
    compressor_message: Optional[int] = None


def want_compress(mode: int, alloc_hints: int = 0) -> bool:
    """Mode x hint decision (BlueStore.cc:13475-13497)."""
    if mode == COMP_NONE:
        return False
    if mode == COMP_FORCE:
        return True
    if mode == COMP_PASSIVE:
        return bool(alloc_hints & ALLOC_HINT_COMPRESSIBLE)
    if mode == COMP_AGGRESSIVE:
        return not (alloc_hints & ALLOC_HINT_INCOMPRESSIBLE)
    return False


def maybe_compress(
    data: bytes,
    compressor: Optional[Compressor],
    mode: int = COMP_AGGRESSIVE,
    alloc_hints: int = 0,
    required_ratio: float = DEFAULT_REQUIRED_RATIO,
) -> Tuple[bytes, Optional[CompressionHeader]]:
    """Returns (payload, header); header is None when stored raw.

    The accept test mirrors the reference's required-ratio gate:
    compressed length must be <= len(data) * required_ratio, else the raw
    bytes are stored and the attempt counts as rejected.  Unlike
    BlueStore (where bluestore_compression_header_t rides the stored
    payload and counts against want_len), the header here lives in onode
    metadata, so no header bytes are part of the comparison.
    """
    if compressor is None or not data or not want_compress(mode, alloc_hints):
        return data, None
    compressed, message = compressor.compress(data)
    want_len = int(len(data) * required_ratio)
    if len(compressed) > want_len:
        return data, None
    return compressed, CompressionHeader(
        alg=compressor.get_type(),
        original_length=len(data),
        compressor_message=message,
    )


def decompress(payload: bytes, header: Optional[CompressionHeader]) -> bytes:
    if header is None:
        return payload
    from ceph_tpu.compressor import get_comp_alg_name

    compressor = Compressor.create(get_comp_alg_name(header.alg))
    if compressor is None:
        raise ValueError(
            f"no codec for algorithm {header.alg} in this build")
    out = compressor.decompress(payload, header.compressor_message)
    if len(out) != header.original_length:
        raise ValueError("decompressed length mismatch")
    return out

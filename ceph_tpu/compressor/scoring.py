"""TPU batched compressibility scoring.

The reference decides compress-vs-skip per blob *after* running the codec,
rejecting results above `bluestore_compression_required_ratio`
(BlueStore.cc:13545-13585).  On TPU we can do better: an order-0 entropy
estimate over byte histograms — one MXU matmul for thousands of blocks —
predicts the achievable ratio before any host codec runs, so incompressible
blobs (encrypted, already-compressed) skip the codec entirely.  The final
required-ratio gate (ceph_tpu.compressor.gate) still applies to actual
codec output, preserving reference semantics.

Histogram trick: one-hot(block) @ ones == bincount, expressed as a
(B*S, 256) one-hot against an identity gather — XLA lowers the batched
one-hot sum to an MXU-friendly matmul instead of a scatter.
"""

from __future__ import annotations

import numpy as np

try:
    import jax
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False


def byte_histograms_host(blocks: np.ndarray) -> np.ndarray:
    """(B, S) uint8 -> (B, 256) int32 byte histograms (numpy).

    One offset-bincount over the whole batch: row i's bytes are
    shifted into the disjoint range [256*i, 256*(i+1)), so a single
    np.bincount of the flattened batch produces every row's histogram
    at once — no per-row Python loop."""
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    b, _s = blocks.shape
    if b == 0:
        return np.zeros((0, 256), dtype=np.int32)
    offset = blocks.astype(np.intp) + \
        256 * np.arange(b, dtype=np.intp)[:, None]
    return np.bincount(offset.ravel(),
                       minlength=256 * b).reshape(b, 256) \
        .astype(np.int32)


def entropy_bits_per_byte_host(blocks: np.ndarray) -> np.ndarray:
    hist = byte_histograms_host(blocks).astype(np.float64)
    s = blocks.shape[1]
    p = hist / s
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(p > 0, -p * np.log2(p), 0.0)
    return terms.sum(axis=1).astype(np.float32)


MIN_DEVICE_BYTES = 64 * 1024  # below this the host path wins on latency


def _device_ok(blocks) -> bool:
    from ceph_tpu.ops import gf

    nbytes = getattr(blocks, "nbytes", 0) or np.asarray(blocks).nbytes
    return (HAVE_JAX and nbytes >= MIN_DEVICE_BYTES
            and gf.backend_available())


# lags probed for periodicity: every period p with p | some lag is caught
# (covers power-of-two, ×3 and common text/record strides up to 512)
_PROBE_LAGS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96,
               128, 192, 256, 384, 512)


def _match_fraction_host(blocks: np.ndarray) -> np.ndarray:
    """(B, S) -> (B,) best self-match fraction over the probe lags."""
    b, s = blocks.shape
    best = np.zeros(b, dtype=np.float32)
    for lag in _PROBE_LAGS:
        if lag >= s:
            break
        frac = (blocks[:, lag:] == blocks[:, :-lag]).mean(axis=1)
        best = np.maximum(best, frac.astype(np.float32))
    return best


if HAVE_JAX:

    @jax.jit
    def _byte_histograms_dev(blocks):
        onehot = jax.nn.one_hot(blocks.astype(jnp.int32), 256,
                                dtype=jnp.float32)
        return onehot.sum(axis=1).astype(jnp.int32)

    @jax.jit
    def _entropy_dev(blocks):
        hist = _byte_histograms_dev(blocks).astype(jnp.float32)
        s = blocks.shape[1]
        p = hist / s
        terms = jnp.where(p > 0, -p * jnp.log2(jnp.maximum(p, 1e-30)), 0.0)
        return terms.sum(axis=1)

    @jax.jit
    def _match_fraction_dev(blocks):
        s = blocks.shape[1]
        best = jnp.zeros(blocks.shape[0], dtype=jnp.float32)
        for lag in _PROBE_LAGS:  # static python loop, unrolled at trace
            if lag >= s:
                break
            frac = (blocks[:, lag:] == blocks[:, :-lag]).mean(
                axis=1, dtype=jnp.float32)
            best = jnp.maximum(best, frac)
        return best


def byte_histograms(blocks):
    """(B, S) uint8 -> (B, 256) int32, batched one-hot reduction."""
    if _device_ok(blocks):
        return _byte_histograms_dev(blocks)
    return byte_histograms_host(np.asarray(blocks))


def entropy_bits_per_byte(blocks):
    """(B, S) uint8 -> (B,) float32 order-0 entropy in bits/byte."""
    if _device_ok(blocks):
        return _entropy_dev(blocks)
    return entropy_bits_per_byte_host(np.asarray(blocks))


def match_fraction(blocks):
    """(B, S) uint8 -> (B,) float32: best self-match fraction over the
    probe lags — a cheap repetition signal that catches periodic data
    whose byte histogram is uniform (LZ compresses it, entropy doesn't
    see it)."""
    if _device_ok(blocks):
        return _match_fraction_dev(blocks)
    return _match_fraction_host(np.asarray(blocks))


def compress_decision(blocks, required_ratio: float = 0.875,
                      margin: float = 0.05,
                      match_threshold: float = 0.5):
    """(B, S) uint8 -> (B,) bool: worth running the codec?

    True when either (a) the order-0 entropy bound predicts a ratio
    comfortably under `required_ratio` (`margin` absorbs codec overhead
    vs the bound), or (b) the lag-probe repetition signal fires —
    periodic data (e.g. a repeating 256-byte random pattern) has a
    uniform histogram yet compresses far below required_ratio, so
    entropy alone would permanently skip the codec for it.

    Known false-negative class: data whose only redundancy is
    long-range, aperiodic matches (period not dividing any probe lag,
    or match distance > 512).  Such spans are stored raw; COMP_FORCE
    mode bypasses this prescreen entirely at the store layer.
    """
    est_ratio = np.asarray(entropy_bits_per_byte(blocks)) / 8.0
    entropy_ok = est_ratio <= (required_ratio + margin)
    if entropy_ok.all():  # common path: no need for the lag probe
        return entropy_ok
    repetitive = np.asarray(match_fraction(blocks)) >= match_threshold
    return entropy_ok | repetitive

"""TPU batched compressibility scoring.

The reference decides compress-vs-skip per blob *after* running the codec,
rejecting results above `bluestore_compression_required_ratio`
(BlueStore.cc:13545-13585).  On TPU we can do better: an order-0 entropy
estimate over byte histograms — one MXU matmul for thousands of blocks —
predicts the achievable ratio before any host codec runs, so incompressible
blobs (encrypted, already-compressed) skip the codec entirely.  The final
required-ratio gate (ceph_tpu.compressor.gate) still applies to actual
codec output, preserving reference semantics.

Histogram trick: one-hot(block) @ ones == bincount, expressed as a
(B*S, 256) one-hot against an identity gather — XLA lowers the batched
one-hot sum to an MXU-friendly matmul instead of a scatter.
"""

from __future__ import annotations

import numpy as np

try:
    import jax
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False


def byte_histograms_host(blocks: np.ndarray) -> np.ndarray:
    """(B, S) uint8 -> (B, 256) int32 byte histograms (numpy)."""
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    b, s = blocks.shape
    out = np.zeros((b, 256), dtype=np.int32)
    for i in range(b):
        out[i] = np.bincount(blocks[i], minlength=256)
    return out


def entropy_bits_per_byte_host(blocks: np.ndarray) -> np.ndarray:
    hist = byte_histograms_host(blocks).astype(np.float64)
    s = blocks.shape[1]
    p = hist / s
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(p > 0, -p * np.log2(p), 0.0)
    return terms.sum(axis=1).astype(np.float32)


MIN_DEVICE_BYTES = 64 * 1024  # below this the host path wins on latency


def _device_ok(blocks) -> bool:
    from ceph_tpu.ops import gf

    nbytes = getattr(blocks, "nbytes", 0) or np.asarray(blocks).nbytes
    return (HAVE_JAX and nbytes >= MIN_DEVICE_BYTES
            and gf.backend_available())


if HAVE_JAX:

    @jax.jit
    def _byte_histograms_dev(blocks):
        onehot = jax.nn.one_hot(blocks.astype(jnp.int32), 256,
                                dtype=jnp.float32)
        return onehot.sum(axis=1).astype(jnp.int32)

    @jax.jit
    def _entropy_dev(blocks):
        hist = _byte_histograms_dev(blocks).astype(jnp.float32)
        s = blocks.shape[1]
        p = hist / s
        terms = jnp.where(p > 0, -p * jnp.log2(jnp.maximum(p, 1e-30)), 0.0)
        return terms.sum(axis=1)


def byte_histograms(blocks):
    """(B, S) uint8 -> (B, 256) int32, batched one-hot reduction."""
    if _device_ok(blocks):
        return _byte_histograms_dev(blocks)
    return byte_histograms_host(np.asarray(blocks))


def entropy_bits_per_byte(blocks):
    """(B, S) uint8 -> (B,) float32 order-0 entropy in bits/byte."""
    if _device_ok(blocks):
        return _entropy_dev(blocks)
    return entropy_bits_per_byte_host(np.asarray(blocks))


def compress_decision(blocks, required_ratio: float = 0.875,
                      margin: float = 0.05):
    """(B, S) uint8 -> (B,) bool: worth running the codec?

    True when the order-0 entropy bound predicts a ratio comfortably
    under `required_ratio`; `margin` absorbs codec overhead vs the
    entropy bound (real LZ output never beats order-0 entropy on
    random data, but beats it easily on repetitive data — the margin
    keeps marginal blobs on the "try it" side).
    """
    est_ratio = np.asarray(entropy_bits_per_byte(blocks)) / 8.0
    return est_ratio <= (required_ratio + margin)

"""GF(2) bitmatrix constructions for the RAID-6 bit-matrix codes.

Reference parity: the jerasure plugin's bitmatrix trio
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:452
liberation, :476 blaum_roth, :488-513 liber8tion).  The jerasure C
sources for these live in a submodule that is EMPTY in the reference
tree, so the constructions here are written from the published
definitions:

- liberation: Plank, "The RAID-6 Liberation Codes" (FAST 2008).
  w prime, k <= w, m = 2.  P block = k identities; Q block's X_i is
  the i-step bit rotation plus, for i > 0, one extra 1 at row
  (i*(w-1)/2) mod w, column (row + i - 1) mod w — the minimal-density
  construction from the paper (kw + k - 1 total ones).
- blaum_roth: Blaum & Roth, "On Lowest Density MDS Codes" (IT 1999).
  w + 1 prime, k <= w, m = 2.  Q block's X_i = C^i where C is
  multiplication by x in the ring GF(2)[x]/(1 + x + ... + x^w)
  (subdiagonal shift with an all-ones last column).
- liber8tion: Plank, "The RAID-6 Liber8tion Code" (w = 8, m = 2,
  k <= 8).  Upstream's X matrices are a hard-coded exhaustive-search
  table (liber8tion.c) that is not available in this tree; this build
  derives the X_i from the GF(2^8) companion ladder (X_i = C^i over
  poly 0x11d), which keeps the technique's contract — an MDS RAID-6
  bitmatrix at w=8 with single-XOR-per-bit P — but does NOT claim
  wire-level chunk compatibility with upstream liber8tion (density is
  not minimal either).  Documented deviation, not an oversight.

Matrix convention matches jerasure's bitmatrix layout: (m*w, k*w)
with out_bit[j*w + r] = XOR over data bits [i*w + c] where
bm[j*w + r, i*w + c] == 1; data bit (i, c) is packet c of data chunk
i (jerasure_bitmatrix_encode packet semantics).
"""

from __future__ import annotations

import numpy as np


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    i = 2
    while i * i <= n:
        if n % i == 0:
            return False
        i += 1
    return True


def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, kw) liberation coding bitmatrix (FAST'08 construction)."""
    if not _is_prime(w):
        raise ValueError(f"liberation: w={w} must be prime")
    if k > w:
        raise ValueError(f"liberation: k={k} must be <= w={w}")
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        for i in range(w):
            bm[i, j * w + i] = 1                    # P: identity blocks
            bm[w + i, j * w + (j + i) % w] = 1      # Q: rotation by j
        if j > 0:
            i = (j * ((w - 1) // 2)) % w            # the extra 1
            bm[w + i, j * w + (i + j - 1) % w] = 1
    return bm


def _ladder_bitmatrix(c_mat: np.ndarray, k: int) -> np.ndarray:
    """(2w, kw) RAID-6 bitmatrix with X_i = C^i: P = identities,
    Q = the companion ladder of c_mat (shared by blaum_roth and
    liber8tion, which differ only in their rings)."""
    w = c_mat.shape[0]
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    x = np.eye(w, dtype=np.uint8)
    for j in range(k):
        bm[:w, j * w:(j + 1) * w] = np.eye(w, dtype=np.uint8)
        bm[w:, j * w:(j + 1) * w] = x
        x = (c_mat.astype(np.uint32) @ x) & 1
    return bm


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """(2w, kw) Blaum-Roth coding bitmatrix (ring construction)."""
    if not _is_prime(w + 1):
        raise ValueError(f"blaum_roth: w+1={w + 1} must be prime")
    if k > w:
        raise ValueError(f"blaum_roth: k={k} must be <= w={w}")
    # C = multiplication by x in GF(2)[x]/(1 + x + ... + x^w):
    # x * x^c = x^{c+1} for c < w-1; x * x^{w-1} = x^w = sum_t x^t
    c_mat = np.zeros((w, w), dtype=np.uint8)
    for r in range(1, w):
        c_mat[r, r - 1] = 1
    c_mat[:, w - 1] ^= 1
    return _ladder_bitmatrix(c_mat, k)


def _companion_gf256() -> np.ndarray:
    """Multiplication-by-x matrix of GF(2^8)/0x11d on coefficient bits."""
    c = np.zeros((8, 8), dtype=np.uint8)
    for r in range(1, 8):
        c[r, r - 1] = 1
    # x^8 = x^4 + x^3 + x^2 + 1 (0x1d)
    for bit in (0, 2, 3, 4):
        c[bit, 7] ^= 1
    return c


def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """(16, 8k) w=8 RAID-6 bitmatrix (module docstring: companion-
    ladder derivation, not upstream's searched table)."""
    if k > 8:
        raise ValueError(f"liber8tion: k={k} must be <= 8")
    return _ladder_bitmatrix(_companion_gf256(), k)


def packet_views(buf, w: int, packetsize: int) -> list:
    """One chunk's buffer -> its w per-packet (blocks, packetsize)
    numpy views, zero-copy.

    The jerasure packet convention this module's matrices index:
    a chunk of b*w*packetsize bytes is b repeats of w packets;
    bitmatrix column i*w + c selects packet c of chunk i across every
    block.  ``packet_views(chunk_i, w, ps)[c]`` IS that column — a
    strided view over the caller's buffer (bytearray, memoryview or
    ndarray; writable buffers yield writable views, so coding/
    recovered chunks are written in place).  The XOR-schedule host
    tier (ec/xsched.execute_host) runs directly over these views —
    no stack, no transpose, no copies."""
    if isinstance(buf, np.ndarray):
        # a non-contiguous array would make reshape COPY — writes
        # into the views would land in the throwaway copy, not the
        # caller's buffer.  Refuse loudly rather than corrupt parity.
        assert buf.flags.c_contiguous, \
            "packet_views needs a contiguous buffer"
        arr = buf.reshape(-1)
    else:
        arr = np.frombuffer(buf, dtype=np.uint8)
    arr = arr.reshape(-1, w, packetsize)
    return [arr[:, c, :] for c in range(w)]


def gf2_inv(mat: np.ndarray) -> np.ndarray:
    """Invert a square 0/1 matrix over GF(2) (Gaussian elimination)."""
    n = mat.shape[0]
    assert mat.shape == (n, n)
    a = mat.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = None
        for r in range(col, n):
            if a[r, col]:
                pivot = r
                break
        if pivot is None:
            raise ValueError("singular GF(2) matrix")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


def decode_bitmatrix(bm: np.ndarray, k: int, w: int,
                     have: tuple, erasures: tuple) -> np.ndarray:
    """Rows mapping k surviving chunks' bits -> the erased chunks' bits.

    bm is the (m*w, k*w) coding matrix; chunk ids 0..k-1 are data,
    k..k+m-1 coding.  `have` lists the k surviving chunk ids (in the
    order their packets will be stacked); returns
    (len(erasures)*w, k*w) GF(2) rows (the isa-plugin decode strategy
    — invert the surviving submatrix — in bit-space).
    """
    kw = k * w
    full = np.concatenate([np.eye(kw, dtype=np.uint8), bm], axis=0)
    gs = np.concatenate(
        [full[c * w:(c + 1) * w] for c in have], axis=0)   # (kw, kw)
    inv = gf2_inv(gs)
    rows = []
    for e in erasures:
        target = full[e * w:(e + 1) * w]                   # (w, kw)
        rows.append((target.astype(np.uint32) @ inv) & 1)
    return np.concatenate(rows, axis=0).astype(np.uint8)

"""GF(2^16) and GF(2^32) field arithmetic for wide-word Reed-Solomon.

Reference parity: jerasure/gf-complete support w in {8, 16, 32} for
technique=reed_sol_van (ErasureCodeJerasure.cc:62-78 parses w; the
gf-complete submodule is empty in the reference tree, so the field
parameters here are gf-complete's PUBLISHED defaults: primitive
polynomials 0x1100B for w=16 and 0x400007 for w=32).

w=16 uses log/antilog tables (128 KiB — trivial).  w=32 cannot table a
4-billion-element field; multiplication is vectorized carry-less
multiply + polynomial reduction (the same math gf-complete's SPLIT/
CARRY_FREE implementations compute), and inversion is
exponentiation by 2^32 - 2 (Fermat), cached per matrix coefficient.
"""

from __future__ import annotations

import functools

import numpy as np

POLY16 = 0x1100B
POLY32 = 0x400007  # x^32 + x^22 + x^2 + x + 1 (gf-complete default)


# ---------------------------------------------------------------------------
# GF(2^16): log/antilog tables
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _tables16():
    exp = np.zeros(131070, dtype=np.uint16)
    log = np.zeros(65536, dtype=np.int32)
    x = 1
    for i in range(65535):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x10000:
            x ^= POLY16
    exp[65535:] = exp[:65535]
    return exp, log


def mul16(a, b):
    """Elementwise GF(2^16) product of uint16 arrays/scalars."""
    exp, log = _tables16()
    a = np.asarray(a, dtype=np.uint16)
    b = np.asarray(b, dtype=np.uint16)
    out = exp[log[a] + log[b]]
    return np.where((a == 0) | (b == 0), np.uint16(0), out)


def inv16(a: int) -> int:
    exp, log = _tables16()
    if a == 0:
        raise ZeroDivisionError("GF(2^16) inverse of 0")
    return int(exp[(65535 - log[a]) % 65535])


# ---------------------------------------------------------------------------
# GF(2^32): carry-less multiply + reduction (vectorized)
# ---------------------------------------------------------------------------

def mul32(coeff: int, data):
    """GF(2^32) product of one coefficient with a uint32 array.

    clmul via shift-accumulate over the coefficient's set bits into a
    64-bit intermediate, then reduction by POLY32 from the top bit
    down — the schoolbook carry-free multiply gf-complete's
    CARRY_FREE path computes with PCLMULQDQ.
    """
    d = np.asarray(data, dtype=np.uint64)
    acc = np.zeros_like(d)
    c = int(coeff)
    b = 0
    while c:
        if c & 1:
            acc ^= d << np.uint64(b)
        c >>= 1
        b += 1
    # reduce the 64-bit intermediates mod x^32 + (POLY32 & 0xffffffff)
    red = np.uint64(POLY32 & 0xFFFFFFFF)
    for bit in range(62, 31, -1):
        mask = (acc >> np.uint64(bit)) & np.uint64(1)
        acc ^= (mask * red) << np.uint64(bit - 32)
        acc &= ~(mask << np.uint64(bit))
    return acc.astype(np.uint32)


def _mul32_scalar(a: int, b: int) -> int:
    return int(mul32(a, np.array([b], dtype=np.uint32))[0])


@functools.lru_cache(maxsize=4096)
def inv32(a: int) -> int:
    """a^(2^32 - 2) by square-and-multiply (Fermat inverse)."""
    if a == 0:
        raise ZeroDivisionError("GF(2^32) inverse of 0")
    result, base = 1, a
    e = (1 << 32) - 2
    while e:
        if e & 1:
            result = _mul32_scalar(result, base)
        base = _mul32_scalar(base, base)
        e >>= 1
    return result


# ---------------------------------------------------------------------------
# field façade used by the wide Vandermonde construction
# ---------------------------------------------------------------------------

class Field:
    """Scalar ops for one word size (8 delegates to ops.gf)."""

    def __init__(self, w: int):
        assert w in (8, 16, 32)
        self.w = w
        self.dtype = {8: np.uint8, 16: np.uint16, 32: np.uint32}[w]

    def mul(self, a: int, b: int) -> int:
        if self.w == 8:
            from ceph_tpu.ops import gf

            return int(gf.gf_mul(np.uint8(a), np.uint8(b)))
        if self.w == 16:
            return int(mul16(np.uint16(a), np.uint16(b)))
        return _mul32_scalar(a, b)

    def inv(self, a: int) -> int:
        if self.w == 8:
            from ceph_tpu.ops import gf

            return gf.gf_inv(a)
        if self.w == 16:
            return inv16(a)
        return inv32(a)

    def mul_vec(self, coeff: int, data):
        """coeff x uint<w> array, vectorized."""
        if self.w == 8:
            from ceph_tpu.ops import gf

            return gf.gf_mul(np.asarray(data, np.uint8), np.uint8(coeff))
        if self.w == 16:
            return mul16(data, np.uint16(coeff))
        return mul32(coeff, data)


def invert_matrix_w(mat: np.ndarray, w: int) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2^w)."""
    f = Field(w)
    n = mat.shape[0]
    a = mat.astype(np.uint64).copy()
    inv = np.eye(n, dtype=np.uint64)
    for col in range(n):
        pivot = None
        for r in range(col, n):
            if a[r, col]:
                pivot = r
                break
        if pivot is None:
            raise ValueError("singular matrix")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        if a[col, col] != 1:
            c = f.inv(int(a[col, col]))
            for j in range(n):
                a[col, j] = f.mul(int(a[col, j]), c)
                inv[col, j] = f.mul(int(inv[col, j]), c)
        for r in range(n):
            if r != col and a[r, col]:
                c = int(a[r, col])
                for j in range(n):
                    a[r, j] ^= f.mul(int(a[col, j]), c)
                    inv[r, j] ^= f.mul(int(inv[col, j]), c)
    return inv.astype(f.dtype)


def decode_matrix_w(coding: np.ndarray, k: int, erasures: list,
                    have: list, w: int) -> np.ndarray:
    """models/reed_solomon.decode_matrix generalized over GF(2^w)."""
    f = Field(w)
    assert len(have) == k
    gen = np.zeros((k, k), dtype=np.uint64)
    for row, c in enumerate(have):
        if c < k:
            gen[row, c] = 1
        else:
            gen[row] = coding[c - k]
    inv = invert_matrix_w(gen, w).astype(np.uint64)
    out = np.zeros((len(erasures), k), dtype=np.uint64)
    for row, e in enumerate(erasures):
        if e < k:
            out[row] = inv[e]
        else:
            for j in range(k):
                acc = 0
                for t in range(k):
                    acc ^= f.mul(int(coding[e - k, t]), int(inv[t, j]))
                out[row, j] = acc
    return out.astype(f.dtype)


def reed_sol_van_matrix_w(k: int, m: int, w: int) -> np.ndarray:
    """The jerasure reed_sol_van construction over GF(2^w) (the w=8
    path in models/reed_solomon.py generalized to wide words): extended
    Vandermonde -> systematize by column ops -> scale coding columns so
    the first coding row is all ones."""
    f = Field(w)
    rows, cols = k + m, k
    v = np.zeros((rows, cols), dtype=np.uint64)
    v[0, 0] = 1
    if rows > 1:
        v[rows - 1, cols - 1] = 1
        for i in range(1, rows - 1):
            acc = 1
            for j in range(cols):
                v[i, j] = acc
                acc = f.mul(acc, i)
    # systematize (column ops)
    for i in range(k):
        if v[i, i] == 0:
            for j in range(i + 1, k):
                if v[i, j] != 0:
                    v[:, [i, j]] = v[:, [j, i]]
                    break
            else:
                raise ValueError("vandermonde not reducible")
        if v[i, i] != 1:
            c = f.inv(int(v[i, i]))
            for r in range(rows):
                v[r, i] = f.mul(int(v[r, i]), c)
        for j in range(k):
            if j != i and v[i, j] != 0:
                c = int(v[i, j])
                for r in range(rows):
                    v[r, j] ^= f.mul(int(v[r, i]), c)
    # scale coding columns so coding row 0 is all ones
    coding = v[k:]
    for j in range(k):
        if coding[0, j] not in (0, 1):
            c = f.inv(int(coding[0, j]))
            for r in range(m):
                coding[r, j] = f.mul(int(coding[r, j]), c)
    return coding.astype(f.dtype)

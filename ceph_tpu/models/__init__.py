"""Codec families — the framework's "model zoo".

Each module constructs the generator matrices / layouts for one erasure-code
family (the analog of the reference's plugin techniques under
src/erasure-code/): Reed-Solomon (Vandermonde, RAID6), Cauchy, LRC, SHEC,
CLAY.  Construction is host-side integer math; execution is
ceph_tpu.ops.gf on TPU.
"""

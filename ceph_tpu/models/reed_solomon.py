"""Generator-matrix constructions for Reed-Solomon and Cauchy codes.

These reproduce the *published* constructions used by the reference's default
plugin (jerasure's reed_sol.c / cauchy.c, per Plank's tutorial and its 2003
correction) so that encoded chunks are bit-identical with the reference for
technique=reed_sol_van / reed_sol_r6_op / cauchy_orig at w=8
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:200-204,
:252-255, :327).  Implementation is original, written from the algorithm (extended
Vandermonde -> systematic by column ops -> coding columns scaled so the
first coding row is all ones); the single Field-parameterized copy
lives in models/gf_wide.py and serves w in {8, 16, 32}.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops.gf import gf_div, gf_inv, gf_mul, gf_pow


def reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) coding matrix, jerasure reed_sol_vandermonde_coding_matrix(w=8).

    ONE implementation serves every word size: the Field-parameterized
    construction in models/gf_wide.py (this w=8 entry is what the
    golden-vector and independent-derivation tests pin, so wide words
    inherit the pinned algorithm rather than a drifting copy)."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    from ceph_tpu.models.gf_wide import reed_sol_van_matrix_w

    return reed_sol_van_matrix_w(k, m, 8)


def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """(2, k) RAID-6 matrix: row0 = ones (P), row1 = powers of 2 (Q).

    jerasure reed_sol_r6_coding_matrix; technique=reed_sol_r6_op.
    """
    m = np.zeros((2, k), dtype=np.uint8)
    m[0, :] = 1
    for j in range(k):
        m[1, j] = gf_pow(2, j)
    return m


def cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) Cauchy matrix: element (i, j) = 1 / (i XOR (m + j)) in GF(2^8).

    jerasure cauchy_original_coding_matrix; technique=cauchy_orig.
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    out = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[i, j] = gf_div(1, i ^ (m + j))
    return out


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """Improved Cauchy matrix (jerasure cauchy_good technique).

    jerasure's "good" variant rescales the original Cauchy matrix to minimize
    the bit-matrix one-count: divide column j by element (0, j) so row 0 is
    all ones, then for each subsequent row pick the row divisor yielding the
    fewest bits.  We implement the row-0 normalization and per-row best-divisor
    search over the row's own elements, the documented improvement strategy.
    """
    mat = cauchy_orig_matrix(k, m)
    for j in range(k):
        a = int(mat[0, j])
        if a != 1:
            mat[:, j] = gf_mul(mat[:, j], np.uint8(gf_inv(a)))
    from ceph_tpu.ops.gf import gf_const_to_bits

    def row_ones(row: np.ndarray) -> int:
        return int(sum(gf_const_to_bits(int(c)).sum() for c in row))

    for i in range(1, m):
        best = mat[i].copy()
        best_ones = row_ones(best)
        for div in set(int(c) for c in mat[i] if c > 1):
            cand = gf_mul(mat[i], np.uint8(gf_inv(div)))
            ones = row_ones(cand)
            if ones < best_ones:
                best, best_ones = cand, ones
        mat[i] = best
    return mat


def decode_matrix(coding: np.ndarray, k: int, erasures: list[int],
                  have: list[int]) -> np.ndarray:
    """Rows mapping the k chosen surviving chunks -> the erased chunks.

    Mirrors the role of jerasure_matrix_decode / isa_decode
    (/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:151-311): build the
    generator rows of the k surviving chunks, invert, then express every
    erased chunk (data via the inverse, coding via re-encoding) as a GF(2^8)
    combination of the survivors.

    coding: (m, k) coding matrix.  have: exactly k surviving chunk ids in the
    order their buffers will be stacked.  Returns (len(erasures), k).
    """
    from ceph_tpu.ops.gf import gf_invert_matrix, gf_matmul_ref

    assert len(have) == k
    gen = np.zeros((k, k), dtype=np.uint8)
    for row, c in enumerate(have):
        if c < k:
            gen[row, c] = 1
        else:
            gen[row] = coding[c - k]
    inv = gf_invert_matrix(gen)  # survivors -> original data
    out = np.zeros((len(erasures), k), dtype=np.uint8)
    for row, e in enumerate(erasures):
        if e < k:
            out[row] = inv[e]
        else:
            # erased coding chunk: coding_row @ inv
            out[row] = gf_matmul_ref(coding[e - k : e - k + 1], inv)[0]
    return out

"""Generator-matrix constructions for Reed-Solomon and Cauchy codes.

These reproduce the *published* constructions used by the reference's default
plugin (jerasure's reed_sol.c / cauchy.c, per Plank's tutorial and its 2003
correction) so that encoded chunks are bit-identical with the reference for
technique=reed_sol_van / reed_sol_r6_op / cauchy_orig at w=8
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:200-204,
:252-255, :327).  Implementation is original, written from the algorithm:

1. Extended (k+m) x k Vandermonde matrix over GF(2^8):
   row 0 = e_0, row (k+m-1) = e_{k-1}, row i = [1, i, i^2, ... i^(k-1)].
2. Elementary column operations turn the top k x k into the identity
   (column ops right-multiply the generator by an invertible matrix — the
   code stays MDS and becomes systematic).
3. Each column of the *coding rows only* is scaled so the first coding row
   becomes all ones (the XOR row; jerasure decodes with row_k_ones=1).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.ops.gf import gf_div, gf_inv, gf_mul, gf_pow


def extended_vandermonde(rows: int, cols: int) -> np.ndarray:
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[0, 0] = 1
    if rows == 1:
        return v
    v[rows - 1, cols - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            v[i, j] = acc
            acc = gf_mul(np.uint8(acc), np.uint8(i)).item()
    return v


def _systematize(v: np.ndarray, k: int) -> np.ndarray:
    """Column-reduce so the top k x k block is the identity."""
    v = v.copy()
    rows = v.shape[0]
    for i in range(k):
        if v[i, i] == 0:
            for j in range(i + 1, k):
                if v[i, j] != 0:
                    v[:, [i, j]] = v[:, [j, i]]
                    break
            else:
                raise ValueError("vandermonde not reducible")
        if v[i, i] != 1:
            inv = gf_inv(int(v[i, i]))
            v[:, i] = gf_mul(v[:, i], np.uint8(inv))
        for j in range(k):
            if j != i and v[i, j] != 0:
                c = np.uint8(v[i, j])
                v[:, j] ^= gf_mul(v[:, i], c)
    return v


def reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) coding matrix, jerasure reed_sol_vandermonde_coding_matrix(w=8)."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    dist = _systematize(extended_vandermonde(k + m, k), k)
    coding = dist[k:, :].copy()
    # Scale coding-row columns so the first coding row is all ones.  Only the
    # coding rows are touched, so the systematic identity above is preserved
    # and every k x k submatrix determinant changes by a nonzero factor (MDS
    # preserved).
    for j in range(k):
        a = int(coding[0, j])
        if a == 0:
            raise ValueError("MDS violation in vandermonde construction")
        if a != 1:
            inv = np.uint8(gf_inv(a))
            coding[:, j] = gf_mul(coding[:, j], inv)
    return coding


def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """(2, k) RAID-6 matrix: row0 = ones (P), row1 = powers of 2 (Q).

    jerasure reed_sol_r6_coding_matrix; technique=reed_sol_r6_op.
    """
    m = np.zeros((2, k), dtype=np.uint8)
    m[0, :] = 1
    for j in range(k):
        m[1, j] = gf_pow(2, j)
    return m


def cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) Cauchy matrix: element (i, j) = 1 / (i XOR (m + j)) in GF(2^8).

    jerasure cauchy_original_coding_matrix; technique=cauchy_orig.
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    out = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[i, j] = gf_div(1, i ^ (m + j))
    return out


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """Improved Cauchy matrix (jerasure cauchy_good technique).

    jerasure's "good" variant rescales the original Cauchy matrix to minimize
    the bit-matrix one-count: divide column j by element (0, j) so row 0 is
    all ones, then for each subsequent row pick the row divisor yielding the
    fewest bits.  We implement the row-0 normalization and per-row best-divisor
    search over the row's own elements, the documented improvement strategy.
    """
    mat = cauchy_orig_matrix(k, m)
    for j in range(k):
        a = int(mat[0, j])
        if a != 1:
            mat[:, j] = gf_mul(mat[:, j], np.uint8(gf_inv(a)))
    from ceph_tpu.ops.gf import gf_const_to_bits

    def row_ones(row: np.ndarray) -> int:
        return int(sum(gf_const_to_bits(int(c)).sum() for c in row))

    for i in range(1, m):
        best = mat[i].copy()
        best_ones = row_ones(best)
        for div in set(int(c) for c in mat[i] if c > 1):
            cand = gf_mul(mat[i], np.uint8(gf_inv(div)))
            ones = row_ones(cand)
            if ones < best_ones:
                best, best_ones = cand, ones
        mat[i] = best
    return mat


def decode_matrix(coding: np.ndarray, k: int, erasures: list[int],
                  have: list[int]) -> np.ndarray:
    """Rows mapping the k chosen surviving chunks -> the erased chunks.

    Mirrors the role of jerasure_matrix_decode / isa_decode
    (/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:151-311): build the
    generator rows of the k surviving chunks, invert, then express every
    erased chunk (data via the inverse, coding via re-encoding) as a GF(2^8)
    combination of the survivors.

    coding: (m, k) coding matrix.  have: exactly k surviving chunk ids in the
    order their buffers will be stacked.  Returns (len(erasures), k).
    """
    from ceph_tpu.ops.gf import gf_invert_matrix, gf_matmul_ref

    assert len(have) == k
    gen = np.zeros((k, k), dtype=np.uint8)
    for row, c in enumerate(have):
        if c < k:
            gen[row, c] = 1
        else:
            gen[row] = coding[c - k]
    inv = gf_invert_matrix(gen)  # survivors -> original data
    out = np.zeros((len(erasures), k), dtype=np.uint8)
    for row, e in enumerate(erasures):
        if e < k:
            out[row] = inv[e]
        else:
            # erased coding chunk: coding_row @ inv
            out[row] = gf_matmul_ref(coding[e - k : e - k + 1], inv)[0]
    return out

"""Wire frame discipline.

Reference parity: the msgr2 frame format
(/root/reference/src/msg/async/frames_v2.cc:44-77) — a fixed preamble
carrying tag + segment layout protected by its own crc32c, segments each
followed by a crc32c epilogue.  This framework uses one segment per frame
(payloads are single encoded messages; large data rides inside them), so
the format collapses to:

    preamble (20 bytes):
        magic   u32  = 0xCE9F0205
        tag     u16  (message type)
        flags   u16
        seq     u64  (per-connection frame counter)
        len     u32  (payload length)
    preamble_crc u32  crc32c(-1) over the 20 preamble bytes
    payload      len bytes
    payload_crc  u32  crc32c(-1) over payload

Any crc or magic mismatch is a protocol error: the connection is dropped
(the reference resets the session on a bad frame; lossless peers
reconnect and replay, lossy clients resend at the Objecter layer).

cephx-lite signing (ceph_tpu.common.auth): when a secret is configured,
FLAG_SIGNED is set and an 8-byte truncated HMAC-SHA256 over
preamble+payload follows the payload crc (CephxSessionHandler
sign_message role); a receiver with a secret drops unsigned or
mis-signed frames.
"""

from __future__ import annotations

import struct
from typing import Tuple

from ceph_tpu.ops.checksum import crc32c

MAGIC = 0xCE9F0205
PREAMBLE = struct.Struct("<IHHQI")
CRC = struct.Struct("<I")
FLAG_SIGNED = 0x0001
FLAG_SECURE = 0x0002      # payload AEAD-sealed under the session key
FLAG_COMPRESSED = 0x0004  # payload compressed with the negotiated codec


class FrameError(Exception):
    """Bad magic or crc: the connection must be dropped."""


def encode_frame_parts(tag: int, seq: int, payload: bytes,
                       flags: int = 0, key=None,
                       role: bytes = b"") -> list:
    """Frame as (head, payload, tail): the payload rides as-is —
    zero-copy at this layer; for multi-MiB data frames the join it
    avoids is a full extra pass over the object.

    key: the signing key BYTES for this frame (a cephx session key, or
    the static active key during the hello handshake); None = unsigned.

    role: the sender's direction byte (b"c"/b"s"), BOUND INTO the
    signature so a frame recorded in one direction can never verify in
    the other — without it, symmetric per-direction seq counters let
    an active MITM reflect a captured frame back to its sender (the
    reference binds direction via distinct c->s / s->c nonce halves,
    msg/async/crypto_onwire.cc:34-46)."""
    if key is not None:
        flags |= FLAG_SIGNED
    pre = PREAMBLE.pack(MAGIC, tag, flags, seq, len(payload))
    head = pre + CRC.pack(crc32c(0xFFFFFFFF, pre))
    tail = CRC.pack(crc32c(0xFFFFFFFF, payload))
    if key is not None:
        from ceph_tpu.common import auth

        tail += auth.sign(key, role, pre, payload)
    return [head, payload, tail]


def encode_frame(tag: int, seq: int, payload: bytes,
                 flags: int = 0, key=None, role: bytes = b"") -> bytes:
    """Whole-frame convenience form (tests, sniffers).  The product
    path writes the parts straight to the socket
    (Connection._send_signed) and never pays this join."""
    head, body, tail = encode_frame_parts(tag, seq, payload,
                                          flags=flags, key=key,
                                          role=role)
    out = bytearray(head)
    out += body
    out += tail
    # deliberate copy: this convenience form exists to hand tests one
    # contiguous frame  # lint: disable=hot-path-copy
    return bytes(out)


def check_signature(key, flags: int, pre_buf: bytes,
                    payload: bytes, sig: bytes,
                    role: bytes = b"") -> None:
    """Receiver-side auth adjudication; FrameError drops the conn.
    role: the SENDER's direction byte (the receiver's rx role)."""
    from ceph_tpu.common import auth

    if key is None:
        return
    if not flags & FLAG_SIGNED:
        raise FrameError("unsigned frame from peer (auth required)")
    # memoryview slice: the HMAC walks the view; no preamble copy
    if not auth.verify(key, sig, role,
                       memoryview(pre_buf)[:PREAMBLE.size], payload):
        raise FrameError("frame signature mismatch (wrong key?)")


def decode_preamble(buf: bytes) -> Tuple[int, int, int, int]:
    """24 preamble+crc bytes -> (tag, flags, seq, payload_len)."""
    magic, tag, flags, seq, length = PREAMBLE.unpack_from(buf)
    (crc,) = CRC.unpack_from(buf, PREAMBLE.size)
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic:#x}")
    # memoryview slice: crc32c walks the view; no preamble copy
    if crc32c(0xFFFFFFFF, memoryview(buf)[:PREAMBLE.size]) != crc:
        raise FrameError("preamble crc mismatch")
    return tag, flags, seq, length


def check_payload(payload: bytes, crc_bytes: bytes) -> None:
    (crc,) = CRC.unpack(crc_bytes)
    if crc32c(0xFFFFFFFF, payload) != crc:
        raise FrameError("payload crc mismatch")


PREAMBLE_WIRE_LEN = PREAMBLE.size + CRC.size  # 24

"""Typed wire messages.

Reference parity: src/messages/ (MOSDOp.h, MOSDPing.h, MOSDFailure.h,
MOSDMap.h, MMonCommand.h, MOSDECSubOpWrite.h, ...) — each message is a
versioned struct carried in a tagged frame.  The reference dispatches on
the header type id; here every class has a TAG and a registry maps tag ->
class at decode time.  Payloads use the versioned encoder
(ceph_tpu.common.encoding), so messages can grow fields without breaking
older peers (DECODE_FINISH skips unknown tails).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.osd.osdmap import PgId

_REGISTRY: Dict[int, type] = {}


def register(cls):
    assert cls.TAG not in _REGISTRY, f"duplicate tag {cls.TAG}"
    _REGISTRY[cls.TAG] = cls
    return cls


class Message:
    TAG = 0
    VERSION = 1
    COMPAT = 1

    def encode(self) -> bytes:
        enc = Encoder()
        enc.start(self.VERSION, self.COMPAT)
        self.encode_payload(enc)
        enc.finish()
        return enc.to_bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Message":
        dec = Decoder(data)
        dec.start(cls.VERSION)
        msg = cls.decode_payload(dec)
        dec.finish()
        return msg

    def encode_payload(self, enc: Encoder) -> None:
        raise NotImplementedError

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "Message":
        raise NotImplementedError

    def __repr__(self) -> str:
        fields = ", ".join(f"{k}={v!r}" for k, v in vars(self).items()
                           if not k.startswith("_") and k != "data")
        return f"{type(self).__name__}({fields})"


def decode_message(tag: int, payload: bytes) -> Message:
    cls = _REGISTRY.get(tag)
    if cls is None:
        raise ValueError(f"unknown message tag {tag}")
    return cls.decode(payload)


def _enc_pg(enc: Encoder, pg: PgId) -> None:
    enc.s64(pg.pool)
    enc.u32(pg.ps)


def _dec_pg(dec: Decoder) -> PgId:
    return PgId(dec.s64(), dec.u32())


# -- session / control ------------------------------------------------------


@register
class MHello(Message):
    """Connection handshake: who is on the other end (entity_addr_t
    role).  v2 appends the cephx session-negotiation fields: a fresh
    nonce, the key id the hello is signed with, and an optional
    mon-granted ticket (CephxSessionHandler / msgr2 auth frames role).
    v3 appends the sender's accepted compression methods (csv, in
    preference order — the frames_v2 compression negotiation role,
    /root/reference/src/msg/async/frames_v2.cc).  v4 appends the
    sender's AEAD capability so secure-mode peers can negotiate the
    sealing mode instead of each side guessing from its OWN toolchain
    (the crypto_onwire mode-selection role): absent = unknown
    (pre-v4 peer), True/False = advertised."""

    TAG = 1
    VERSION = 4
    COMPAT = 1

    def __init__(self, entity_name: str, addr: str,
                 nonce: bytes = b"", kid: int = 0,
                 ticket: bytes = b"", compression: str = "",
                 aead: Optional[bool] = None):
        self.entity_name = entity_name
        self.addr = addr
        self.nonce = nonce
        self.kid = kid
        self.ticket = ticket
        # set only when non-empty so dumps of pre-v3 blobs (and the
        # archived corpus) are unchanged
        if compression:
            self.compression = compression
        if aead is not None:
            self.aead = aead

    def encode_payload(self, enc: Encoder) -> None:
        enc.string(self.entity_name)
        enc.string(self.addr)
        enc.bytes(self.nonce)
        enc.s32(self.kid)
        enc.bytes(self.ticket)
        enc.string(getattr(self, "compression", ""))
        enc.bool(getattr(self, "aead", False))

    @classmethod
    def decode(cls, data: bytes) -> "MHello":
        dec = Decoder(data)
        struct_v = dec.start(cls.VERSION)
        msg = cls(dec.string(), dec.string())
        if struct_v >= 2:
            msg.nonce = dec.bytes()
            msg.kid = dec.s32()
            msg.ticket = dec.bytes()
        if struct_v >= 3:
            comp = dec.string()
            if comp:
                msg.compression = comp
        if struct_v >= 4:
            msg.aead = dec.bool()
        dec.finish()
        return msg


PING = 0
PING_REPLY = 1


@register
class MPing(Message):
    """MOSDPing role: OSD<->OSD heartbeat (OSD.cc:5235 handle_osd_ping)."""

    TAG = 2

    def __init__(self, kind: int, stamp: float, epoch: int = 0,
                 from_osd: int = -1):
        self.kind = kind
        self.stamp = stamp
        self.epoch = epoch
        self.from_osd = from_osd

    def encode_payload(self, enc: Encoder) -> None:
        enc.u8(self.kind)
        enc.f64(self.stamp)
        enc.u32(self.epoch)
        enc.s32(self.from_osd)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MPing":
        return cls(dec.u8(), dec.f64(), dec.u32(), dec.s32())


@register
class MOSDBoot(Message):
    """OSD -> mon: I'm up at this address (MOSDBoot role)."""

    TAG = 3

    def __init__(self, osd: int, addr: str, boot_epoch: int = 0):
        self.osd = osd
        self.addr = addr
        self.boot_epoch = boot_epoch

    def encode_payload(self, enc: Encoder) -> None:
        enc.s32(self.osd)
        enc.string(self.addr)
        enc.u32(self.boot_epoch)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDBoot":
        return cls(dec.s32(), dec.string(), dec.u32())


@register
class MOSDFailure(Message):
    """OSD -> mon failure report (MOSDFailure; OSDMonitor::prepare_failure)."""

    TAG = 4

    def __init__(self, target_osd: int, reporter: int, failed_for: float,
                 epoch: int):
        self.target_osd = target_osd
        self.reporter = reporter
        self.failed_for = failed_for
        self.epoch = epoch

    def encode_payload(self, enc: Encoder) -> None:
        enc.s32(self.target_osd)
        enc.s32(self.reporter)
        enc.f64(self.failed_for)
        enc.u32(self.epoch)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDFailure":
        return cls(dec.s32(), dec.s32(), dec.f64(), dec.u32())


@register
class MGetMap(Message):
    """Client/OSD -> mon: send me the OSDMap (subscribe semantics)."""

    TAG = 5

    def __init__(self, since_epoch: int = 0, subscribe: bool = True):
        self.since_epoch = since_epoch
        self.subscribe = subscribe

    def encode_payload(self, enc: Encoder) -> None:
        enc.u32(self.since_epoch)
        enc.bool(self.subscribe)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MGetMap":
        return cls(dec.u32(), dec.bool())


@register
class MOSDMapMsg(Message):
    """Mon -> peer: full map and/or incrementals (MOSDMap role)."""

    TAG = 6

    def __init__(self, epoch: int, full_map: Optional[bytes] = None,
                 incrementals: Optional[List[bytes]] = None,
                 gap_unfillable: bool = False):
        self.epoch = epoch
        self.full_map = full_map
        self.incrementals = incrementals or []
        # mon could not supply the contiguous incremental range (log
        # trimmed): the receiver must adopt the full map despite the
        # epoch gap instead of re-requesting forever
        self.gap_unfillable = gap_unfillable

    def encode_payload(self, enc: Encoder) -> None:
        enc.u32(self.epoch)
        enc.optional(self.full_map, Encoder.bytes)
        enc.list(self.incrementals, Encoder.bytes)
        enc.bool(self.gap_unfillable)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDMapMsg":
        return cls(dec.u32(), dec.optional(Decoder.bytes),
                   dec.list(Decoder.bytes), dec.bool())


@register
class MMonCommand(Message):
    """JSON command to the mon (MMonCommand / `ceph` CLI role)."""

    TAG = 7

    def __init__(self, tid: int, cmd: Dict[str, Any]):
        self.tid = tid
        self.cmd = cmd

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.string(json.dumps(self.cmd))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MMonCommand":
        return cls(dec.u64(), json.loads(dec.string()))


@register
class MMonCommandReply(Message):
    TAG = 8

    def __init__(self, tid: int, rc: int, out: Dict[str, Any]):
        self.tid = tid
        self.rc = rc
        self.out = out

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.string(json.dumps(self.out))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MMonCommandReply":
        return cls(dec.u64(), dec.s32(), json.loads(dec.string()))


# -- client data path -------------------------------------------------------


class OSDOp:
    """One sub-operation of an MOSDOp (OSDOp / ceph_osd_op role)."""

    def __init__(self, op: str, offset: int = 0, length: int = 0,
                 data: bytes = b"", args: Optional[Dict[str, Any]] = None):
        self.op = op
        self.offset = offset
        self.length = length
        self.data = data
        self.args = args or {}

    def encode(self, enc: Encoder) -> None:
        enc.string(self.op)
        enc.u64(self.offset)
        enc.u64(self.length)
        enc.bytes(self.data)
        enc.string(json.dumps(self.args))

    @classmethod
    def decode(cls, dec: Decoder) -> "OSDOp":
        return cls(dec.string(), dec.u64(), dec.u64(),
                   dec.bytes_view(), json.loads(dec.string()))

    def __repr__(self) -> str:
        return (f"OSDOp({self.op!r}, off={self.offset}, "
                f"len={self.length or len(self.data)})")


@register
class MOSDOp(Message):
    """Client -> primary OSD op (MOSDOp.h role)."""

    TAG = 9

    def __init__(self, tid: int, client: str, pg: PgId, oid: str,
                 ops: List[OSDOp], epoch: int,
                 snapc_seq: int = 0,
                 snapc_snaps: Optional[List[int]] = None,
                 snap_id: int = 0,
                 tenant: str = "",
                 qos_delta: int = 1,
                 qos_rho: int = 1):
        self.tid = tid
        self.client = client
        self.pg = pg
        self.oid = oid
        self.ops = ops
        self.epoch = epoch
        # write-time snap context (SnapContext: seq + live snap ids,
        # newest first) and read-time snap id (0 = head)
        self.snapc_seq = snapc_seq
        self.snapc_snaps = snapc_snaps or []
        self.snap_id = snap_id
        # QoS tenant identity ("" = untagged): the OSD schedules the
        # op under the per-tenant mClock class `client.<tenant>` and
        # runs it through the admission gate
        self.tenant = tenant
        # dmClock piggyback (delta/rho): completions this tenant saw
        # at OTHER OSDs since its last op on the target (plus one) —
        # all-phase and reservation-phase respectively.  The target's
        # mClock tags advance by delta x cost, making per-tenant
        # reservation/limit hold cluster-wide.  1/1 = local mClock.
        self.qos_delta = max(int(qos_delta), 1)
        self.qos_rho = max(int(qos_rho), 1)
        # blkin-role trace context: (trace_id, parent span id) or None
        self.trace: Optional[tuple] = None

    # v2 appends the snap context + read snap; v3 the trace context;
    # v4 the QoS tenant; v5 the dmClock delta/rho piggyback.  COMPAT
    # stays 1 so a v1 frame still decodes with defaults
    VERSION = 5
    COMPAT = 1

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.string(self.client)
        _enc_pg(enc, self.pg)
        enc.string(self.oid)
        enc.list(self.ops, lambda e, op: op.encode(e))
        enc.u32(self.epoch)
        enc.u64(self.snapc_seq)
        enc.list(self.snapc_snaps, Encoder.u64)
        enc.u64(self.snap_id)
        enc.optional(self.trace,
                     lambda e, v: (e.u64(v[0]), e.u64(v[1])))
        enc.string(self.tenant)
        enc.u32(self.qos_delta)
        enc.u32(self.qos_rho)

    @classmethod
    def decode(cls, data: bytes) -> "MOSDOp":
        dec = Decoder(data)
        struct_v = dec.start(cls.VERSION)
        msg = cls(dec.u64(), dec.string(), _dec_pg(dec), dec.string(),
                  dec.list(OSDOp.decode), dec.u32())
        if struct_v >= 2:
            msg.snapc_seq = dec.u64()
            msg.snapc_snaps = dec.list(Decoder.u64)
            msg.snap_id = dec.u64()
        if struct_v >= 3:
            msg.trace = dec.optional(lambda d: (d.u64(), d.u64()))
        if struct_v >= 4:
            msg.tenant = dec.string()
        if struct_v >= 5:
            msg.qos_delta = max(dec.u32(), 1)
            msg.qos_rho = max(dec.u32(), 1)
        dec.finish()
        return msg


@register
class MOSDOpReply(Message):
    TAG = 10
    # v2 appends the dmClock grant phase (the rho piggyback).  COMPAT
    # stays 1 so archived/old-peer frames decode with the default
    VERSION = 2
    COMPAT = 1

    def __init__(self, tid: int, rc: int, data: bytes = b"",
                 out: Optional[Dict[str, Any]] = None,
                 replay_epoch: int = 0,
                 qos_phase: str = ""):
        self.tid = tid
        self.rc = rc
        self.data = data
        self.out = out or {}
        # >0: client should wait for this map epoch and resend (the
        # ENOENT-on-wrong-primary / EAGAIN resend discipline)
        self.replay_epoch = replay_epoch
        # dmClock phase the op's scheduler grant won ("reservation" /
        # "priority", "" when unscheduled): the client ServiceTracker
        # counts reservation-phase completions into rho
        self.qos_phase = qos_phase

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.bytes(self.data)
        enc.string(json.dumps(self.out))
        enc.u32(self.replay_epoch)
        enc.string(self.qos_phase)

    @classmethod
    def decode(cls, data: bytes) -> "MOSDOpReply":
        dec = Decoder(data)
        struct_v = dec.start(cls.VERSION)
        msg = cls(dec.u64(), dec.s32(), dec.bytes(),
                  json.loads(dec.string()), dec.u32())
        if struct_v >= 2:
            msg.qos_phase = dec.string()
        dec.finish()
        return msg


# -- primary -> shard sub-ops ----------------------------------------------


class ShardOp:
    """One ObjectStore-level mutation on a shard (ECSubWrite payload item)."""

    def __init__(self, op: str, offset: int = 0, data: bytes = b"",
                 name: str = "", value: bytes = b"", size: int = 0):
        # write | truncate | remove | setattr | rmattr | create |
        # clone | omap_set | omap_rm  (omap payloads ride in `data`
        # as an encoded map/list)
        self.op = op
        self.offset = offset
        self.data = data
        self.name = name
        self.value = value
        self.size = size

    def encode(self, enc: Encoder) -> None:
        enc.string(self.op)
        enc.u64(self.offset)
        enc.bytes(self.data)
        enc.string(self.name)
        enc.bytes(self.value)
        enc.u64(self.size)

    @classmethod
    def decode(cls, dec: Decoder) -> "ShardOp":
        return cls(dec.string(), dec.u64(), dec.bytes_view(),
                   dec.string(), dec.bytes(), dec.u64())


@register
class MOSDSubWrite(Message):
    """Primary -> shard write (MOSDECSubOpWrite / MOSDRepOp role).

    Carries the shard transaction plus the pg log entry for that write so
    replicas journal the op (PGLog) before applying it.
    """

    TAG = 11
    VERSION = 3  # v2 appends guard (recovery-push causality token);
    #              v3 the blkin-role trace context
    COMPAT = 1   # v1 peers decode head fields; tails default to None

    def __init__(self, tid: int, pg: PgId, shard: int, oid: str,
                 ops: List[ShardOp], epoch: int,
                 log_entry: Optional[Dict[str, Any]] = None,
                 from_osd: int = -1,
                 guard: Optional[tuple] = None):
        self.tid = tid
        self.pg = pg
        self.shard = shard
        self.oid = oid
        self.ops = ops
        self.epoch = epoch
        self.log_entry = log_entry
        self.from_osd = from_osd
        # guard: for recovery/repair sub-writes (log_entry=None), the
        # newest object version the primary's plan OBSERVED when it
        # adjudicated.  The replica refuses a below-floor install whose
        # guard predates its current state — that is exactly a stale
        # (timed-out, still-in-flight) push overtaken by a newer write.
        self.guard = tuple(guard) if guard is not None else None
        # blkin-role trace context: (trace_id, parent span id) or None
        self.trace: Optional[tuple] = None

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        _enc_pg(enc, self.pg)
        enc.s32(self.shard)
        enc.string(self.oid)
        enc.list(self.ops, lambda e, op: op.encode(e))
        enc.u32(self.epoch)
        enc.optional(self.log_entry,
                     lambda e, v: e.string(json.dumps(v)))
        enc.s32(self.from_osd)
        enc.optional(self.guard,
                     lambda e, v: (e.u64(v[0]), e.u64(v[1])))
        enc.optional(self.trace,
                     lambda e, v: (e.u64(v[0]), e.u64(v[1])))

    @classmethod
    def decode(cls, data: bytes) -> "MOSDSubWrite":
        dec = Decoder(data)
        struct_v = dec.start(cls.VERSION)
        msg = cls(dec.u64(), _dec_pg(dec), dec.s32(), dec.string(),
                  dec.list(ShardOp.decode), dec.u32(),
                  dec.optional(lambda d: json.loads(d.string())),
                  dec.s32())
        if struct_v >= 2:
            msg.guard = dec.optional(lambda d: (d.u64(), d.u64()))
        if struct_v >= 3:
            msg.trace = dec.optional(lambda d: (d.u64(), d.u64()))
        dec.finish()
        return msg


@register
class MOSDSubWriteReply(Message):
    TAG = 12

    def __init__(self, tid: int, rc: int, shard: int = -1):
        self.tid = tid
        self.rc = rc
        self.shard = shard

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.s32(self.shard)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDSubWriteReply":
        return cls(dec.u64(), dec.s32(), dec.s32())


@register
class MOSDSubRead(Message):
    """Primary -> shard read (MOSDECSubOpRead role)."""

    TAG = 13

    VERSION = 5  # v2 appends want_omap; v3 appends record (hit-set);
    #              v4 the blkin-role trace context; v5 the repair
    #              sub-chunk fraction spec (regenerating-code reads)
    COMPAT = 1

    def __init__(self, tid: int, pg: PgId, shard: int, oid: str,
                 offset: int = 0, length: int = 0,
                 want_attrs: bool = True, want_omap: bool = False,
                 record: bool = False):
        self.tid = tid
        self.pg = pg
        self.shard = shard
        self.oid = oid
        self.offset = offset
        self.length = length
        self.want_attrs = want_attrs
        self.want_omap = want_omap
        # client-read provenance: only these sub-reads feed the
        # replica's hot-set tracking (scrub/recovery/stat probes
        # would drown the skew signal)
        self.record = record
        # blkin-role trace context: (trace_id, parent span id) or None
        self.trace: Optional[tuple] = None
        # repair-fragment read: (lost chunk id, expected sub-chunk
        # count alpha) or None.  When set the replica projects its
        # stored chunk against the codec's repair vector and ships the
        # beta = chunk/alpha byte fragment instead of the full chunk;
        # an alpha mismatch (profile drift) answers EOPNOTSUPP so the
        # primary falls back to the classic k-read path
        self.repair: Optional[tuple] = None

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        _enc_pg(enc, self.pg)
        enc.s32(self.shard)
        enc.string(self.oid)
        enc.u64(self.offset)
        enc.u64(self.length)
        enc.bool(self.want_attrs)
        enc.bool(self.want_omap)
        enc.bool(self.record)
        enc.optional(self.trace,
                     lambda e, v: (e.u64(v[0]), e.u64(v[1])))
        enc.optional(self.repair,
                     lambda e, v: (e.s32(v[0]), e.u32(v[1])))

    @classmethod
    def decode(cls, data: bytes) -> "MOSDSubRead":
        dec = Decoder(data)
        struct_v = dec.start(cls.VERSION)
        msg = cls(dec.u64(), _dec_pg(dec), dec.s32(), dec.string(),
                  dec.u64(), dec.u64(), dec.bool())
        if struct_v >= 2:
            msg.want_omap = dec.bool()
        if struct_v >= 3:
            msg.record = dec.bool()
        if struct_v >= 4:
            msg.trace = dec.optional(lambda d: (d.u64(), d.u64()))
        if struct_v >= 5:
            msg.repair = dec.optional(lambda d: (d.s32(), d.u32()))
        dec.finish()
        return msg


@register
class MOSDSubReadReply(Message):
    TAG = 14

    VERSION = 2  # v2 appends the omap payload
    COMPAT = 1

    def __init__(self, tid: int, rc: int, data: bytes = b"",
                 attrs: Optional[Dict[str, bytes]] = None,
                 shard: int = -1,
                 omap: Optional[Dict[str, bytes]] = None):
        self.tid = tid
        self.rc = rc
        self.data = data
        self.attrs = attrs or {}
        self.shard = shard
        self.omap = omap or {}

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.bytes(self.data)
        enc.map(self.attrs, Encoder.string, Encoder.bytes)
        enc.s32(self.shard)
        enc.map(self.omap, Encoder.string, Encoder.bytes)

    @classmethod
    def decode(cls, data: bytes) -> "MOSDSubReadReply":
        dec = Decoder(data)
        struct_v = dec.start(cls.VERSION)
        # bulk shard payload stays a VIEW of the frame buffer: the
        # primary's gather/decode path slices and CRCs it in place
        # (k shards per EC read — the copy here was per-shard)
        msg = cls(dec.u64(), dec.s32(), dec.bytes_view(),
                  dec.map(Decoder.string, Decoder.bytes), dec.s32())
        if struct_v >= 2:
            msg.omap = dec.map(Decoder.string, Decoder.bytes)
        dec.finish()
        return msg


# -- coded compute (scan/aggregate/score pushdown) --------------------------


@register
class MOSDCompute(Message):
    """Client -> primary: run a registered compute kernel over MANY
    objects' shards where they live (the coded-compute scan op,
    ceph_tpu/compute).  SET-valued by design — one request names a
    kernel + many oids, so a 10k-object scan is a handful of frames,
    not 10k round trips.  cls-exec style, but the primary fans
    sub-compute ops to the OSDs holding each object's shards and
    completes each object from the FIRST k shard-results."""

    TAG = 32
    VERSION = 1
    COMPAT = 1

    def __init__(self, tid: int, client: str, pool: int,
                 oids: List[str], kernel: str, args: str = "",
                 epoch: int = 0, tenant: str = ""):
        self.tid = tid
        self.client = client
        self.pool = pool
        self.oids = oids
        self.kernel = kernel
        self.args = args          # JSON text (kernel-specific)
        self.epoch = epoch
        # QoS tenant identity ("" = untagged): compute ops schedule
        # under the dedicated `compute` mClock class AND pass the
        # tenant admission gate, so scans cannot starve client I/O
        self.tenant = tenant

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.string(self.client)
        enc.s64(self.pool)
        enc.list(self.oids, Encoder.string)
        enc.string(self.kernel)
        enc.string(self.args)
        enc.u32(self.epoch)
        enc.string(self.tenant)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDCompute":
        return cls(dec.u64(), dec.string(), dec.s64(),
                   dec.list(Decoder.string), dec.string(),
                   dec.string(), dec.u32(), dec.string())


@register
class MOSDComputeReply(Message):
    """Primary -> client: per-oid (rc, result bytes) + a summary map
    (pushdown/fallback counts, result bytes moved) for observability.
    Only KERNEL RESULTS ride here — never object payloads."""

    TAG = 33
    VERSION = 1
    COMPAT = 1

    def __init__(self, tid: int, rc: int,
                 results: Optional[Dict[str, Tuple[int, bytes]]] = None,
                 out: Optional[Dict[str, Any]] = None,
                 replay_epoch: int = 0):
        self.tid = tid
        self.rc = rc
        self.results = results or {}
        self.out = out or {}
        self.replay_epoch = replay_epoch

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.map(self.results, Encoder.string,
                lambda e, v: (e.s32(v[0]), e.bytes(v[1])))
        enc.string(json.dumps(self.out))
        enc.u32(self.replay_epoch)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDComputeReply":
        return cls(dec.u64(), dec.s32(),
                   dec.map(Decoder.string,
                           lambda d: (d.s32(), d.bytes())),
                   json.loads(dec.string()), dec.u32())


@register
class MOSDSubCompute(Message):
    """Primary -> shard OSD: evaluate the kernel over THIS OSD's
    shards of a wave of objects (MOSDECSubOpRead-shaped, but the
    reply carries R-byte kernel results, not chunk payloads — the
    payload bytes never cross the wire).  items are
    (pool, ps, shard, oid) tuples; the receiver batches every local
    shard of the wave into ONE plan-cached device dispatch."""

    TAG = 34
    VERSION = 1
    COMPAT = 1

    def __init__(self, tid: int, kernel: str, args: str,
                 items: List[Tuple[int, int, int, str]],
                 epoch: int = 0):
        self.tid = tid
        self.kernel = kernel
        self.args = args
        self.items = [tuple(it) for it in items]
        self.epoch = epoch
        # blkin-role trace context: (trace_id, parent span id) or None
        self.trace: Optional[tuple] = None

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.string(self.kernel)
        enc.string(self.args)
        enc.list(self.items,
                 lambda e, it: (e.s64(it[0]), e.u32(it[1]),
                                e.s32(it[2]), e.string(it[3])))
        enc.u32(self.epoch)
        enc.optional(self.trace,
                     lambda e, v: (e.u64(v[0]), e.u64(v[1])))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDSubCompute":
        msg = cls(dec.u64(), dec.string(), dec.string(),
                  dec.list(lambda d: (d.s64(), d.u32(), d.s32(),
                                      d.string())),
                  dec.u32())
        msg.trace = dec.optional(lambda d: (d.u64(), d.u64()))
        return msg


@register
class MOSDSubComputeReply(Message):
    """Shard OSD -> primary: per-item (rc, object-info version,
    result bytes), aligned with the request's item order.  The
    version rides so the primary can complete each object from k
    SAME-VERSION shard-results (the consistency story of the
    hedged first-k read, applied to computation)."""

    TAG = 35
    VERSION = 1
    COMPAT = 1

    def __init__(self, tid: int, rc: int,
                 results: Optional[List[Tuple[int, str, bytes]]] = None):
        self.tid = tid
        self.rc = rc
        self.results = [tuple(r) for r in (results or [])]

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.list(self.results,
                 lambda e, r: (e.s32(r[0]), e.string(r[1]),
                               e.bytes(r[2])))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDSubComputeReply":
        return cls(dec.u64(), dec.s32(),
                   dec.list(lambda d: (d.s32(), d.string(),
                                       d.bytes_view())))


# -- peering ----------------------------------------------------------------


@register
class MPGQuery(Message):
    """Primary -> replica: send me your pg info + log (GetLog/GetInfo).

    v2 appends an optional explicit shard: a split child's primary
    sweeps NON-acting OSDs for stray shard state, and a stray cannot
    derive its shard from an acting set it is not part of."""

    TAG = 15
    VERSION = 2
    COMPAT = 1

    def __init__(self, tid: int, pg: PgId, epoch: int, from_osd: int,
                 shard: Optional[int] = None):
        self.tid = tid
        self.pg = pg
        self.epoch = epoch
        self.from_osd = from_osd
        self.shard = shard

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        _enc_pg(enc, self.pg)
        enc.u32(self.epoch)
        enc.s32(self.from_osd)
        enc.optional(self.shard, Encoder.s32)

    @classmethod
    def decode(cls, data: bytes) -> "MPGQuery":
        dec = Decoder(data)
        struct_v = dec.start(cls.VERSION)
        msg = cls(dec.u64(), _dec_pg(dec), dec.u32(), dec.s32())
        if struct_v >= 2:
            msg.shard = dec.optional(Decoder.s32)
        dec.finish()
        return msg


@register
class MPGLogMsg(Message):
    """Replica -> primary: pg info + full log (MOSDPGLog role)."""

    TAG = 16

    def __init__(self, tid: int, pg: PgId, shard: int,
                 info: Dict[str, Any], entries: List[Dict[str, Any]],
                 epoch: int = 0, from_osd: int = -1,
                 is_reply: bool = False):
        self.tid = tid
        self.pg = pg
        self.shard = shard
        self.info = info
        self.entries = entries
        self.epoch = epoch
        self.from_osd = from_osd
        # pushes (primary -> peer, authoritative log) and replies (peer ->
        # primary) share this struct; the flag keeps them apart — tids
        # alone cannot, since each daemon numbers its own requests
        self.is_reply = is_reply

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        _enc_pg(enc, self.pg)
        enc.s32(self.shard)
        enc.string(json.dumps(self.info))
        enc.list(self.entries, lambda e, v: e.string(json.dumps(v)))
        enc.u32(self.epoch)
        enc.s32(self.from_osd)
        enc.bool(self.is_reply)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MPGLogMsg":
        return cls(dec.u64(), _dec_pg(dec), dec.s32(),
                   json.loads(dec.string()),
                   dec.list(lambda d: json.loads(d.string())),
                   dec.u32(), dec.s32(), dec.bool())


@register
class MWatchNotify(Message):
    """Primary -> watcher: a notify fired on an object you watch
    (MWatchNotify role, /root/reference/src/messages/MWatchNotify.h)."""

    TAG = 17

    def __init__(self, notify_id: int, pool: int, oid: str,
                 payload: bytes = b"", cookie: int = 0):
        self.notify_id = notify_id
        self.pool = pool
        self.oid = oid
        self.payload = payload
        self.cookie = cookie

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.notify_id)
        enc.s64(self.pool)
        enc.string(self.oid)
        enc.bytes(self.payload)
        enc.u64(self.cookie)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MWatchNotify":
        return cls(dec.u64(), dec.s64(), dec.string(), dec.bytes(),
                   dec.u64())


@register
class MWatchNotifyAck(Message):
    """Watcher -> primary: notify delivered to the local callback."""

    TAG = 18

    def __init__(self, notify_id: int, cookie: int = 0):
        self.notify_id = notify_id
        self.cookie = cookie

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.notify_id)
        enc.u64(self.cookie)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MWatchNotifyAck":
        return cls(dec.u64(), dec.u64())


@register
class MOSDCommand(Message):
    """JSON command to an OSD daemon over the wire — the `ceph tell
    osd.N <cmd>` role (reference: MCommand.h carried over the client
    messenger, handled in OSD::do_command, OSD.cc).  Same admin
    surface as the local admin socket (perf dump, dump_ops_in_flight,
    scrub) but reachable by the mgr and remote CLIs."""

    TAG = 19

    def __init__(self, tid: int, cmd: Dict[str, Any]):
        self.tid = tid
        self.cmd = cmd

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.string(json.dumps(self.cmd))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDCommand":
        return cls(dec.u64(), json.loads(dec.string()))


@register
class MOSDCommandReply(Message):
    TAG = 20

    def __init__(self, tid: int, rc: int, out: Dict[str, Any]):
        self.tid = tid
        self.rc = rc
        self.out = out

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.string(json.dumps(self.out))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MOSDCommandReply":
        return cls(dec.u64(), dec.s32(), json.loads(dec.string()))


@register
class MClientRequest(Message):
    """Client -> MDS metadata request (MClientRequest.h role): a named
    op with JSON args.  File DATA never rides this — clients talk to
    the OSDs directly for data, like the reference."""

    TAG = 21

    def __init__(self, tid: int, op: str, args: Dict[str, Any]):
        self.tid = tid
        self.op = op
        self.args = args

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.string(self.op)
        enc.string(json.dumps(self.args))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MClientRequest":
        return cls(dec.u64(), dec.string(), json.loads(dec.string()))


@register
class MClientReply(Message):
    TAG = 22

    def __init__(self, tid: int, rc: int, out: Dict[str, Any]):
        self.tid = tid
        self.rc = rc
        self.out = out

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.string(json.dumps(self.out))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MClientReply":
        return cls(dec.u64(), dec.s32(), json.loads(dec.string()))


@register
class MClientCaps(Message):
    """Capability traffic between MDS and client (MClientCaps.h role).

    MDS -> client: op="revoke" — give up the cap on ino (down to the
    mode in `cap`, "" = none); the client must drop the matching cache
    entries, fold any DIRTY buffered attrs into `attrs`, and answer
    op="ack" with the same tid.  Client -> MDS: op="release" — a
    voluntary cap return (close of a write handle), attrs carrying the
    final flushed size/mtime.  Grants ride metadata REPLIES (the
    `cap` field of MClientReply.out), not this message."""

    TAG = 31

    def __init__(self, op: str, ino: int, cap: str = "",
                 tid: int = 0, attrs: Optional[Dict[str, Any]] = None):
        self.op = op
        self.ino = ino
        self.cap = cap
        self.tid = tid
        self.attrs = attrs or {}

    def encode_payload(self, enc: Encoder) -> None:
        enc.string(self.op)
        enc.u64(self.ino)
        enc.string(self.cap)
        enc.u64(self.tid)
        enc.string(json.dumps(self.attrs))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MClientCaps":
        return cls(dec.string(), dec.u64(), dec.string(), dec.u64(),
                   json.loads(dec.string()))


# -- mon quorum (Paxos + elections) -----------------------------------------


@register
class MMonElection(Message):
    """Election traffic (MMonElection role, src/messages/MMonElection.h):
    kind PROPOSE/ACK/VICTORY/PING/PONG, epoch-numbered.  v2 adds the
    sender's connectivity score (the reference ships a full
    ConnectionTracker blob in its `sharing_bl`; here one aggregate
    float carries the CONNECTIVITY-strategy signal)."""

    TAG = 23
    VERSION = 2

    def __init__(self, kind: int, epoch: int, rank: int,
                 quorum: Optional[List[int]] = None,
                 score: float = 0.0):
        self.kind = kind
        self.epoch = epoch
        self.rank = rank
        self.quorum = quorum or []
        self.score = score

    def encode_payload(self, enc: Encoder) -> None:
        enc.u8(self.kind)
        enc.u64(self.epoch)
        enc.s32(self.rank)
        enc.list(self.quorum, Encoder.s32)
        enc.f64(self.score)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MMonElection":
        kind, epoch, rank = dec.u8(), dec.u64(), dec.s32()
        quorum = dec.list(Decoder.s32)
        # v1 blobs end here; DECODE_FINISH discipline skips/supplies
        score = dec.f64() if dec.remaining() >= 8 else 0.0
        return cls(kind, epoch, rank, quorum, score)


@register
class MMonPaxos(Message):
    """Paxos traffic (MMonPaxos role, src/messages/MMonPaxos.h): one
    message shape for collect/last/begin/accept/commit/lease (+ the
    pull/full catch-up ops), fields meaningful per op."""

    TAG = 24

    def __init__(self, op: int, pn: int = 0, version: int = 0,
                 value: bytes = b"", last_committed: int = 0,
                 first_committed: int = 0,
                 values: Optional[Dict[int, bytes]] = None,
                 lease: float = 0.0, uncommitted_pn: int = 0,
                 from_rank: int = -1):
        self.op = op
        self.pn = pn
        self.version = version
        self.value = value
        self.last_committed = last_committed
        self.first_committed = first_committed
        self.values = values or {}
        self.lease = lease
        self.uncommitted_pn = uncommitted_pn
        self.from_rank = from_rank

    def encode_payload(self, enc: Encoder) -> None:
        enc.u8(self.op)
        enc.u64(self.pn)
        enc.u64(self.version)
        enc.bytes(self.value)
        enc.u64(self.last_committed)
        enc.u64(self.first_committed)
        enc.map(self.values, Encoder.u64, Encoder.bytes)
        enc.f64(self.lease)
        enc.u64(self.uncommitted_pn)
        enc.s32(self.from_rank)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MMonPaxos":
        return cls(dec.u8(), dec.u64(), dec.u64(), dec.bytes(),
                   dec.u64(), dec.u64(),
                   dec.map(Decoder.u64, Decoder.bytes), dec.f64(),
                   dec.u64(), dec.s32())


@register
class MMonForward(Message):
    """Peon -> leader relay of a client message (MForward role): the
    inner message rides as (tag, payload); fwd_tid routes the reply
    back through the peon; fwd_tid 0 = fire-and-forget."""

    TAG = 25

    def __init__(self, fwd_tid: int, inner_tag: int,
                 inner_payload: bytes):
        self.fwd_tid = fwd_tid
        self.inner_tag = inner_tag
        self.inner_payload = inner_payload

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.fwd_tid)
        enc.u32(self.inner_tag)
        enc.bytes(self.inner_payload)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MMonForward":
        return cls(dec.u64(), dec.u32(), dec.bytes())


@register
class MMonForwardReply(Message):
    """Leader -> peon reply for a forwarded command."""

    TAG = 26

    def __init__(self, fwd_tid: int, rc: int, out: Dict[str, Any]):
        self.fwd_tid = fwd_tid
        self.rc = rc
        self.out = out

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.fwd_tid)
        enc.s32(self.rc)
        enc.string(json.dumps(self.out))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MMonForwardReply":
        return cls(dec.u64(), dec.s32(), json.loads(dec.string()))


# -- centralized config + cluster log ---------------------------------------


@register
class MConfig(Message):
    """Mon -> daemon: the centralized config snapshot relevant to the
    subscriber (ConfigMonitor's config push role).  Sent on
    subscription and on every config commit."""

    TAG = 29

    def __init__(self, version: int, values: Dict[str, Any]):
        self.version = version
        self.values = values

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.version)
        enc.string(json.dumps(self.values))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MConfig":
        return cls(dec.u64(), json.loads(dec.string()))


@register
class MLog(Message):
    """Daemon -> mon: structured cluster-log entries (MLog /
    LogMonitor role) — one place to read a multi-daemon incident."""

    TAG = 30

    def __init__(self, entries: List[Dict[str, Any]]):
        self.entries = entries

    def encode_payload(self, enc: Encoder) -> None:
        enc.string(json.dumps(self.entries))

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MLog":
        return cls(json.loads(dec.string()))


# -- cephx KDC (mon ticket service) -----------------------------------------


@register
class MAuth(Message):
    """Client -> mon ticket request (MAuth role, CephxServiceHandler
    two-step: stage 1 fetches a server challenge, stage 2 presents the
    proof)."""

    TAG = 27

    def __init__(self, tid: int, entity: str, stage: int,
                 kid: int = 0, client_challenge: bytes = b"",
                 proof: bytes = b""):
        self.tid = tid
        self.entity = entity
        self.stage = stage
        self.kid = kid
        self.client_challenge = client_challenge
        self.proof = proof

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.string(self.entity)
        enc.u8(self.stage)
        enc.s32(self.kid)
        enc.bytes(self.client_challenge)
        enc.bytes(self.proof)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MAuth":
        return cls(dec.u64(), dec.string(), dec.u8(), dec.s32(),
                   dec.bytes(), dec.bytes())


@register
class MAuthReply(Message):
    """Mon -> client: server challenge (stage 1) or ticket (stage 2)."""

    TAG = 28

    def __init__(self, tid: int, rc: int,
                 server_challenge: bytes = b"", ticket: bytes = b""):
        self.tid = tid
        self.rc = rc
        self.server_challenge = server_challenge
        self.ticket = ticket

    def encode_payload(self, enc: Encoder) -> None:
        enc.u64(self.tid)
        enc.s32(self.rc)
        enc.bytes(self.server_challenge)
        enc.bytes(self.ticket)

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "MAuthReply":
        return cls(dec.u64(), dec.s32(), dec.bytes(), dec.bytes())


# -- small wire codecs shared by ShardOp omap payloads ----------------------


def encode_kv_map(kv) -> bytes:
    enc = Encoder()
    enc.start(1, 1)
    enc.map(dict(kv), Encoder.string, Encoder.bytes)
    enc.finish()
    return enc.to_bytes()


def decode_kv_map(raw: bytes) -> Dict[str, bytes]:
    dec = Decoder(raw)
    dec.start(1)
    out = dec.map(Decoder.string, Decoder.bytes)
    dec.finish()
    return out


def encode_str_list(items) -> bytes:
    enc = Encoder()
    enc.start(1, 1)
    enc.list(list(items), Encoder.string)
    enc.finish()
    return enc.to_bytes()


def decode_str_list(raw: bytes) -> List[str]:
    dec = Decoder(raw)
    dec.start(1)
    out = dec.list(Decoder.string)
    dec.finish()
    return out

"""Async messenger (L3).

Reference parity: AsyncMessenger + Connection + Dispatcher
(/root/reference/src/msg/Messenger.h:1-824, src/msg/async/) re-designed
on asyncio: each daemon owns one event loop; connections are asyncio
streams carrying crc32c-framed messages (frames.py, the frames_v2
discipline).  Dispatch is fast-dispatch only — a received message is
handed straight to the dispatcher coroutine, no DispatchQueue thread
(DispatchQueue.h:200-203's fast path is the only path here).

Lossy-client semantics (src/msg/Policy.h): a dead connection is simply
forgotten; recovery is the caller's job (the Objecter-role client resends
ops on map change / reconnect, exactly like the reference's lossy client
policy).

TPU note: this layer is pure host control-plane.  Bulk data riding in
messages stays bytes; the compute (EC encode, crc, placement) happens in
the OSD daemon's batched device dispatches before/after the wire.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Awaitable, Callable, Dict, Optional

from ceph_tpu.common import auth
from ceph_tpu.msg import frames
from ceph_tpu.msg.messages import Message, MHello, decode_message

log = logging.getLogger("msgr")

DispatchFn = Callable[["Connection", Message], Awaitable[None]]


class Connection:
    """One peer session (Connection role)."""

    def __init__(self, messenger: "Messenger",
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 peer_name: str = "", peer_addr: str = ""):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer_name = peer_name
        self.peer_addr = peer_addr
        self._seq = itertools.count()
        self._send_lock = asyncio.Lock()
        self.closed = False

    # a wedged peer (stopped reading, socket buffer full) must not
    # park drain() — and with it this connection's send lock — forever;
    # on timeout the connection dies and the next send reconnects
    DRAIN_TIMEOUT = 15.0

    async def send(self, msg: Message) -> None:
        if self.closed:
            raise ConnectionError(f"connection to {self.peer_name} closed")
        parts = frames.encode_frame_parts(msg.TAG, next(self._seq),
                                          msg.encode(),
                                          secret=self.messenger.secret)
        async with self._send_lock:
            for part in parts:
                self.writer.write(part)
            try:
                await asyncio.wait_for(self.writer.drain(),
                                       self.DRAIN_TIMEOUT)
            except asyncio.TimeoutError:
                self.close()
                raise ConnectionError(
                    f"drain to {self.peer_name} timed out")

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self.writer.close()
            except Exception:
                pass

    def __repr__(self) -> str:
        return f"Connection(peer={self.peer_name}@{self.peer_addr})"


class Messenger:
    """Bind/connect endpoint owning all connections of one entity."""

    def __init__(self, entity_name: str, secret=None):
        self.entity_name = entity_name
        # cephx-lite cluster secret: frames are HMAC-signed and
        # unsigned/mis-signed inbound frames drop the connection
        self.secret = secret
        self.addr: str = ""
        self.dispatcher: Optional[DispatchFn] = None
        self.on_connection_fault: Optional[
            Callable[[Connection], None]] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: Dict[str, Connection] = {}      # by peer addr
        self._accepted: list = []                     # inbound conns
        self._tasks: set = set()

    # -- lifecycle ---------------------------------------------------------

    # stream buffer: bulk data frames are multi-MiB; the 64 KiB default
    # limit makes readexactly assemble them from ~64 tiny feeds
    STREAM_LIMIT = 8 << 20

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(
            self._handle_accept, host, port, limit=self.STREAM_LIMIT)
        port = self._server.sockets[0].getsockname()[1]
        self.addr = f"{host}:{port}"
        return self.addr

    async def shutdown(self) -> None:
        # close live connections BEFORE wait_closed(): since 3.12 it
        # waits for all connection handlers, which sit in read loops
        # until their connection dies
        if self._server is not None:
            self._server.close()
        for conn in list(self._conns.values()) + list(self._accepted):
            conn.close()
        self._conns.clear()
        self._accepted.clear()
        for task in list(self._tasks):
            task.cancel()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
            self._server = None

    # -- outbound ----------------------------------------------------------

    async def connect(self, addr: str) -> Connection:
        """Get-or-create a connection to addr (cached, like the
        AsyncMessenger connection table)."""
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        host, port_s = addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(
            host, int(port_s), limit=self.STREAM_LIMIT)
        conn = Connection(self, reader, writer, peer_addr=addr)
        self._conns[addr] = conn
        await conn.send(MHello(self.entity_name, self.addr))
        self._spawn(self._read_loop(conn))
        return conn

    async def send_to(self, addr: str, msg: Message) -> None:
        conn = await self.connect(addr)
        await conn.send(msg)

    # -- inbound -----------------------------------------------------------

    async def _handle_accept(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        conn = Connection(self, reader, writer)
        self._accepted.append(conn)
        await self._read_loop(conn)

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def _read_loop(self, conn: Connection) -> None:
        try:
            while True:
                pre = await conn.reader.readexactly(
                    frames.PREAMBLE_WIRE_LEN)
                tag, flags, _seq, length = frames.decode_preamble(pre)
                payload = await conn.reader.readexactly(length)
                frames.check_payload(
                    payload, await conn.reader.readexactly(4))
                sig = b""
                if flags & frames.FLAG_SIGNED:
                    sig = await conn.reader.readexactly(auth.SIG_LEN)
                frames.check_signature(self.secret, flags, pre,
                                       payload, sig)
                msg = decode_message(tag, payload)
                if isinstance(msg, MHello):
                    conn.peer_name = msg.entity_name
                    conn.peer_addr = msg.addr
                    continue
                if self.dispatcher is not None:
                    # fast dispatch: run handlers concurrently so a slow
                    # op never blocks the connection's read loop
                    self._spawn(self._dispatch_one(conn, msg))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer went away: lossy policy, just forget it
        except frames.FrameError as e:
            log.warning("%s: dropping %s: %s", self.entity_name, conn, e)
        except asyncio.CancelledError:
            raise
        finally:
            conn.close()
            # evict only THIS connection: an accepted conn can share the
            # peer's listen addr with a healthy outbound conn
            if self._conns.get(conn.peer_addr) is conn:
                del self._conns[conn.peer_addr]
            if conn in self._accepted:
                self._accepted.remove(conn)
            if self.on_connection_fault is not None:
                try:
                    self.on_connection_fault(conn)
                except Exception:
                    log.exception("connection fault handler failed")

    async def _dispatch_one(self, conn: Connection, msg: Message) -> None:
        try:
            await self.dispatcher(conn, msg)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("%s: dispatch of %r failed",
                          self.entity_name, msg)

"""Async messenger (L3).

Reference parity: AsyncMessenger + Connection + Dispatcher
(/root/reference/src/msg/Messenger.h:1-824, src/msg/async/) re-designed
on asyncio: each daemon owns one event loop; connections are asyncio
streams carrying crc32c-framed messages (frames.py, the frames_v2
discipline).  Dispatch is fast-dispatch only — a received message is
handed straight to the dispatcher coroutine, no DispatchQueue thread
(DispatchQueue.h:200-203's fast path is the only path here).

Authentication (cephx, common/auth.py): with a keyring configured, the
hello exchange is a mutual nonce handshake — each side's hello is
signed with a listed cluster key and carries a fresh nonce (plus an
optional mon ticket); both sides derive a per-connection SESSION key
and every later frame is signed with it and must arrive with a
strictly increasing sequence number.  A recorded frame therefore
verifies nowhere else (fresh nonces => fresh key), never twice on
the same connection (seq monotonicity), and never in the OPPOSITE
direction (the sender's role byte is bound into every signature, so
reflection by an active MITM fails) — the CephxSessionHandler
sign_message + session-key discipline.

Lossy-client semantics (src/msg/Policy.h): a dead connection is simply
forgotten; recovery is the caller's job (the Objecter-role client resends
ops on map change / reconnect, exactly like the reference's lossy client
policy).

TPU note: this layer is pure host control-plane.  Bulk data riding in
messages stays bytes; the compute (EC encode, crc, placement) happens in
the OSD daemon's batched device dispatches before/after the wire.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import random
from typing import Awaitable, Callable, Dict, Optional

from ceph_tpu.common import auth, lockdep
from ceph_tpu.msg import frames
from ceph_tpu.msg.messages import Message, MHello, decode_message

log = logging.getLogger("msgr")

DispatchFn = Callable[["Connection", Message], Awaitable[None]]

HANDSHAKE_TIMEOUT = 10.0

# Process-global kill switch for the in-process fast path (tests that
# must observe wire bytes — sniffers, frame-level auth tests — flip it)
LOCAL_FASTPATH = True

# bound addr -> Messenger, for same-process endpoint discovery
_LOCAL_REGISTRY: Dict[str, "Messenger"] = {}


class Connection:
    """One peer session (Connection role)."""

    def __init__(self, messenger: "Messenger",
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 peer_name: str = "", peer_addr: str = "",
                 outbound: bool = False):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer_name = peer_name
        self.peer_addr = peer_addr
        self.outbound = outbound
        self._seq = itertools.count()
        self._send_lock = lockdep.Lock("msg.send")
        self.closed = False
        # cephx session state
        self.session_key: Optional[bytes] = None
        self.session_ready = asyncio.Event()
        self.my_nonce: bytes = b""
        self.base_key: Optional[bytes] = None  # connector side choice
        # per-direction compression, negotiated from the two hellos
        # (frames_v2 compression negotiation): tx = first method the
        # PEER accepts that we support; rx = first method WE accept
        # that the peer supports.  None until the peer's hello arrives.
        self.peer_compress: tuple = ()
        # peer's hello-advertised AEAD capability (None until its
        # hello arrives; secure sends wait on session_ready, which is
        # set only after that hello is processed)
        self.peer_aead = None
        self._tx_comp = None   # (name, Compressor) | None
        self._rx_comp = None
        # acceptor replies with the CONNECTOR's kid: during rotation a
        # peer still on the old key must be able to verify our hello
        self.reply_kid: Optional[int] = None
        self.rx_seq = -1

    def _tx_role(self) -> bytes:
        return b"c" if self.outbound else b"s"

    def _rx_role(self) -> bytes:
        return b"s" if self.outbound else b"c"

    # a wedged peer (stopped reading, socket buffer full) must not
    # park drain() — and with it this connection's send lock — forever;
    # on timeout the connection dies and the next send reconnects
    DRAIN_TIMEOUT = 15.0

    async def send(self, msg: Message) -> None:
        key = None
        if self.messenger.secret is not None:
            if self.session_key is None:
                # wait out the handshake: pre-session frames would be
                # unverifiable at a keyed receiver
                try:
                    await asyncio.wait_for(self.session_ready.wait(),
                                           HANDSHAKE_TIMEOUT)
                except asyncio.TimeoutError:
                    self.close()
                    raise ConnectionError(
                        f"cephx handshake with {self.peer_name or self.peer_addr}"
                        " timed out")
            key = self.session_key
        await self._send_signed(msg, key)

    def _negotiated_comp(self, direction: str):
        """Resolve (lazily) the compressor for one direction from the
        two advertised method lists; None = no common method."""
        cached = self._tx_comp if direction == "tx" else self._rx_comp
        if cached is not None:
            return cached[1]
        mine = self.messenger.compress_methods
        theirs = self.peer_compress
        if not mine or not theirs:
            return None
        from ceph_tpu.compressor import Compressor

        # the RECEIVER's preference order rules: tx picks from the
        # peer's list, rx from ours — both sides compute the same
        # method for each direction
        prefer, support = (theirs, mine) if direction == "tx" \
            else (mine, theirs)
        for name in prefer:
            if name in support:
                comp = Compressor.create(name)
                if comp is not None:
                    pair = (name, comp)
                    if direction == "tx":
                        self._tx_comp = pair
                    else:
                        self._rx_comp = pair
                    return comp
        return None

    async def _send_signed(self, msg: Message,
                           key: Optional[bytes]) -> None:
        if self.closed:
            raise ConnectionError(f"connection to {self.peer_name} closed")
        await self.messenger._inject_faults(self)
        payload = msg.encode()
        flags = 0
        m = self.messenger
        if not isinstance(msg, MHello) \
                and len(payload) >= m.compress_min_size \
                and (not m.secure or m.compress_secure):
            # negotiated wire compression (frames_v2 compression role;
            # secure connections compress only when ms_compress_secure
            # says so — compress-then-encrypt leaks payload entropy)
            comp = self._negotiated_comp("tx")
            if comp is not None:
                import struct as _struct

                # payload is the encoder's bytes: the codec walks it
                # directly, no defensive copy
                blob, cmsg = comp.compress(payload)
                if len(blob) + 4 < len(payload):
                    payload = _struct.pack(
                        "<i", -1 if cmsg is None else cmsg) + blob
                    flags |= frames.FLAG_COMPRESSED
        async with self._send_lock:
            # seq is allocated INSIDE the send lock: a hedged sub-read
            # may be CANCELLED while waiting for this lock, and a seq
            # consumed for a frame that never hits the wire would gap
            # the receiver's replay check (seq != rx_seq + 1 kills the
            # connection).  Past this point the only await is drain(),
            # by which time the frame is fully buffered — cancellation
            # can no longer corrupt framing.
            seq = next(self._seq)
            if key is not None and key is self.session_key and \
                    self.messenger.secure:
                # secure mode: the payload rides AEAD-sealed under the
                # session key (hellos stay plaintext — they carry no
                # secrets and exist before the session does)
                payload = auth.seal(key, self._tx_role(), seq, payload,
                                    peer_aead=self.peer_aead)
                flags |= frames.FLAG_SECURE
            parts = frames.encode_frame_parts(msg.TAG, seq,
                                              payload, flags=flags,
                                              key=key,
                                              role=self._tx_role())
            for part in parts:
                self.writer.write(part)
            try:
                await asyncio.wait_for(self.writer.drain(),
                                       self.DRAIN_TIMEOUT)
            except asyncio.TimeoutError:
                self.close()
                raise ConnectionError(
                    f"drain to {self.peer_name} timed out")

    async def send_hello(self, ticket: bytes = b"") -> None:
        """Handshake frame: signed with the ACTIVE static key (the only
        shared context before a session exists), carrying my nonce."""
        m = self.messenger
        if not self.my_nonce:
            self.my_nonce = auth.new_nonce()
        key = None
        kid = 0
        if m.secret is not None:
            kid = m.secret.active if self.reply_kid is None \
                else self.reply_kid
            key = m.secret.get(kid)
        hello = MHello(m.entity_name, m.addr, nonce=self.my_nonce,
                       kid=kid, ticket=ticket,
                       compression=",".join(m.compress_methods),
                       aead=auth.aead_available())
        await self._send_signed(hello, key)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            # wake handshake waiters: closed=True makes their send
            # raise immediately instead of riding out the timeout
            self.session_ready.set()
            try:
                self.writer.close()
            except Exception:
                pass

    def __repr__(self) -> str:
        return f"Connection(peer={self.peer_name}@{self.peer_addr})"


class LocalConnection(Connection):
    """In-process peer session: the loopback fast path.

    Reference parity: AsyncMessenger delivers messages addressed to an
    endpoint in the same process without serializing them onto a socket
    (Messenger::get_loopback_connection / DispatchQueue local_delivery,
    /root/reference/src/msg/DispatchQueue.h:200-245 local_delivery +
    Messenger.h loopback connection) — same discipline here: a Message
    object is handed to the peer dispatcher as-is, zero-copy, no
    framing, no signing (same-process peers share a trust domain; the
    fast path only engages when both endpoints hold the SAME keyring
    and secure flag, so a mis-keyed peer still takes the socket path
    and fails authentication honestly).

    Contract: a sent Message is TRANSFERRED — the sender must not
    mutate or resend the same instance (matching the reference, where
    a queued local message is owned by the dispatch queue).
    """

    def __init__(self, messenger: "Messenger", peer_name: str,
                 peer_addr: str, outbound: bool):
        self.messenger = messenger
        self.peer_name = peer_name
        self.peer_addr = peer_addr
        self.outbound = outbound
        self.closed = False
        self.session_key = None
        self._peer: Optional["LocalConnection"] = None

    async def send(self, msg: Message) -> None:
        peer = self._peer
        if self.closed or peer is None or peer.closed:
            raise ConnectionError(
                f"local connection to {self.peer_name} closed")
        # the fast path is still "the wire" for fault purposes: both
        # endpoints' injection settings apply, like a socket whose
        # either end can fail it
        await self.messenger._inject_faults(self)
        if peer.closed:
            raise ConnectionError(
                f"local connection to {self.peer_name} closed")
        try:
            await peer.messenger._inject_faults(peer)
        except ConnectionError:
            # receiver-side roll = the lost-ack shape: the message is
            # swallowed and the connection dies, but the SENDER returns
            # success — it cannot know the peer never dispatched
            # (mirrors the socket path, where the drop happens after
            # the sender's write completed)
            return
        m = peer.messenger
        if m.dispatcher is not None:
            if isinstance(msg, MHello):
                return  # identification already happened at connect
            m._spawn(m._dispatch_one(peer, msg))

    async def send_hello(self, ticket: bytes = b"") -> None:
        pass  # no handshake: identities were exchanged at connect

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        peer = self._peer
        m = self.messenger
        if m._conns.get(self.peer_addr) is self:
            del m._conns[self.peer_addr]
        if self in m._accepted:
            m._accepted.remove(self)
        if m.on_connection_fault is not None:
            try:
                m.on_connection_fault(self)
            except Exception:
                log.exception("connection fault handler failed")
        if peer is not None and not peer.closed:
            # propagate asynchronously, mimicking the socket path where
            # the peer's read loop notices the close a tick later
            try:
                asyncio.get_running_loop().call_soon(peer.close)
            except RuntimeError:
                peer.close()

    def __repr__(self) -> str:
        return f"LocalConnection(peer={self.peer_name}@{self.peer_addr})"


class Messenger:
    """Bind/connect endpoint owning all connections of one entity."""

    def __init__(self, entity_name: str, secret=None):
        self.entity_name = entity_name
        # cephx keyring (auth.Keyring): hellos are static-signed, all
        # later frames session-signed; unsigned/mis-signed inbound
        # frames drop the connection
        self.secret = auth.parse_secret(secret) \
            if not isinstance(secret, auth.Keyring) else secret
        # mon-granted ticket attached to outbound hellos (clients set
        # this after an MAuth exchange; services validate offline)
        self.ticket: bytes = b""
        # on-wire encryption (msgr2 secure mode): session-keystream
        # payload encryption; a secure endpoint also REFUSES plaintext
        # post-handshake frames
        self.secure = False
        self.addr: str = ""
        # opt-in per endpoint: daemons and clients enable it
        # (ms_local_fastpath); frame-level tests leave it off so two
        # in-process messengers still exercise the real wire
        self.local_fastpath = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.dispatcher: Optional[DispatchFn] = None
        self.on_connection_fault: Optional[
            Callable[[Connection], None]] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: Dict[str, Connection] = {}      # by peer addr
        self._accepted: list = []                     # inbound conns
        self._tasks: set = set()
        # fault injection (ms_inject_* options,
        # /root/reference/src/common/options.cc:1087-1108): daemons wire
        # these from config at boot (OSDs also re-wire on every
        # central-config push; mons are boot-time only).  N > 0 fails
        # roughly every Nth frame; delay > 0 sleeps a uniform
        # [0, delay) before each send (the reference's
        # ms_inject_internal_delays discipline).
        self.inject_socket_failures: int = 0
        self.inject_internal_delays: float = 0.0
        self._inject_rng = random.Random()
        # wire compression (ms_compress_* options): methods this
        # endpoint ACCEPTS, advertised in its hello, in preference
        # order; empty = no compression.  min_size gates tiny frames
        # (compression overhead beats the saving); compress_secure
        # must be opted into (compressed-then-encrypted length leaks)
        self.compress_methods: tuple = ()
        self.compress_min_size: int = 4096
        self.compress_secure: bool = False

    def apply_compress_config(self, config: dict) -> None:
        """Wire the ms_compress_* options into this endpoint.  The
        advertised list is filtered to codecs that actually LOAD here:
        negotiation is computed independently on both ends from the
        two advertised lists, so advertising a codec this host cannot
        instantiate would make the two ends settle on different
        methods for one direction — every bulk frame would then die in
        decompression."""
        from ceph_tpu.compressor import Compressor

        methods = []
        for name in str(config.get("ms_compress_methods", "")
                        or "").split(","):
            name = name.strip()
            if not name or name == "random":
                continue  # "random" diverges per instantiation
            if Compressor.create(name) is None:
                log.warning("%s: compression method %r unavailable"
                            " here; not advertising it",
                            self.entity_name, name)
                continue
            methods.append(name)
        self.compress_methods = tuple(methods)
        try:
            self.compress_min_size = int(config.get(
                "ms_compress_min_size", 4096))
        except (TypeError, ValueError):
            pass
        self.compress_secure = bool(config.get("ms_compress_secure",
                                               False))

    # stream buffer: bulk data frames are multi-MiB; the 64 KiB default
    # limit makes readexactly assemble them from ~64 tiny feeds
    STREAM_LIMIT = 8 << 20

    async def _inject_faults(self, conn: Connection) -> None:
        """Honor ms_inject_* on this frame: maybe delay, maybe kill the
        connection (AsyncConnection::inject_delay + the every-Nth
        socket-failure roll).  Killing closes the connection exactly
        like a real socket fault — the peer sees EOF, the fault handler
        fires, and callers get ConnectionError."""
        d = self.inject_internal_delays
        if d > 0:
            await asyncio.sleep(self._inject_rng.random() * d)
        n = self.inject_socket_failures
        if n > 0 and self._inject_rng.randrange(n) == 0:
            log.info("%s: injecting socket failure on %r",
                     self.entity_name, conn)
            conn.close()
            raise ConnectionError(
                f"injected socket failure to {conn.peer_name or conn.peer_addr}")

    # -- lifecycle ---------------------------------------------------------

    @staticmethod
    async def _prewarm_native() -> None:
        """Prewarm the native library's build-once path OFF-loop: the
        first get_lib() may compile the .so (a subprocess), and every
        wire frame's crc32c rides it.  This is the SHARED choke point —
        every server binds and every client connects — so MDS and
        client-only processes get the same guarantee the OSD/Mon
        daemons do, which is what lets the analyzer exempt get_lib
        from transitive-blocking-call (rules_async._BLOCKING_EXEMPT:
        steady-state calls are a dict read)."""
        from ceph_tpu import native
        if not native.prewarmed():
            await asyncio.to_thread(native.get_lib)

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> str:
        if self.secure and self.secret is None:
            # claiming wire encryption with no key would silently send
            # plaintext — refuse to start misconfigured
            raise ValueError(
                f"{self.entity_name}: auth_secure requires a keyring"
                " (auth_secret)")
        await self._prewarm_native()
        self._server = await asyncio.start_server(
            self._handle_accept, host, port, limit=self.STREAM_LIMIT)
        port = self._server.sockets[0].getsockname()[1]
        self.addr = f"{host}:{port}"
        self._loop = asyncio.get_running_loop()
        _LOCAL_REGISTRY[self.addr] = self
        return self.addr

    async def shutdown(self) -> None:
        # close live connections BEFORE wait_closed(): since 3.12 it
        # waits for all connection handlers, which sit in read loops
        # until their connection dies
        if _LOCAL_REGISTRY.get(self.addr) is self:
            del _LOCAL_REGISTRY[self.addr]
        if self._server is not None:
            self._server.close()
        for conn in list(self._conns.values()) + list(self._accepted):
            conn.close()
        self._conns.clear()
        self._accepted.clear()
        for task in list(self._tasks):
            task.cancel()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
            self._server = None

    # -- outbound ----------------------------------------------------------

    async def connect(self, addr: str) -> Connection:
        """Get-or-create a connection to addr (cached, like the
        AsyncMessenger connection table)."""
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        if LOCAL_FASTPATH and self.local_fastpath:
            target = _LOCAL_REGISTRY.get(addr)
            if (target is not None and target is not self
                    and target.local_fastpath
                    and target._loop is asyncio.get_running_loop()
                    and self._local_compatible(target)):
                return self._connect_local(addr, target)
        await self._prewarm_native()
        host, port_s = addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(
            host, int(port_s), limit=self.STREAM_LIMIT)
        conn = Connection(self, reader, writer, peer_addr=addr,
                          outbound=True)
        self._conns[addr] = conn
        ticket = self.ticket
        if ticket and self.secret is not None:
            chk = auth.check_ticket(self.secret, ticket)
            if chk is not None:
                conn.base_key = chk[1]
            else:
                ticket = b""  # expired locally: fall back to static
        if conn.base_key is None and self.secret is not None:
            conn.base_key = self.secret.active_key
        await conn.send_hello(ticket=ticket)
        self._spawn(self._read_loop(conn))
        return conn

    def _local_compatible(self, target: "Messenger") -> bool:
        """The fast path must not launder authentication: it engages
        only where the socket handshake would trivially succeed — both
        endpoints keyless, or both holding the same active key with the
        same secure-mode stance."""
        if (self.secret is None) != (target.secret is None):
            return False
        if self.secret is not None:
            if self.secret.active_key != target.secret.active_key:
                return False
            if bool(self.secure) != bool(target.secure):
                return False
        return True

    def _connect_local(self, addr: str,
                       target: "Messenger") -> "LocalConnection":
        me = LocalConnection(self, target.entity_name, addr,
                             outbound=True)
        back = LocalConnection(
            target, self.entity_name,
            self.addr or f"local:{self.entity_name}", outbound=False)
        me._peer = back
        back._peer = me
        self._conns[addr] = me
        target._accepted.append(back)
        return me

    async def send_to(self, addr: str, msg: Message) -> None:
        conn = await self.connect(addr)
        await conn.send(msg)

    # -- inbound -----------------------------------------------------------

    async def _handle_accept(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        conn = Connection(self, reader, writer)
        self._accepted.append(conn)
        await self._read_loop(conn)

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def _read_frame(self, conn: Connection):
        pre = await conn.reader.readexactly(frames.PREAMBLE_WIRE_LEN)
        tag, flags, seq, length = frames.decode_preamble(pre)
        payload = await conn.reader.readexactly(length)
        frames.check_payload(payload,
                             await conn.reader.readexactly(4))
        sig = b""
        if flags & frames.FLAG_SIGNED:
            sig = await conn.reader.readexactly(auth.SIG_LEN)
        return pre, tag, flags, seq, payload, sig

    async def _handshake_hello(self, conn: Connection, tag, pre, flags,
                               seq, payload, sig) -> None:
        """First frame at a keyed endpoint: a static-signed hello.
        Raises FrameError on any auth failure."""
        if not flags & frames.FLAG_SIGNED:
            raise frames.FrameError("unsigned frame (auth required)")
        msg = decode_message(tag, payload)
        if not isinstance(msg, MHello):
            raise frames.FrameError("expected hello before session")
        key = self.secret.get(msg.kid)
        if key is None or not auth.verify(
                key, sig, conn._rx_role(),
                pre[:frames.PREAMBLE.size], payload):
            raise frames.FrameError("hello signature mismatch"
                                    " (wrong key?)")
        conn.rx_seq = seq
        conn.peer_name = msg.entity_name
        conn.peer_addr = msg.addr or conn.peer_addr
        conn.peer_compress = tuple(
            x for x in getattr(msg, "compression", "").split(",") if x)
        conn.peer_aead = getattr(msg, "aead", None)
        if conn.outbound:
            # acceptor's reply (never ticket-bearing): session =
            # f(base chosen at connect, my_nonce, its_nonce)
            conn.session_key = auth.derive_session(
                conn.base_key, conn.my_nonce, msg.nonce)
            conn.session_ready.set()
        else:
            base = key
            if msg.ticket:
                chk = auth.check_ticket(self.secret, msg.ticket)
                if chk is None:
                    raise frames.FrameError("invalid or expired"
                                            " ticket")
                _entity, base = chk
            conn.base_key = base
            conn.reply_kid = msg.kid
            # reply with MY hello BEFORE arming the session, so the
            # hello is guaranteed to be this side's first frame
            await conn.send_hello()
            conn.session_key = auth.derive_session(
                base, msg.nonce, conn.my_nonce)
            conn.session_ready.set()

    async def _read_loop(self, conn: Connection) -> None:
        try:
            while True:
                pre, tag, flags, seq, payload, sig = \
                    await self._read_frame(conn)
                # receive-side injection: drop the connection AFTER a
                # frame arrived but BEFORE it dispatches — the lost-ack
                # shape (sender thinks it delivered; receiver never saw
                # it) that distinguishes socket faults from clean stops
                await self._inject_faults(conn)
                if self.secret is not None:
                    if conn.session_key is None:
                        await self._handshake_hello(
                            conn, tag, pre, flags, seq, payload, sig)
                        continue
                    if not flags & frames.FLAG_SIGNED:
                        raise frames.FrameError(
                            "unsigned frame (auth required)")
                    if not auth.verify(conn.session_key, sig,
                                       conn._rx_role(),
                                       pre[:frames.PREAMBLE.size],
                                       payload):
                        raise frames.FrameError(
                            "session signature mismatch (replayed or"
                            " forged frame)")
                    if seq != conn.rx_seq + 1:
                        raise frames.FrameError(
                            f"non-monotonic frame seq {seq} (last"
                            f" {conn.rx_seq}): replay rejected")
                    conn.rx_seq = seq
                    if flags & frames.FLAG_SECURE:
                        payload = auth.unseal(conn.session_key,
                                              conn._rx_role(), seq,
                                              payload,
                                              peer_aead=conn.peer_aead)
                    elif self.secure:
                        raise frames.FrameError(
                            "plaintext frame but secure mode required")
                if flags & frames.FLAG_COMPRESSED:
                    comp = conn._negotiated_comp("rx")
                    if comp is None:
                        raise frames.FrameError(
                            "compressed frame but no negotiated codec")
                    import struct as _struct

                    try:
                        (cmsg,) = _struct.unpack_from("<i", payload)
                        # hand the codec a VIEW past the header: the
                        # decompressor walks the frame buffer in
                        # place — zero copies between socket and codec
                        payload = comp.decompress(
                            memoryview(payload)[4:],
                            None if cmsg < 0 else cmsg)
                    except frames.FrameError:
                        raise
                    except Exception as e:
                        # includes a truncated (<4 byte) length prefix —
                        # malformed frames all take the FrameError path
                        raise frames.FrameError(
                            f"decompression failed: {e}")
                msg = decode_message(tag, payload)
                if isinstance(msg, MHello):
                    # keyless endpoint: hellos are identification only
                    # (a keyed connector talking to a keyless acceptor
                    # rejects the unsigned reply and drops — keyed
                    # peers refuse keyless clusters by design)
                    conn.peer_name = msg.entity_name
                    conn.peer_addr = msg.addr or conn.peer_addr
                    conn.peer_compress = tuple(
                        x for x in getattr(msg, "compression",
                                           "").split(",") if x)
                    conn.peer_aead = getattr(msg, "aead", None)
                    if not conn.outbound and \
                            not getattr(conn, "_hello_sent", False):
                        # identify back: the connector needs OUR
                        # advertised compression methods (and name)
                        # to finish the per-direction negotiation
                        conn._hello_sent = True
                        await conn.send_hello()
                    continue
                if self.dispatcher is not None:
                    # fast dispatch: run handlers concurrently so a slow
                    # op never blocks the connection's read loop
                    self._spawn(self._dispatch_one(conn, msg))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer went away: lossy policy, just forget it
        except (frames.FrameError, auth.SealError) as e:
            log.warning("%s: dropping %s: %s", self.entity_name, conn, e)
        except asyncio.CancelledError:
            raise
        finally:
            conn.close()
            # evict only THIS connection: an accepted conn can share the
            # peer's listen addr with a healthy outbound conn
            if self._conns.get(conn.peer_addr) is conn:
                del self._conns[conn.peer_addr]
            if conn in self._accepted:
                self._accepted.remove(conn)
            if self.on_connection_fault is not None:
                try:
                    self.on_connection_fault(conn)
                except Exception:
                    log.exception("connection fault handler failed")

    async def _dispatch_one(self, conn: Connection, msg: Message) -> None:
        try:
            await self.dispatcher(conn, msg)
        except asyncio.CancelledError:
            raise
        except ConnectionError as e:
            # replying into a just-closed connection is ordinary churn
            # (peer died between request and response): debug, not error
            log.debug("%s: dispatch of %r hit dead conn: %s",
                      self.entity_name, msg, e)
        except Exception:
            log.exception("%s: dispatch of %r failed",
                          self.entity_name, msg)

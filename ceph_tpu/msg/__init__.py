"""Async messenger (L3).

Reference parity: AsyncMessenger + Connection + Dispatcher
(/root/reference/src/msg/Messenger.h:1-824, src/msg/async/) re-designed
on asyncio: each daemon owns one event loop; connections are asyncio
streams carrying crc32c-framed messages (frames.py, the frames_v2
discipline).  Dispatch is fast-dispatch only — a received message is
handed straight to the dispatcher coroutine, no DispatchQueue thread
(DispatchQueue.h:200-203's fast path is the only path here).

Authentication (cephx, common/auth.py): with a keyring configured, the
hello exchange is a mutual nonce handshake — each side's hello is
signed with a listed cluster key and carries a fresh nonce (plus an
optional mon ticket); both sides derive a per-connection SESSION key
and every later frame is signed with it and must arrive with a
strictly increasing sequence number.  A recorded frame therefore
verifies nowhere else (fresh nonces => fresh key) and never twice on
the same connection (seq monotonicity) — the CephxSessionHandler
sign_message + session-key discipline.

Lossy-client semantics (src/msg/Policy.h): a dead connection is simply
forgotten; recovery is the caller's job (the Objecter-role client resends
ops on map change / reconnect, exactly like the reference's lossy client
policy).

TPU note: this layer is pure host control-plane.  Bulk data riding in
messages stays bytes; the compute (EC encode, crc, placement) happens in
the OSD daemon's batched device dispatches before/after the wire.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Awaitable, Callable, Dict, Optional

from ceph_tpu.common import auth
from ceph_tpu.msg import frames
from ceph_tpu.msg.messages import Message, MHello, decode_message

log = logging.getLogger("msgr")

DispatchFn = Callable[["Connection", Message], Awaitable[None]]

HANDSHAKE_TIMEOUT = 10.0


class Connection:
    """One peer session (Connection role)."""

    def __init__(self, messenger: "Messenger",
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 peer_name: str = "", peer_addr: str = "",
                 outbound: bool = False):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer_name = peer_name
        self.peer_addr = peer_addr
        self.outbound = outbound
        self._seq = itertools.count()
        self._send_lock = asyncio.Lock()
        self.closed = False
        # cephx session state
        self.session_key: Optional[bytes] = None
        self.session_ready = asyncio.Event()
        self.my_nonce: bytes = b""
        self.base_key: Optional[bytes] = None  # connector side choice
        # acceptor replies with the CONNECTOR's kid: during rotation a
        # peer still on the old key must be able to verify our hello
        self.reply_kid: Optional[int] = None
        self.rx_seq = -1

    def _tx_role(self) -> bytes:
        return b"c" if self.outbound else b"s"

    def _rx_role(self) -> bytes:
        return b"s" if self.outbound else b"c"

    # a wedged peer (stopped reading, socket buffer full) must not
    # park drain() — and with it this connection's send lock — forever;
    # on timeout the connection dies and the next send reconnects
    DRAIN_TIMEOUT = 15.0

    async def send(self, msg: Message) -> None:
        key = None
        if self.messenger.secret is not None:
            if self.session_key is None:
                # wait out the handshake: pre-session frames would be
                # unverifiable at a keyed receiver
                try:
                    await asyncio.wait_for(self.session_ready.wait(),
                                           HANDSHAKE_TIMEOUT)
                except asyncio.TimeoutError:
                    self.close()
                    raise ConnectionError(
                        f"cephx handshake with {self.peer_name or self.peer_addr}"
                        " timed out")
            key = self.session_key
        await self._send_signed(msg, key)

    async def _send_signed(self, msg: Message,
                           key: Optional[bytes]) -> None:
        if self.closed:
            raise ConnectionError(f"connection to {self.peer_name} closed")
        seq = next(self._seq)
        payload = msg.encode()
        flags = 0
        if key is not None and key is self.session_key and \
                self.messenger.secure:
            # secure mode: the payload rides encrypted under the
            # session keystream (hellos stay plaintext — they carry
            # no secrets and exist before the session does)
            payload = auth.seal(key, self._tx_role(), seq, payload)
            flags = frames.FLAG_SECURE
        parts = frames.encode_frame_parts(msg.TAG, seq,
                                          payload, flags=flags,
                                          key=key)
        async with self._send_lock:
            for part in parts:
                self.writer.write(part)
            try:
                await asyncio.wait_for(self.writer.drain(),
                                       self.DRAIN_TIMEOUT)
            except asyncio.TimeoutError:
                self.close()
                raise ConnectionError(
                    f"drain to {self.peer_name} timed out")

    async def send_hello(self, ticket: bytes = b"") -> None:
        """Handshake frame: signed with the ACTIVE static key (the only
        shared context before a session exists), carrying my nonce."""
        m = self.messenger
        if not self.my_nonce:
            self.my_nonce = auth.new_nonce()
        key = None
        kid = 0
        if m.secret is not None:
            kid = m.secret.active if self.reply_kid is None \
                else self.reply_kid
            key = m.secret.get(kid)
        hello = MHello(m.entity_name, m.addr, nonce=self.my_nonce,
                       kid=kid, ticket=ticket)
        await self._send_signed(hello, key)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            # wake handshake waiters: closed=True makes their send
            # raise immediately instead of riding out the timeout
            self.session_ready.set()
            try:
                self.writer.close()
            except Exception:
                pass

    def __repr__(self) -> str:
        return f"Connection(peer={self.peer_name}@{self.peer_addr})"


class Messenger:
    """Bind/connect endpoint owning all connections of one entity."""

    def __init__(self, entity_name: str, secret=None):
        self.entity_name = entity_name
        # cephx keyring (auth.Keyring): hellos are static-signed, all
        # later frames session-signed; unsigned/mis-signed inbound
        # frames drop the connection
        self.secret = auth.parse_secret(secret) \
            if not isinstance(secret, auth.Keyring) else secret
        # mon-granted ticket attached to outbound hellos (clients set
        # this after an MAuth exchange; services validate offline)
        self.ticket: bytes = b""
        # on-wire encryption (msgr2 secure mode): session-keystream
        # payload encryption; a secure endpoint also REFUSES plaintext
        # post-handshake frames
        self.secure = False
        self.addr: str = ""
        self.dispatcher: Optional[DispatchFn] = None
        self.on_connection_fault: Optional[
            Callable[[Connection], None]] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: Dict[str, Connection] = {}      # by peer addr
        self._accepted: list = []                     # inbound conns
        self._tasks: set = set()

    # stream buffer: bulk data frames are multi-MiB; the 64 KiB default
    # limit makes readexactly assemble them from ~64 tiny feeds
    STREAM_LIMIT = 8 << 20

    # -- lifecycle ---------------------------------------------------------

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> str:
        if self.secure and self.secret is None:
            # claiming wire encryption with no key would silently send
            # plaintext — refuse to start misconfigured
            raise ValueError(
                f"{self.entity_name}: auth_secure requires a keyring"
                " (auth_secret)")
        self._server = await asyncio.start_server(
            self._handle_accept, host, port, limit=self.STREAM_LIMIT)
        port = self._server.sockets[0].getsockname()[1]
        self.addr = f"{host}:{port}"
        return self.addr

    async def shutdown(self) -> None:
        # close live connections BEFORE wait_closed(): since 3.12 it
        # waits for all connection handlers, which sit in read loops
        # until their connection dies
        if self._server is not None:
            self._server.close()
        for conn in list(self._conns.values()) + list(self._accepted):
            conn.close()
        self._conns.clear()
        self._accepted.clear()
        for task in list(self._tasks):
            task.cancel()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except (Exception, asyncio.TimeoutError):
                pass
            self._server = None

    # -- outbound ----------------------------------------------------------

    async def connect(self, addr: str) -> Connection:
        """Get-or-create a connection to addr (cached, like the
        AsyncMessenger connection table)."""
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        host, port_s = addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(
            host, int(port_s), limit=self.STREAM_LIMIT)
        conn = Connection(self, reader, writer, peer_addr=addr,
                          outbound=True)
        self._conns[addr] = conn
        ticket = self.ticket
        if ticket and self.secret is not None:
            chk = auth.check_ticket(self.secret, ticket)
            if chk is not None:
                conn.base_key = chk[1]
            else:
                ticket = b""  # expired locally: fall back to static
        if conn.base_key is None and self.secret is not None:
            conn.base_key = self.secret.active_key
        await conn.send_hello(ticket=ticket)
        self._spawn(self._read_loop(conn))
        return conn

    async def send_to(self, addr: str, msg: Message) -> None:
        conn = await self.connect(addr)
        await conn.send(msg)

    # -- inbound -----------------------------------------------------------

    async def _handle_accept(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        conn = Connection(self, reader, writer)
        self._accepted.append(conn)
        await self._read_loop(conn)

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def _read_frame(self, conn: Connection):
        pre = await conn.reader.readexactly(frames.PREAMBLE_WIRE_LEN)
        tag, flags, seq, length = frames.decode_preamble(pre)
        payload = await conn.reader.readexactly(length)
        frames.check_payload(payload,
                             await conn.reader.readexactly(4))
        sig = b""
        if flags & frames.FLAG_SIGNED:
            sig = await conn.reader.readexactly(auth.SIG_LEN)
        return pre, tag, flags, seq, payload, sig

    async def _handshake_hello(self, conn: Connection, tag, pre, flags,
                               seq, payload, sig) -> None:
        """First frame at a keyed endpoint: a static-signed hello.
        Raises FrameError on any auth failure."""
        if not flags & frames.FLAG_SIGNED:
            raise frames.FrameError("unsigned frame (auth required)")
        msg = decode_message(tag, payload)
        if not isinstance(msg, MHello):
            raise frames.FrameError("expected hello before session")
        key = self.secret.get(msg.kid)
        if key is None or not auth.verify(
                key, sig, pre[:frames.PREAMBLE.size], payload):
            raise frames.FrameError("hello signature mismatch"
                                    " (wrong key?)")
        conn.rx_seq = seq
        conn.peer_name = msg.entity_name
        conn.peer_addr = msg.addr or conn.peer_addr
        if conn.outbound:
            # acceptor's reply (never ticket-bearing): session =
            # f(base chosen at connect, my_nonce, its_nonce)
            conn.session_key = auth.derive_session(
                conn.base_key, conn.my_nonce, msg.nonce)
            conn.session_ready.set()
        else:
            base = key
            if msg.ticket:
                chk = auth.check_ticket(self.secret, bytes(msg.ticket))
                if chk is None:
                    raise frames.FrameError("invalid or expired"
                                            " ticket")
                _entity, base = chk
            conn.base_key = base
            conn.reply_kid = msg.kid
            # reply with MY hello BEFORE arming the session, so the
            # hello is guaranteed to be this side's first frame
            await conn.send_hello()
            conn.session_key = auth.derive_session(
                base, msg.nonce, conn.my_nonce)
            conn.session_ready.set()

    async def _read_loop(self, conn: Connection) -> None:
        try:
            while True:
                pre, tag, flags, seq, payload, sig = \
                    await self._read_frame(conn)
                if self.secret is not None:
                    if conn.session_key is None:
                        await self._handshake_hello(
                            conn, tag, pre, flags, seq, payload, sig)
                        continue
                    if not flags & frames.FLAG_SIGNED:
                        raise frames.FrameError(
                            "unsigned frame (auth required)")
                    if not auth.verify(conn.session_key, sig,
                                       pre[:frames.PREAMBLE.size],
                                       payload):
                        raise frames.FrameError(
                            "session signature mismatch (replayed or"
                            " forged frame)")
                    if seq != conn.rx_seq + 1:
                        raise frames.FrameError(
                            f"non-monotonic frame seq {seq} (last"
                            f" {conn.rx_seq}): replay rejected")
                    conn.rx_seq = seq
                    if flags & frames.FLAG_SECURE:
                        payload = auth.unseal(conn.session_key,
                                              conn._rx_role(), seq,
                                              payload)
                    elif self.secure:
                        raise frames.FrameError(
                            "plaintext frame but secure mode required")
                msg = decode_message(tag, payload)
                if isinstance(msg, MHello):
                    # keyless endpoint: hellos are identification only
                    # (a keyed connector talking to a keyless acceptor
                    # rejects the unsigned reply and drops — keyed
                    # peers refuse keyless clusters by design)
                    conn.peer_name = msg.entity_name
                    conn.peer_addr = msg.addr or conn.peer_addr
                    continue
                if self.dispatcher is not None:
                    # fast dispatch: run handlers concurrently so a slow
                    # op never blocks the connection's read loop
                    self._spawn(self._dispatch_one(conn, msg))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer went away: lossy policy, just forget it
        except frames.FrameError as e:
            log.warning("%s: dropping %s: %s", self.entity_name, conn, e)
        except asyncio.CancelledError:
            raise
        finally:
            conn.close()
            # evict only THIS connection: an accepted conn can share the
            # peer's listen addr with a healthy outbound conn
            if self._conns.get(conn.peer_addr) is conn:
                del self._conns[conn.peer_addr]
            if conn in self._accepted:
                self._accepted.remove(conn)
            if self.on_connection_fault is not None:
                try:
                    self.on_connection_fault(conn)
                except Exception:
                    log.exception("connection fault handler failed")

    async def _dispatch_one(self, conn: Connection, msg: Message) -> None:
        try:
            await self.dispatcher(conn, msg)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("%s: dispatch of %r failed",
                          self.entity_name, msg)

"""The orchestrator: one seeded timeline over live traffic.

ChaosEngine runs a :class:`~ceph_tpu.chaos.scenario.Scenario` against
a live Cluster: it prefills the pool, drives the open-loop
multi-tenant load through a :class:`ChaosTarget` (inline bit-exact
verification + the acked-write ledger), and walks the scenario's
event timeline firing hazards at their seeded offsets while the
invariant monitors watch.  After the last event it restores every
flag it touched (snapshot backstop), lets the cluster settle, then
runs the end-of-run judgments: report bounds, durability sweep, leak
audit.  The returned report leads with the seed — a violating run
replays from that number alone.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ceph_tpu.chaos.hazards import HAZARDS, Hazard
from ceph_tpu.chaos.monitors import (ChaosTarget, Violation,
                                     capture_worst_op, check_leaks,
                                     evaluate_report)
from ceph_tpu.chaos.scenario import Scenario
from ceph_tpu.common import flags
from ceph_tpu.loadgen.runner import run_open_loop
from ceph_tpu.loadgen.targets import RadosTarget

__all__ = ["ChaosEngine", "run_scenario"]

log = logging.getLogger(__name__)


def _conflict_key(kind: str, params: Dict[str, Any]) -> Optional[str]:
    """Hazards sharing one global lever must not overlap — the second
    start would save the first's injected value as its "previous" and
    restore chaos into the steady state.  Key such levers; the engine
    stops the incumbent before starting the newcomer."""
    if kind == "device_fail":
        return "flag:CEPH_TPU_INJECT_DEVICE_FAIL"
    if kind == "kill_switch":
        return f"flag:{params.get('flag', '')}"
    if kind in ("powercut", "drain", "straggler"):
        return f"{kind}:osd{params.get('osd')}"
    return None


class ChaosEngine:
    """One scenario run over a live cluster.  Reusable only per
    instance-per-run (monitors accumulate)."""

    def __init__(self, cluster, scenario: Scenario,
                 pool: str = "chaos", pool_size: int = 2,
                 pg_num: int = 16) -> None:
        self.cluster = cluster
        self.scenario = scenario
        self.pool = pool
        self.pool_size = pool_size
        self.pg_num = pg_num
        self.target: Optional[ChaosTarget] = None
        self.violations: List[Violation] = []
        self.events_fired: List[Dict[str, Any]] = []
        self._powercut_osds: List[int] = []
        self._sweep_pending = False

    # -- hazard context callbacks -----------------------------------------

    def note_powercut(self, osd: int) -> None:
        self._powercut_osds.append(osd)
        self._sweep_pending = True

    def revive_failed(self, osd: int) -> None:
        self.violations.append(Violation(
            "revive-failed",
            f"osd.{osd} failed to revive after power cut",
            {"osd": osd}))

    # -- run ----------------------------------------------------------------

    async def _ensure_pool(self):
        from ceph_tpu.rados.client import RadosError

        client = self.cluster.client
        if client.osdmap.lookup_pool(self.pool) < 0:
            try:
                await client.create_replicated_pool(
                    self.pool, size=self.pool_size, pg_num=self.pg_num)
            except RadosError:
                if client.osdmap.lookup_pool(self.pool) < 0:
                    raise
        return client.open_ioctx(self.pool)

    def _touched_flags(self) -> List[str]:
        out = {"CEPH_TPU_INJECT_DEVICE_FAIL"}
        for ev in self.scenario.events:
            if ev.hazard == "kill_switch":
                out.add(ev.params["flag"])
        return sorted(out)

    async def run(self) -> Dict[str, Any]:
        sc = self.scenario
        log.info("chaos: seed=%d duration=%.0fs events=%d "
                 "(replay with this seed)", sc.seed, sc.duration,
                 len(sc.events))
        io = await self._ensure_pool()
        self.target = ChaosTarget(RadosTarget(io), io, sc.object_size)
        await self.target.setup(sc.objects, sc.object_size)
        await self.cluster.wait_for_clean(timeout=30.0)

        snapshot = {n: flags.peek(n) for n in self._touched_flags()}
        flips_before = len(flags.flips())

        traffic = asyncio.get_running_loop().create_task(
            run_open_loop(self.target, sc.tenants, sc.duration,
                          seed=sc.seed,
                          per_tenant=[t.name for t in sc.tenants]))
        try:
            await self._run_timeline()
            report = await traffic
        finally:
            traffic.cancel()
            # snapshot backstop: whatever a hazard failed to restore
            for name, prev in snapshot.items():
                if flags.peek(name) != prev:
                    if prev is None:
                        flags.clear(name)
                    else:
                        flags.set_flag(name, prev)

        # settle, then judge: the leak monitors only mean something
        # once in-flight work has had time to retire
        await asyncio.sleep(sc.settle_s)
        try:
            await self.cluster.wait_for_clean(timeout=30.0)
        except TimeoutError:
            self.violations.append(Violation(
                "never-clean",
                "cluster failed to go clean after the storm"))

        self.violations.extend(evaluate_report(
            report, sc.p99_bounds, sc.rate_bounds))
        await self.target.durability_sweep()
        # inline monitors (bit-rot + sweep findings) accumulate on
        # the target; fold them in once
        self.violations.extend(self.target.violations)
        self.violations.extend(check_leaks(self.cluster))

        out: Dict[str, Any] = {
            "seed": sc.seed,
            "scenario": sc.to_dict(),
            "loadgen": report,
            "events_fired": list(self.events_fired),
            "powercuts": list(self._powercut_osds),
            "reads_verified": self.target.reads_verified,
            "acked_writes_swept": len(self.target.acked),
            "flag_flips": len(flags.flips()) - flips_before,
            "violations": [v.to_dict() for v in self.violations],
        }
        if self.violations:
            worst = capture_worst_op(self.cluster)
            if worst is not None:
                out["worst_op"] = worst
            log.error("chaos: %d violation(s); replay with seed=%d",
                      len(self.violations), sc.seed)
        return out

    async def _run_timeline(self) -> None:
        """Fire every scenario event at its seeded offset.  Actions
        are a merged (time, start|stop, hazard) walk; conflicting
        hazards (same global lever) pre-empt the incumbent."""
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        actions = []
        for ev in self.scenario.events:
            cls = HAZARDS.get(ev.hazard)
            if cls is None:
                raise ValueError(f"unknown hazard {ev.hazard!r}")
            h = cls(ev.params)
            actions.append((ev.start, 0, "start", h, ev))
            actions.append((ev.start + ev.duration, 1, "stop", h, ev))
        actions.sort(key=lambda a: (a[0], a[1]))
        active: Dict[str, Hazard] = {}
        for when, _tie, what, h, ev in actions:
            delay = (t0 + when) - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            key = _conflict_key(h.name, h.params)
            try:
                if what == "start":
                    incumbent = active.get(key) if key else None
                    if incumbent is not None and incumbent.active:
                        await incumbent.stop(self)
                    await h.start(self)
                    if key and h.active:
                        active[key] = h
                    self.events_fired.append(
                        {**ev.to_dict(), "fired_at": round(
                            loop.time() - t0, 3)})
                else:
                    await h.stop(self)
                    if key and active.get(key) is h:
                        del active[key]
                    if h.name == "powercut" and self._sweep_pending:
                        self._sweep_pending = False
                        await self.target.durability_sweep()
            except Exception as e:  # noqa: BLE001 — a hazard adapter
                # crashing must not abort the storm: record and go on
                log.exception("chaos: %s %s failed", what, h.name)
                self.violations.append(Violation(
                    "hazard-error",
                    f"{what} of {h.name} raised {type(e).__name__}: "
                    f"{e}", {"event": ev.to_dict()}))
        # storm over: force-stop anything still holding its lever
        for h in list(active.values()):
            if h.active:
                try:
                    await h.stop(self)
                except Exception:
                    log.exception("chaos: final stop of %s failed",
                                  h.name)


async def run_scenario(cluster, scenario: Scenario,
                       **kw) -> Dict[str, Any]:
    """One-call harness: engine + run, returns the report."""
    return await ChaosEngine(cluster, scenario, **kw).run()

"""Always-on invariant monitors for composed-chaos runs.

The monitors are the product here: a chaos run that "didn't crash"
proves nothing.  Each invariant is checked continuously (ChaosTarget,
inline with every op) or at deterministic barriers (the engine, after
settle):

- **zero client errors** — sheds are QoS doing its job; anything else
  surfacing to the client during a storm the system claims to mask is
  a violation,
- **bit-exact readback** — every read is compared against the seeded
  expected bytes inline; a recovery/repair/failover path returning
  plausible-but-wrong data is the worst storage failure mode,
- **durability** — every write acked before a power cut must read
  back after kill + revive + WAL replay,
- **bounded tails** — per-tenant p99 must stay under the scenario
  bound; a protected tenant starving under compound faults is an
  isolation failure even when all ops "succeed",
- **cluster-wide limit conformance** — a limit-L tenant spread over N
  primaries must complete ~L ops/s TOTAL (the dmClock delta/rho
  piggyback), not N x L,
- **no leaks** — after the storm settles: zero scheduler slots held,
  zero tracked ops live, zero breaker probes stuck half-open.

When a monitor fires it grabs the worst completed op's retained trace
tree (dump_op_trace shape) from the OSDs as the failure exemplar, so
a red run explains itself without a rerun.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

from ceph_tpu.loadgen.targets import (EBUSY, SheddedOp, Target,
                                      _payload, _write_payload)

__all__ = ["Violation", "ChaosTarget", "evaluate_report",
           "check_leaks", "capture_worst_op"]


class Violation:
    """One invariant breach, self-describing enough to file."""

    __slots__ = ("kind", "detail", "info")

    def __init__(self, kind: str, detail: str,
                 info: Optional[Dict[str, Any]] = None):
        self.kind = kind
        self.detail = detail
        self.info = dict(info or {})

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "detail": self.detail,
                **({"info": self.info} if self.info else {})}

    def __repr__(self) -> str:
        return f"Violation({self.kind}: {self.detail})"


@functools.lru_cache(maxsize=8)
def _expected_read(size: int) -> bytes:
    """The shared hot-set content (targets.setup writes
    _payload(size, seed=1) into every `lg-<i>`)."""
    return _payload(size, seed=1)


class ChaosTarget(Target):
    """Wraps a networked target: delegates the op mix, but serves
    read/ranged itself so every byte coming back is compared against
    the seeded expected content inline, and keeps the acked-write
    ledger the durability sweep checks after each power cut.

    Needs the wrapped target's IoCtx (`io`) because Target.op returns
    byte COUNTS — verification needs the bytes."""

    def __init__(self, inner: Target, io, object_size: int) -> None:
        self.inner = inner
        self.io = io
        self.object_size = int(object_size)
        self._objects = 0
        #: oid -> set of acceptable (size, slot) payloads.  Every
        #: write to lg-w-<tenant>-<slot> carries _write_payload(size,
        #: slot); sizes can differ per tenant spec, so the sweep
        #: accepts any payload this run ever acked for the oid.
        self.acked: Dict[str, set] = {}
        self.violations: List[Violation] = []
        self.reads_verified = 0

    async def setup(self, objects: int, object_size: int) -> None:
        await self.inner.setup(objects, object_size)
        self._objects = objects
        self.object_size = int(object_size)

    async def close(self) -> None:
        await self.inner.close()

    async def op(self, tenant: str, kind: str, obj: int,
                 size: int) -> int:
        if kind in ("read", "ranged"):
            return await self._verified_read(tenant, kind, obj, size)
        moved = await self.inner.op(tenant, kind, obj, size)
        if kind == "write":
            # only reached when the inner op ACKED (sheds/errors
            # raised past us): this write is now a durability promise
            slot = obj & 7
            self.acked.setdefault(f"lg-w-{tenant}-{slot}",
                                  set()).add((size, slot))
        return moved

    async def _verified_read(self, tenant: str, kind: str, obj: int,
                             size: int) -> int:
        from ceph_tpu.rados.client import RadosError, tenant_scope

        name = f"lg-{obj % max(self._objects, 1)}"
        try:
            with tenant_scope(tenant):
                if kind == "read":
                    off, ln = 0, None
                    data = await self.io.read(name)
                else:
                    off = size // 4
                    ln = max(size // 4, 1)
                    data = await self.io.read(name, offset=off,
                                              length=ln)
        except RadosError as e:
            if e.rc == EBUSY:
                raise SheddedOp(tenant) from e
            raise
        full = _expected_read(self.object_size)
        expect = full if ln is None else full[off:off + ln]
        if data != expect:
            self.violations.append(Violation(
                "bit-rot",
                f"{kind} of {name} returned {len(data)}B != expected "
                f"{len(expect)}B (first diff at "
                f"{_first_diff(data, expect)})",
                {"tenant": tenant, "object": name, "kind": kind,
                 "offset": off}))
        self.reads_verified += 1
        return len(data)

    async def durability_sweep(self) -> List[Violation]:
        """Read back every acked write and demand one of its acked
        payloads, bit-exact.  Run after each power-cut revive (the
        WAL-replay path) and once at scenario end."""
        from ceph_tpu.rados.client import RadosError

        out: List[Violation] = []
        for oid, wants in sorted(self.acked.items()):
            try:
                data = await self.io.read(oid)
            except RadosError as e:
                out.append(Violation(
                    "durability-lost",
                    f"acked object {oid} unreadable after revive "
                    f"(rc={e.rc})", {"object": oid}))
                continue
            if not any(data == _write_payload(size, slot)
                       for size, slot in wants):
                out.append(Violation(
                    "durability-corrupt",
                    f"acked object {oid} read back {len(data)}B "
                    f"matching none of {len(wants)} acked payloads",
                    {"object": oid,
                     "acked_sizes": sorted(s for s, _ in wants)}))
        self.violations.extend(out)
        return out


def _first_diff(a: bytes, b: bytes) -> int:
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return i
    return min(len(a), len(b))


def evaluate_report(report: Dict[str, Any],
                    p99_bounds: Dict[str, float],
                    rate_bounds: Dict[str, float]) -> List[Violation]:
    """Judge a finished loadgen report against the scenario bounds:
    zero client errors, per-tenant p99 ceilings (ms), and per-tenant
    completed-rate ceilings (the cluster-wide dmClock limit check)."""
    out: List[Violation] = []
    if report.get("errors", 0):
        out.append(Violation(
            "client-errors",
            f"{report['errors']} client-visible errors "
            f"(of {report.get('offered', 0)} offered)"))
    per = report.get("per_tenant", {})
    for name, bound in sorted(p99_bounds.items()):
        t = per.get(name)
        if t is None or t.get("count", 0) == 0:
            out.append(Violation(
                "tenant-starved",
                f"tenant {name} completed zero ops "
                f"(p99 bound {bound}ms unevaluable)",
                {"tenant": name}))
            continue
        if t.get("errors", 0):
            out.append(Violation(
                "client-errors",
                f"tenant {name}: {t['errors']} errors",
                {"tenant": name}))
        p99 = t.get("p99_ms")
        if p99 is not None and p99 > bound:
            out.append(Violation(
                "p99-exceeded",
                f"tenant {name} p99 {p99}ms > bound {bound}ms",
                {"tenant": name, "p99_ms": p99, "bound_ms": bound}))
    elapsed = max(report.get("elapsed_s", 0.0), 1e-9)
    for name, ceil in sorted(rate_bounds.items()):
        t = per.get(name)
        rate = (t or {}).get("completed", 0) / elapsed
        if rate > ceil:
            out.append(Violation(
                "limit-exceeded",
                f"tenant {name} completed {rate:.1f} ops/s > "
                f"cluster-wide ceiling {ceil:.1f} (per-OSD-only "
                f"limits let a spread tenant multiply its limit)",
                {"tenant": name, "rate": round(rate, 2),
                 "ceiling": ceil}))
    return out


def check_leaks(cluster) -> List[Violation]:
    """Post-settle resource audit over every live daemon: scheduler
    slots, tracked ops, breaker probes.  Anything nonzero after the
    storm + settle window is a leak some fault path forgot to
    release."""
    from ceph_tpu.common import circuit

    out: List[Violation] = []
    for osd_id, daemon in sorted(cluster.osds.items()):
        held = daemon.scheduler._in_flight
        if held:
            out.append(Violation(
                "leak-scheduler-slot",
                f"osd.{osd_id} scheduler holds {held} slots after "
                "settle", {"osd": osd_id, "held": held}))
        live = daemon.op_tracker.perf()["ops_in_flight"]
        if live:
            out.append(Violation(
                "leak-tracked-op",
                f"osd.{osd_id} has {live} tracked ops live after "
                "settle",
                {"osd": osd_id, "ops": live,
                 "dump": daemon.op_tracker.dump_in_flight()}))
    with circuit._reg_lock:
        brs = dict(circuit._breakers)
    for family, br in sorted(brs.items()):
        if br._probing:
            out.append(Violation(
                "leak-breaker-probe",
                f"breaker {family} still holds its half-open probe "
                "after settle", {"family": family}))
    return out


def capture_worst_op(cluster) -> Optional[Dict[str, Any]]:
    """The failure exemplar: scan every daemon's historic ring for the
    slowest completed op; when the tail policy retained its span tree,
    attach the full dump_op_trace doc.  Called when any monitor fires
    so a red run ships its own explanation."""
    worst: Optional[Dict[str, Any]] = None
    for osd_id, daemon in sorted(cluster.osds.items()):
        hist = daemon.op_tracker.dump_historic()
        for op in hist.get("ops", ()):
            if worst is None or op.get("duration", 0.0) > \
                    worst["op"].get("duration", 0.0):
                worst = {"osd": osd_id, "op": op}
    if worst is None:
        return None
    tid = worst["op"].get("trace_id", "")
    if tid:
        daemon = cluster.osds.get(worst["osd"])
        doc = daemon.op_tracker.get_trace(tid) if daemon else None
        if doc is not None:
            worst["trace"] = doc
    return worst

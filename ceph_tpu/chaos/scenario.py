"""Declarative chaos timelines, composed from one seeded RNG.

A scenario is data: a list of (hazard, start, duration, params)
events plus the traffic spec and the invariant bounds.  Everything
random — event placement, hazard targets, kill-switch choices, the
loadgen schedule — derives from ``Scenario.seed``, so a violating run
replays bit-for-bit from the seed printed in its report.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence

from ceph_tpu.loadgen.workload import TenantSpec

__all__ = ["HazardEvent", "Scenario", "compose",
           "DEFAULT_KILL_SWITCHES"]


#: the cross-mode flip set from the issue: each is a default-on fast
#: path with a behavioral-twin fallback, so flipping any of them
#: mid-traffic must be invisible to clients (results bit-identical,
#: zero errors)
DEFAULT_KILL_SWITCHES = (
    "CEPH_TPU_XSCHED",
    "CEPH_TPU_COMPUTE",
    "CEPH_TPU_NATIVE_XSCHED",
    "CEPH_TPU_MSR_REPAIR",
    "CEPH_TPU_INFERENCE",
)


class HazardEvent:
    """One timeline entry: fire `hazard` at `start` (seconds from
    scenario start), hold it for `duration`, with `params`."""

    __slots__ = ("hazard", "start", "duration", "params")

    def __init__(self, hazard: str, start: float, duration: float,
                 params: Optional[Dict[str, Any]] = None):
        self.hazard = hazard
        self.start = float(start)
        self.duration = float(duration)
        self.params = dict(params or {})

    def to_dict(self) -> Dict[str, Any]:
        return {"hazard": self.hazard, "start": round(self.start, 3),
                "duration": round(self.duration, 3),
                "params": dict(self.params)}

    def __repr__(self) -> str:
        return (f"HazardEvent({self.hazard!r}, t={self.start:.2f}"
                f"+{self.duration:.2f}, {self.params})")


class Scenario:
    """The replayable unit: seed + traffic + timeline + bounds."""

    def __init__(self, seed: int, duration: float,
                 tenants: Sequence[TenantSpec],
                 events: Sequence[HazardEvent],
                 p99_bounds: Optional[Dict[str, float]] = None,
                 rate_bounds: Optional[Dict[str, float]] = None,
                 objects: int = 32, object_size: int = 8192,
                 settle_s: float = 2.0):
        self.seed = int(seed)
        self.duration = float(duration)
        self.tenants = list(tenants)
        self.events = sorted(events, key=lambda e: e.start)
        # per-tenant invariant bounds; absent tenant = unmonitored
        self.p99_bounds = dict(p99_bounds or {})
        # cluster-wide completed-ops/s ceilings (the dmClock monitor:
        # a limit-L tenant spread over N primaries must not complete
        # more than ~L/s TOTAL — per-OSD mClock grants it N x L)
        self.rate_bounds = dict(rate_bounds or {})
        self.objects = int(objects)
        self.object_size = int(object_size)
        # post-traffic settle window before the leak monitors judge
        self.settle_s = float(settle_s)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seed": self.seed,
            "duration_s": self.duration,
            "tenants": [t.name for t in self.tenants],
            "events": [e.to_dict() for e in self.events],
            "p99_bounds": dict(self.p99_bounds),
            "rate_bounds": dict(self.rate_bounds),
            "objects": self.objects,
            "object_size": self.object_size,
        }


def _windows(rng: random.Random, duration: float, n: int,
             hold: float, lead: float = 0.5) -> List[float]:
    """n non-anchored start times in [lead, duration - hold]: jittered
    stratified placement so repeated hazards spread over the run
    instead of clustering at one instant."""
    if n <= 0:
        return []
    span = max(duration - hold - lead, 0.0)
    out = []
    for i in range(n):
        lo = lead + span * i / n
        hi = lead + span * (i + 1) / n
        out.append(rng.uniform(lo, hi))
    return out


def compose(seed: int, duration: float,
            tenants: Sequence[TenantSpec],
            osd_ids: Sequence[int],
            hazards: Sequence[str] = ("straggler", "device_fail",
                                      "kill_switch"),
            persistent_osds: Sequence[int] = (),
            protected_osds: Sequence[int] = (),
            kill_switches: Sequence[str] = DEFAULT_KILL_SWITCHES,
            p99_bounds: Optional[Dict[str, float]] = None,
            rate_bounds: Optional[Dict[str, float]] = None,
            objects: int = 32, object_size: int = 8192) -> Scenario:
    """Seeded scenario composer: one event per requested hazard kind
    per ~20 s of runtime, placed and parameterized by `seed`.

    - ``straggler``: messenger delay on a random OSD.
    - ``device_fail``: probabilistic device-fault injection
      (CEPH_TPU_INJECT_DEVICE_FAIL) cluster-wide.
    - ``host_down``: down_host=<H> via the same injection seam.
    - ``kill_switch``: flip a random switch from `kill_switches` off,
      restore after the hold.
    - ``powercut``: kill/revive a random OSD from `persistent_osds`
      (falls back to any non-protected OSD on MemStore clusters —
      then it exercises crash/revive, not disk durability).
    - ``drain``: mark a random OSD out (backfill off it under load),
      back in after the hold.

    `protected_osds` are never killed or drained (keep a quorum of
    primaries alive so the client can always make progress)."""
    rng = random.Random(seed)
    rounds = max(int(duration / 20.0), 1)
    events: List[HazardEvent] = []
    killable = [o for o in osd_ids if o not in set(protected_osds)]
    cuttable = [o for o in (persistent_osds or killable)
                if o not in set(protected_osds)]
    for kind in hazards:
        if kind == "straggler":
            hold = min(6.0, duration / 3)
            for t0 in _windows(rng, duration, rounds, hold):
                events.append(HazardEvent(
                    "straggler", t0, hold,
                    {"osd": rng.choice(list(osd_ids)),
                     "delay_s": round(rng.uniform(0.02, 0.08), 3)}))
        elif kind == "device_fail":
            hold = min(5.0, duration / 3)
            for t0 in _windows(rng, duration, rounds, hold):
                events.append(HazardEvent(
                    "device_fail", t0, hold,
                    {"spec": f"p={round(rng.uniform(0.05, 0.2), 3)}"}))
        elif kind == "host_down":
            hold = min(4.0, duration / 4)
            for t0 in _windows(rng, duration, rounds, hold):
                events.append(HazardEvent(
                    "device_fail", t0, hold,
                    {"spec": "down_host=%d" % rng.choice((0, 1))}))
        elif kind == "kill_switch":
            hold = min(4.0, duration / 3)
            for t0 in _windows(rng, duration,
                               max(rounds, 2), hold):
                events.append(HazardEvent(
                    "kill_switch", t0, hold,
                    {"flag": rng.choice(list(kill_switches)),
                     "value": "0"}))
        elif kind == "powercut":
            if not cuttable:
                continue
            # kill + detect + revive + re-peer needs real time: one
            # cut per ~30 s, held short so retries bridge it
            hold = min(3.0, duration / 5)
            n = max(int(duration / 30.0), 1)
            for t0 in _windows(rng, duration - 8.0, n, hold,
                               lead=2.0):
                events.append(HazardEvent(
                    "powercut", t0, hold,
                    {"osd": rng.choice(cuttable)}))
        elif kind == "drain":
            if not killable:
                continue
            hold = min(8.0, duration / 2)
            n = max(int(duration / 40.0), 1)
            for t0 in _windows(rng, duration - 4.0, n, hold,
                               lead=1.0):
                events.append(HazardEvent(
                    "drain", t0, hold,
                    {"osd": rng.choice(killable)}))
        else:
            raise ValueError(f"unknown hazard kind {kind!r}")
    return Scenario(seed, duration, tenants, events,
                    p99_bounds=p99_bounds, rate_bounds=rate_bounds,
                    objects=objects, object_size=object_size)

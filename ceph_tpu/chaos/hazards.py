"""Hazard adapters: each wraps one EXISTING injection seam behind a
uniform start/stop surface the engine can schedule.

No hazard invents a new fault path — each drives the same lever the
per-subsystem tests already prove in isolation (that is the point:
the composition is the only new variable):

- ``straggler``   -> ``ms_inject_internal_delays`` +
  ``_apply_msgr_injection()`` on a live daemon,
- ``device_fail`` -> ``CEPH_TPU_INJECT_DEVICE_FAIL`` (incl.
  ``down_host=``/``sick=`` modes) through the flags registry,
- ``kill_switch`` -> any registered ``CEPH_TPU_*`` flag flip,
- ``powercut``    -> ``Cluster.kill_osd``/``revive_osd`` (with
  ``CEPH_TPU_CRASH_INJECT`` armed on a persistent FaultStore this is
  a synthesized power-cut image, not a polite shutdown),
- ``drain``       -> ``osd out`` / ``osd in`` mon commands (backfill
  off/onto the OSD under load).

start()/stop() are idempotent per event and must leave the system
restorable: whatever they touched is put back in stop(), and the
engine re-asserts a pre-scenario flags snapshot afterwards as the
backstop.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

from ceph_tpu.common import flags

__all__ = ["Hazard", "HAZARDS"]

log = logging.getLogger(__name__)


class Hazard:
    """One scheduled activation of a hazard kind."""

    name = "hazard"

    def __init__(self, params: Dict[str, Any]):
        self.params = dict(params)
        self.active = False

    async def start(self, ctx) -> None:
        raise NotImplementedError

    async def stop(self, ctx) -> None:
        raise NotImplementedError


class StragglerHazard(Hazard):
    """Messenger-level delay on one OSD: every send on that daemon
    sleeps `delay_s` first (ms_inject_internal_delays role) — the
    hedge/straggler seam, now under composed load."""

    name = "straggler"

    def __init__(self, params):
        super().__init__(params)
        self._prev = 0

    async def start(self, ctx) -> None:
        osd = self.params["osd"]
        daemon = ctx.cluster.osds.get(osd)
        if daemon is None:
            return  # concurrently power-cut: nothing to slow down
        self._prev = daemon.config.get("ms_inject_internal_delays", 0)
        daemon.config["ms_inject_internal_delays"] = \
            self.params.get("delay_s", 0.05)
        daemon._apply_msgr_injection()
        self.active = True

    async def stop(self, ctx) -> None:
        osd = self.params["osd"]
        daemon = ctx.cluster.osds.get(osd)
        if daemon is None or not self.active:
            return
        daemon.config["ms_inject_internal_delays"] = self._prev
        daemon._apply_msgr_injection()
        self.active = False


class DeviceFailHazard(Hazard):
    """Cluster-wide device/host fault injection: the spec string goes
    straight into CEPH_TPU_INJECT_DEVICE_FAIL (re-read per dispatch),
    so ``p=0.1``, ``down_host=1``, ``sick=3`` all ride here."""

    name = "device_fail"

    def __init__(self, params):
        super().__init__(params)
        self._prev = None

    async def start(self, ctx) -> None:
        self._prev = flags.peek("CEPH_TPU_INJECT_DEVICE_FAIL")
        flags.set_flag("CEPH_TPU_INJECT_DEVICE_FAIL",
                       self.params["spec"])
        self.active = True

    async def stop(self, ctx) -> None:
        if not self.active:
            return
        if self._prev is None:
            flags.clear("CEPH_TPU_INJECT_DEVICE_FAIL")
        else:
            flags.set_flag("CEPH_TPU_INJECT_DEVICE_FAIL", self._prev)
        self.active = False


class KillSwitchHazard(Hazard):
    """Live cross-mode flip: force a registered kill switch to
    `value` (default \"0\": fall back to the behavioral twin), restore
    on stop.  Clients must not be able to tell."""

    name = "kill_switch"

    def __init__(self, params):
        super().__init__(params)
        self._prev = None

    async def start(self, ctx) -> None:
        flag = self.params["flag"]
        self._prev = flags.peek(flag)
        flags.set_flag(flag, str(self.params.get("value", "0")))
        self.active = True

    async def stop(self, ctx) -> None:
        if not self.active:
            return
        flag = self.params["flag"]
        if self._prev is None:
            flags.clear(flag)
        else:
            flags.set_flag(flag, self._prev)
        self.active = False


class PowercutHazard(Hazard):
    """Kill an OSD without clean shutdown, revive it after the hold.
    On a persistent FaultStore cluster with CEPH_TPU_CRASH_INJECT the
    kill synthesizes a power-cut disk image; the revive remounts and
    replays the WAL — the durability monitor then checks every
    acked-before-cut write."""

    name = "powercut"

    async def start(self, ctx) -> None:
        osd = self.params["osd"]
        if osd not in ctx.cluster.osds:
            return  # already down (overlapping cut): skip
        await ctx.cluster.kill_osd(osd)
        ctx.note_powercut(osd)
        self.active = True

    async def stop(self, ctx) -> None:
        if not self.active:
            return
        osd = self.params["osd"]
        try:
            await ctx.cluster.revive_osd(osd)
            await ctx.cluster.wait_for_osd_up(osd, timeout=20.0)
        except Exception:
            log.exception("chaos: revive of osd.%d failed", osd)
            ctx.revive_failed(osd)
        self.active = False


class DrainHazard(Hazard):
    """Elasticity: mark an OSD out (CRUSH reweights, data backfills
    off it while client load keeps flowing), back in on stop (it
    backfills back).  The osd_max_backfills throttle is what keeps
    this survivable."""

    name = "drain"

    async def start(self, ctx) -> None:
        osd = self.params["osd"]
        rc, _out = await ctx.cluster.client.mon_command(
            {"prefix": "osd out", "osd": osd})
        if rc == 0:
            self.active = True
        else:
            log.warning("chaos: osd out %d rc=%d", osd, rc)

    async def stop(self, ctx) -> None:
        if not self.active:
            return
        osd = self.params["osd"]
        rc, _out = await ctx.cluster.client.mon_command(
            {"prefix": "osd in", "osd": osd})
        if rc != 0:
            log.warning("chaos: osd in %d rc=%d", osd, rc)
        self.active = False


HAZARDS = {
    h.name: h for h in (StragglerHazard, DeviceFailHazard,
                        KillSwitchHazard, PowercutHazard,
                        DrainHazard)
}

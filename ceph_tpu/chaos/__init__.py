"""Compound-chaos engine: composed fault orchestration with
cluster-wide QoS and always-on invariant monitors (ROADMAP item 6).

Every hazard this tree survives is proven in isolation — stragglers
(test_hedge), device/host faults (test_device_breaker, meshbench),
power cuts (test_crash_consistency), kill-switch flips (per-subsystem
tests).  Production hits them all at once.  This package composes the
EXISTING injectors into continuous scenarios over open-loop
multi-tenant traffic, with invariant monitors that never sleep:

- zero client-visible errors (sheds are QoS, not errors),
- bit-exact readback of every read against the seeded expected bytes,
- durability: an acked write survives a power-cut kill/revive,
- per-tenant p99 bounds and cluster-wide limit conformance
  (the dmClock delta/rho piggyback, CEPH_TPU_DMCLOCK),
- no leaked scheduler slots / tracked ops / breaker probes after
  the storm passes.

Determinism is the design center: a :class:`~ceph_tpu.chaos.scenario.
Scenario` is a declarative timeline (hazard, start, duration, params)
drawn from ONE seeded RNG, and the loadgen schedule derives from the
same seed — any violation replays from the printed seed alone.  When
a monitor fires, it captures the worst op's full ``dump_op_trace``
tree from the OSDs as the failure exemplar.
"""

from ceph_tpu.chaos.engine import ChaosEngine, run_scenario
from ceph_tpu.chaos.hazards import HAZARDS, Hazard
from ceph_tpu.chaos.monitors import ChaosTarget, Violation
from ceph_tpu.chaos.scenario import HazardEvent, Scenario, compose

__all__ = [
    "ChaosEngine", "run_scenario", "HAZARDS", "Hazard",
    "ChaosTarget", "Violation", "HazardEvent", "Scenario", "compose",
]

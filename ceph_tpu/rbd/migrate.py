"""RBD deep-copy and live migration.

Reference parity:
- deep-copy (/root/reference/src/librbd/deep_copy/, `rbd deep cp`):
  copy an image INCLUDING its snapshot history — each snapshot is
  re-created on the destination with the data that was visible at
  that snapshot, replayed oldest-first as delta passes over a moving
  head (SnapshotCopyRequest + ObjectCopyRequest roles).  Works across
  pools AND across clusters (src/dst are just IoCtxs).
- migration (/root/reference/src/librbd/api/Migration.cc, `rbd
  migration prepare/execute/commit/abort`): move an image to another
  pool while it stays readable — the destination is linked to the
  source through the PARENT machinery (the reference literally models
  the migration source as a parent), so reads fall through and
  execute() is a flatten.  Re-design simplifications, documented:
  the source is write-fenced by a header flag rather than hidden
  behind the destination's name, clients open the DESTINATION name
  after prepare, and snapshotted images must use deep_copy (offline)
  instead — replaying snapshot history into a destination that is
  concurrently taking new writes needs write-at-snap-context
  machinery the head-only path avoids.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ceph_tpu.rados.client import IoCtx, ObjectNotFound, RadosError
from ceph_tpu.rbd import (
    RBD,
    Image,
    _header,
    _header_lock,
    _header_unlock,
)

EROFS = -30
EINVAL = -22
EBUSY = -16


async def deep_copy(src_ioctx: IoCtx, src_name: str,
                    dst_ioctx: IoCtx, dst_name: str,
                    data_pool: Optional[str] = None,
                    concurrency: int = 8) -> str:
    """Copy src -> dst with full snapshot history; returns the new
    image id.  Delta passes: each snapshot (ascending id), then the
    head — an object range is written only when it differs from the
    previous pass's content, so unchanged data moves once."""
    rbd = RBD()
    src = await rbd.open(src_ioctx, src_name)
    feats = set(src.meta.get("features", []))
    # journaling is enabled AFTER the copy: there are no concurrent
    # writers to order during it, and journaling each bulk write
    # would move every byte twice (journal event + data object)
    dst_id = await rbd.create(
        dst_ioctx, dst_name, size=0, order=src.meta["order"],
        data_pool=src.meta.get("data_pool")
        if data_pool is None else data_pool,
        exclusive_lock="exclusive-lock" in feats,
        object_map="object-map" in feats)
    dst = await rbd.open(dst_ioctx, dst_name)
    snaps = sorted(src.meta["snaps"].items(),
                   key=lambda kv: kv[1]["id"])
    passes = [(name, s["size"], bool(s.get("protected")))
              for name, s in snaps]
    passes.append((None, src.size(), False))
    objsz = src.object_size
    prev_reader: Optional[Image] = None
    prev_size = 0
    sem = asyncio.Semaphore(concurrency)
    try:
        for snap_name, size, protected in passes:
            # the first pass reuses the probe handle; later passes
            # need a second concurrent handle (prev snap + this one)
            reader = src if prev_reader is None \
                else await rbd.open(src_ioctx, src_name)
            reader.snap_set(snap_name)
            if dst.size() != size:
                await dst.resize(size)

            sparse_ok = not src._has_parent()

            async def _absent(img: Image, objno: int) -> bool:
                from ceph_tpu.rbd import _data

                try:
                    # stat resolves at the handle's read snap, like
                    # any read op
                    await img.data_ioctx.stat(_data(img.id, objno))
                    return False
                except ObjectNotFound:
                    return True

            async def one(off: int, span: int, rd=reader) -> None:
                async with sem:
                    objno = off // objsz
                    if sparse_ok and await _absent(rd, objno) and (
                            prev_reader is None
                            or off >= prev_size
                            or await _absent(prev_reader, objno)):
                        # absent in BOTH passes: nothing changed and
                        # nothing to write — a sparse image skips the
                        # two full-object reads (parent-backed images
                        # cannot skip: absent still reads through)
                        return
                    cur = await rd.read(off, span)
                    if prev_reader is not None and off < prev_size:
                        old = await prev_reader.read(
                            off, min(span, prev_size - off))
                        old = old + bytes(span - len(old))
                    else:
                        old = bytes(span)
                    if cur != old:
                        await dst.write(off, cur)

            await asyncio.gather(*(
                one(off, min(objsz, size - off))
                for off in range(0, size, objsz)))
            if snap_name is not None:
                await dst.snap_create(snap_name)
                if protected:
                    await dst.snap_protect(snap_name)
            if prev_reader is not None:
                await prev_reader.close()  # retired as diff base
            prev_reader, prev_size = reader, size
        if "journaling" in feats:
            dst.meta["features"] = sorted(
                set(dst.meta["features"]) | {"journaling"})
            await dst._save()
    finally:
        if prev_reader is not None:
            await prev_reader.close()
        await dst.close()
    return dst_id


# -- migration (Migration.cc prepare/execute/commit/abort) ----------------


async def migration_prepare(src_ioctx: IoCtx, src_name: str,
                            dst_ioctx: IoCtx, dst_name: str,
                            data_pool: Optional[str] = None) -> str:
    """Create the destination linked to the source via the parent
    machinery and write-fence the source.  Clients switch to the
    destination name; reads of not-yet-copied data fall through."""
    import json as _json

    rbd = RBD()
    src = await rbd.open(src_ioctx, src_name)
    if src.meta["snaps"]:
        raise RadosError(EINVAL, "snapshotted image: use deep_copy"
                                 " (offline) instead")
    if src.meta.get("migration"):
        raise RadosError(EBUSY, f"{src_name!r} already migrating")
    # the reference refuses to prepare an in-use image (Migration.cc
    # checks watchers); the analog here is a held exclusive lock.
    # Images WITHOUT exclusive-lock have no open-ness signal — as
    # with the reference's requirement, the operator must quiesce
    # writers first (pre-prepare handles that never refresh cannot
    # be fenced).
    if "exclusive-lock" in src.meta.get("features", []):
        try:
            info = _json.loads((await src_ioctx.execute(
                _header(src.id), "lock", "get_info",
                _json.dumps({"name": Image.LOCK_NAME})
                .encode())).decode())
            if info.get("lockers"):
                raise RadosError(EBUSY,
                                 f"{src_name!r} is in use"
                                 " (exclusive lock held)")
        except RadosError as e:
            if e.rc == EBUSY:
                raise
    feats = set(src.meta.get("features", []))
    dst_id = await rbd.create(
        dst_ioctx, dst_name, size=src.size(),
        order=src.meta["order"],
        data_pool=src.meta.get("data_pool")
        if data_pool is None else data_pool,
        exclusive_lock="exclusive-lock" in feats,
        object_map="object-map" in feats,
        journaling="journaling" in feats)
    dst = Image(dst_ioctx, dst_name, dst_id)
    await dst.refresh()
    dst.meta["parent"] = {
        "pool_id": src_ioctx.pool_id, "image_id": src.id,
        "snap_name": None, "snap_id": None,
        "overlap": src.size(), "migration": True}
    dst.meta["features"] = sorted(
        set(dst.meta["features"]) | {"layering"})
    dst.meta["migration_source"] = {
        "pool_id": src_ioctx.pool_id, "image_id": src.id,
        "name": src_name, "state": "prepared"}
    await dst._save()
    # child registration + write fence on the source, under its
    # header lock (the clone() discipline): remove(src) now refuses
    # (dependent child) and writers get EROFS on their next header
    # refresh.  On ANY failure the half-made destination is rolled
    # back (clone()'s except-cleanup discipline) — a dst with a
    # parent link but no child record would break permanently when
    # the unfenced source is removed.
    try:
        cookie = await _header_lock(src_ioctx, src.id)
        try:
            await src.refresh()
            src.meta.setdefault("children", []).append(
                {"pool_id": dst_ioctx.pool_id, "image_id": dst_id,
                 "snap_name": None})
            src.meta["migration"] = {"dst_pool": dst_ioctx.pool_id,
                                     "dst_id": dst_id,
                                     "state": "prepared"}
            await src._save()
        finally:
            await _header_unlock(src_ioctx, src.id, cookie)
    except Exception:
        dst.meta.pop("parent", None)  # plain remove, no deregister
        dst.meta.pop("migration_source", None)
        await dst._save()
        try:
            await rbd.remove(dst_ioctx, dst_name)
        except Exception:
            pass
        raise
    return dst_id


async def migration_execute(dst_ioctx: IoCtx, dst_name: str,
                            image: Optional[Image] = None) -> None:
    """Copy everything down (flatten through the migration link).
    For exclusive-lock images with a LIVE writer, pass that writer's
    open handle as `image` — flatten then runs under the lock it
    already holds (the reference executes migration inside librbd for
    the same reason); a second handle would wait out the holder and
    fail EBUSY."""
    rbd = RBD()
    dst = image if image is not None \
        else await rbd.open(dst_ioctx, dst_name)
    ms = dst.meta.get("migration_source")
    if ms is None:
        raise RadosError(EINVAL, f"{dst_name!r} is not a migration"
                                 " destination")
    try:
        if dst.meta.get("parent"):
            await dst.flatten()
        ms["state"] = "executed"
        dst.meta["migration_source"] = ms
        await dst._save()
        # reflect state on the (fenced) source header for operators
        src_io = IoCtx(dst_ioctx.client, ms["pool_id"])
        src = Image(src_io, ms["name"], ms["image_id"])
        try:
            await src.refresh()
            if src.meta.get("migration"):
                src.meta["migration"]["state"] = "executed"
                await src._save()
        except Exception:
            pass  # source header gone: commit already ran elsewhere
    finally:
        if image is None:  # never close a caller-owned handle
            await dst.close()


async def migration_commit(dst_ioctx: IoCtx, dst_name: str) -> None:
    """Finalize: delete the drained source, clear the link."""
    rbd = RBD()
    dst = await rbd.open(dst_ioctx, dst_name)
    ms = dst.meta.get("migration_source")
    if ms is None:
        raise RadosError(EINVAL, f"{dst_name!r} is not a migration"
                                 " destination")
    if ms.get("state") != "executed":
        raise RadosError(EINVAL, "execute the migration first")
    src_io = IoCtx(dst_ioctx.client, ms["pool_id"])
    src = Image(src_io, ms["name"], ms["image_id"])
    try:
        await src.refresh()
        # drop the fence so remove() may proceed, then delete
        src.meta.pop("migration", None)
        await src._save()
        await rbd.remove(src_io, ms["name"])
    except ObjectNotFound:
        pass  # already removed: idempotent commit retry.  Any OTHER
        # failure must propagate BEFORE migration_source is cleared,
        # or the orphaned (possibly still fenced) source loses its
        # only retry path
    dst.meta.pop("migration_source", None)
    await dst._save()
    await dst.close()


async def migration_abort(dst_ioctx: IoCtx, dst_name: str) -> None:
    """Back out: drop the destination, unfence the source."""
    rbd = RBD()
    dst = await rbd.open(dst_ioctx, dst_name)
    ms = dst.meta.get("migration_source")
    if ms is None:
        raise RadosError(EINVAL, f"{dst_name!r} is not a migration"
                                 " destination")
    if ms.get("state") == "executed":
        raise RadosError(EINVAL, "already executed: commit or keep")
    await dst.close()
    # unfence the source FIRST: if it fails, dst still exists and
    # abort can be retried — the reverse order would strand a
    # permanently write-fenced source with no remaining handle on it
    src_io = IoCtx(dst_ioctx.client, ms["pool_id"])
    src = Image(src_io, ms["name"], ms["image_id"])
    try:
        await src.refresh()
        src.meta.pop("migration", None)
        await src._save()
    except ObjectNotFound:
        pass  # source already gone: nothing to unfence
    await rbd.remove(dst_ioctx, dst_name)  # deregisters the child

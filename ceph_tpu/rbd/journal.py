"""RBD image journaling: write-ahead event log per image.

Reference parity: the generic journaler (/root/reference/src/journal/
Journaler.h — numbered journal objects, append position, commit
position, trimming) specialized for images the way librbd/journal/
does: every mutating image op is recorded as an event BEFORE it is
applied to the data objects, so a crash between journal append and
data apply replays the event on next open (librbd::Journal replay),
and an rbd-mirror peer can tail the event stream to replicate the
image (tools/rbd_mirror role — see ceph_tpu.rbd.mirror).

Re-design notes: the reference splays entries across K objects for
parallel append bandwidth; this build keeps ONE active chunk object
(appends in an asyncio daemon serialize anyway) with size-based
rollover, and tracks {first, active, committed} in a small header doc.
Entries are versioned encoder blocks, so chunks scan forward without
a separate index and can grow fields.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict

from ceph_tpu.common import lockdep
from ceph_tpu.common.encoding import DecodeError, Decoder, Encoder

DEFAULT_CHUNK_MAX = 4 << 20  # rollover threshold per journal object


def _hdr(image_id: str) -> str:
    return f"rbd_journal.{image_id}"


def _chunk(image_id: str, n: int) -> str:
    return f"rbd_journal.{image_id}.{n:08x}"


def encode_event(seq: int, ev: Dict[str, Any]) -> bytes:
    enc = Encoder()
    enc.start(1, 1)
    enc.u64(seq)
    enc.string(ev.get("op", ""))
    enc.u64(int(ev.get("offset", 0)))
    enc.u64(int(ev.get("length", 0)))
    enc.bytes(bytes(ev.get("data", b"")))
    enc.string(ev.get("snap_name", ""))
    enc.u64(int(ev.get("size", 0)))
    enc.finish()
    return enc.to_bytes()


def decode_events(raw: bytes) -> list:
    dec = Decoder(raw)
    out = []
    while dec.remaining() > 0:
        try:
            dec.start(1)
            ev = {"seq": dec.u64(), "op": dec.string(),
                  "offset": dec.u64(), "length": dec.u64(),
                  "data": dec.bytes(), "snap_name": dec.string(),
                  "size": dec.u64()}
            dec.finish()
        except DecodeError:
            # torn tail from a crashed append: everything before it is
            # intact (entries are self-delimiting); the tail is the
            # un-acked event whose op never returned — drop it
            break
        out.append(ev)
    return out


class ImageJournal:
    """One image's event journal over its metadata ioctx."""

    def __init__(self, ioctx, image_id: str,
                 chunk_max: int = DEFAULT_CHUNK_MAX):
        self.ioctx = ioctx
        self.image_id = image_id
        self.chunk_max = chunk_max
        self.hdr: Dict[str, Any] = {}
        self.seq = 0          # last allocated
        self._active_size = 0
        self._append_lock = lockdep.Lock("journal.append")
        # out-of-order completions (concurrent writes): the commit
        # POSITION only advances over a CONTIGUOUS prefix — marking
        # seq N committed while N-1 is still applying must not let a
        # crash skip N-1's replay (librbd's commit-position tracker)
        self._done: set = set()

    # -- header ------------------------------------------------------------

    async def _load_hdr(self) -> None:
        try:
            raw = await self.ioctx.read(_hdr(self.image_id))
            self.hdr = json.loads(raw.decode())
        except Exception:
            self.hdr = {"first": 0, "active": 0, "committed": 0,
                        "chunk_last": {}}

    async def _save_hdr(self) -> None:
        await self.ioctx.write_full(
            _hdr(self.image_id), json.dumps(self.hdr).encode())

    async def open(self) -> None:
        """Bind to the on-disk journal: scan the active chunk to find
        the true last seq (the header only records it on rollover —
        per-append header writes would double every journal I/O)."""
        await self._load_hdr()
        self.seq = int(self.hdr.get("committed", 0))
        for n_str, last in self.hdr.get("chunk_last", {}).items():
            self.seq = max(self.seq, int(last))
        raw = await self._read_chunk(self.hdr["active"])
        self._active_size = len(raw)
        for ev in decode_events(raw):
            self.seq = max(self.seq, ev["seq"])

    async def _read_chunk(self, n: int) -> bytes:
        try:
            return await self.ioctx.read(_chunk(self.image_id, n))
        except Exception:
            return b""

    # -- append / commit / trim -------------------------------------------

    async def append(self, ev: Dict[str, Any]) -> int:
        """Journal one event; returns its seq once DURABLE (the
        write-ahead contract: callers apply the mutation only after
        this returns)."""
        async with self._append_lock:
            self.seq += 1
            seq = self.seq
            blob = encode_event(seq, ev)
            if self._active_size + len(blob) > self.chunk_max and \
                    self._active_size > 0:
                # rollover: seal the active chunk (record its last
                # seq for trim adjudication), open the next
                self.hdr.setdefault("chunk_last", {})[
                    str(self.hdr["active"])] = seq - 1
                self.hdr["active"] += 1
                self._active_size = 0
                await self._save_hdr()
            await self.ioctx.append(
                _chunk(self.image_id, self.hdr["active"]), blob)
            self._active_size += len(blob)
            return seq

    async def commit(self, seq: int) -> None:
        """Advance the commit position: events <= seq are applied to
        the image and need no replay.  Persisted lazily-but-monotonic;
        a stale commit pointer only means harmless re-replay of
        idempotent events (the reference's client commit position has
        the same at-least-once contract)."""
        committed = int(self.hdr.get("committed", 0))
        if seq <= committed:
            return
        self._done.add(seq)
        new = committed
        while new + 1 in self._done:
            new += 1
            self._done.discard(new)
        if new == committed:
            return  # a gap below seq is still applying
        self.hdr["committed"] = new
        await self._save_hdr()
        await self._trim()

    async def _trim(self) -> None:
        """Remove chunks whose every entry is committed AND below the
        mirror floor (peers registered in the header pin the stream
        the way the reference's registered journal clients do)."""
        floor = int(self.hdr.get("committed", 0))
        for peer_seq in self.hdr.get("peers", {}).values():
            floor = min(floor, int(peer_seq))
        chunk_last = self.hdr.get("chunk_last", {})
        removed = False
        for n_str in sorted(chunk_last, key=int):
            if int(chunk_last[n_str]) > floor:
                break
            try:
                await self.ioctx.remove(_chunk(self.image_id,
                                               int(n_str)))
            except Exception:
                pass
            del chunk_last[n_str]
            self.hdr["first"] = int(n_str) + 1
            removed = True
        if removed:
            await self._save_hdr()

    # -- replay / tail -----------------------------------------------------

    async def events_since(self, seq: int) -> list:
        """Every journaled event with seq > the given position, in
        order (the Journaler replay/ObjectPlayer role)."""
        out = []
        for n in range(int(self.hdr.get("first", 0)),
                       int(self.hdr.get("active", 0)) + 1):
            raw = await self._read_chunk(n)
            for ev in decode_events(raw):
                if ev["seq"] > seq:
                    out.append(ev)
        return out

    # -- mirror-peer positions (journal client registry role) -------------

    async def peer_get(self, peer: str) -> int:
        await self._load_hdr()
        return int(self.hdr.get("peers", {}).get(peer, 0))

    async def peer_set(self, peer: str, seq: int) -> None:
        self.hdr.setdefault("peers", {})[peer] = int(seq)
        await self._save_hdr()
        await self._trim()

    async def destroy(self) -> None:
        for n in range(int(self.hdr.get("first", 0)),
                       int(self.hdr.get("active", 0)) + 1):
            try:
                await self.ioctx.remove(_chunk(self.image_id, n))
            except Exception:
                pass
        try:
            await self.ioctx.remove(_hdr(self.image_id))
        except Exception:
            pass

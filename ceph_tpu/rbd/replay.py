"""rbd-replay role: record an image workload, replay it elsewhere.

Reference parity: /root/reference/src/rbd_replay/ — the reference
captures librbd API traces (lttng) into a .rbd-replay file and
`rbd-replay` re-executes them against another image, preserving
relative timing (--pacing) for performance studies and regression
reproduction.

Re-design: the trace is JSONL — one op per line {ts, op, offset,
length} (write payloads are synthesized on replay, as the reference's
anonymized traces do; a `data` field carries real bytes when fidelity
matters).  Recording is a transparent Image wrapper (no lttng in this
runtime — the API seam is the tracepoint), and `rbd bench --trace`
records its generated workload directly."""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional, TextIO

from ceph_tpu.rbd import Image


class ImageTracer:
    """Wraps an open Image; every data-path op is executed AND logged
    (the lttng tracepoint role at the API seam)."""

    def __init__(self, image: Image, out: TextIO,
                 record_data: bool = False):
        self.image = image
        self._out = out
        self._record_data = record_data
        self._t0 = time.perf_counter()

    def _log(self, op: str, **fields) -> None:
        rec = {"ts": round(time.perf_counter() - self._t0, 6),
               "op": op}
        rec.update(fields)
        self._out.write(json.dumps(rec) + "\n")

    async def write(self, offset: int, data: bytes) -> int:
        n = await self.image.write(offset, data)
        extra = {"data": data.hex()} if self._record_data else {}
        self._log("write", offset=offset, length=len(data), **extra)
        return n

    async def read(self, offset: int, length: int) -> bytes:
        buf = await self.image.read(offset, length)
        self._log("read", offset=offset, length=length)
        return buf

    async def discard(self, offset: int, length: int) -> None:
        await self.image.discard(offset, length)
        self._log("discard", offset=offset, length=length)

    async def resize(self, new_size: int) -> None:
        await self.image.resize(new_size)
        self._log("resize", size=new_size)

    async def close(self) -> None:
        self._out.flush()
        await self.image.close()


def _payload(length: int, offset: int) -> bytes:
    """Deterministic synthetic payload (anonymized-trace replay):
    offset-seeded so re-replays are reproducible."""
    pat = (offset & 0xFF).to_bytes(1, "big")
    return pat * length


async def _as_aiter(lines):
    """Normalize a sync or async line iterable to async, so callers
    can stream (fileio.iter_lines) or pass a plain list."""
    if hasattr(lines, "__aiter__"):
        async for line in lines:
            yield line
    else:
        for line in lines:
            yield line


async def replay_trace(lines, image: Image, speed: float = 1.0,
                       max_lag: float = 30.0) -> Dict[str, Any]:
    """Re-execute a recorded trace against `image`, pacing ops by
    their recorded timestamps scaled by 1/speed (speed=0 -> as fast
    as possible).  `lines` may be any sync or async iterable of trace
    lines.  Returns {ops, reads, writes, elapsed_s}."""
    stats = {"ops": 0, "reads": 0, "writes": 0}
    t0 = time.perf_counter()   # pacing clock (rebased on capped gaps)
    t_start = t0               # wall clock (never rebased)
    async for line in _as_aiter(lines):
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if speed > 0:
            due = rec.get("ts", 0.0) / speed
            lag = due - (time.perf_counter() - t0)
            if lag > 0:
                # CAP a huge recorded idle gap and REBASE the clock
                # by the forgiven part: a plain skip would disable
                # pacing for the rest of the trace, a plain cap would
                # make every later op pay max_lag again
                await asyncio.sleep(min(lag, max_lag))
                if lag > max_lag:
                    t0 -= lag - max_lag
        op = rec.get("op")
        if op == "write":
            data = bytes.fromhex(rec["data"]) if "data" in rec \
                else _payload(int(rec["length"]), int(rec["offset"]))
            await image.write(int(rec["offset"]), data)
            stats["writes"] += 1
        elif op == "read":
            await image.read(int(rec["offset"]),
                             int(rec["length"]))
            stats["reads"] += 1
        elif op == "discard":
            await image.discard(int(rec["offset"]),
                                int(rec["length"]))
        elif op == "resize":
            await image.resize(int(rec["size"]))
        else:
            continue  # unknown op: skip (forward compatibility)
        stats["ops"] += 1
    stats["elapsed_s"] = round(time.perf_counter() - t_start, 4)
    return stats

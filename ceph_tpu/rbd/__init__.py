"""RBD role: block images striped over rados objects.

Reference parity: librbd (/root/reference/src/librbd/ — librbd.cc API
surface, ObjectMap/image layout in src/librbd/image/CreateRequest.cc):

- an image is an id, a header object `rbd_header.<id>` (metadata in
  omap: size, order, snapshots), and data objects
  `rbd_data.<id>.<objectno:016x>`, each covering 2^order bytes;
- `rbd_directory` maps name <-> id (src/cls/rbd dir_* methods);
- byte-range I/O maps to object extents (the Striper role,
  src/osdc/Striper.cc:file_to_extents) and fans out in parallel —
  absent data objects read as zeros (sparse images);
- erasure-coded backends use a separate data pool (`rbd create
  --data-pool`, librbd data_pool feature): metadata/omap stays on a
  replicated pool (omap is unsupported on EC pools, here as in the
  reference) while data objects live on the EC pool;
- the EXCLUSIVE LOCK feature (librbd::ExclusiveLock,
  src/librbd/ExclusiveLock.h): a writer auto-acquires a cls_lock on
  the header object on its first mutation and renews it on a
  heartbeat; a second writer is refused (EBUSY) while the holder is
  live and breaks the lock only after its renewal counter goes stale
  by the CHALLENGER's own clock — two clients can no longer interleave
  the header's read-modify-write (snapc/size updates);
- snapshots ride the pool's self-managed snap machinery: snap_create
  allocates a snap id and folds it into the image's write snap
  context, so ordinary clone-on-write in the OSDs preserves the
  snapshot state; reading at a snap sets the read-snap on the data
  ioctx (librbd::Image::snap_set).

- LAYERING (librbd clone v2, src/librbd/ parent I/O through ImageCtx
  and cls_rbd children records): a clone is a new image whose header
  carries a parent link {pool, image, snap, overlap}; reads of absent
  child objects fall through to the parent AT THE SNAP (up to the
  overlap), the first partial write to an absent object COPIES UP the
  parent's object content, and flatten() copies every remaining
  parent-backed object down and severs the link.  Clone requires a
  PROTECTED snapshot; unprotect refuses while children exist
  (cls_rbd children bookkeeping lives in the parent's header meta).
- OBJECT MAP (librbd::ObjectMap, src/librbd/object_map/): a 2-bit
  per-object state bitmap in `rbd_object_map.<id>` (requires
  exclusive-lock, as in the reference).  Writes mark objects EXISTS
  before data lands; discard/remove mark NONEXISTENT; reads skip the
  data round-trip for NONEXISTENT objects, and image remove deletes
  only mapped objects instead of probing every index.

The reference keeps image state in cls_rbd stored procedures; here the
same records live directly in header-object omap — the cls-lite layer
can host them later without changing the layout.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional

from ceph_tpu.common import lockdep
from ceph_tpu.rados.client import IoCtx, ObjectNotFound, RadosError

RBD_DIRECTORY = "rbd_directory"
RBD_TRASH = "rbd_trash"
DEFAULT_ORDER = 22  # 4 MiB objects, the reference default


def _header(image_id: str) -> str:
    return f"rbd_header.{image_id}"


def _data(image_id: str, objectno: int) -> str:
    return f"rbd_data.{image_id}.{objectno:016x}"


def _object_map(image_id: str) -> str:
    return f"rbd_object_map.{image_id}"


# object-map states (ObjectMap.h OBJECT_*)
OM_NONEXISTENT = 0
OM_EXISTS = 1


class RBD:
    """Image management surface (librbd::RBD)."""

    async def create(self, ioctx: IoCtx, name: str, size: int,
                     order: int = DEFAULT_ORDER,
                     data_pool: Optional[str] = None,
                     exclusive_lock: bool = False,
                     object_map: bool = False,
                     journaling: bool = False) -> str:
        """Create an image; returns its id.  data_pool places the data
        objects on a different (e.g. erasure-coded) pool while
        metadata stays on this replicated pool (--data-pool role)."""
        if not (12 <= order <= 26):
            raise RadosError(-22, f"order {order} out of range")
        # FRESH unique id per create (the reference allocates one from
        # rbd_directory too): remove+recreate must never reuse an id,
        # or leftovers from a partially failed remove would resurface
        # as data inside the new image
        import os as _os

        image_id = f"{ioctx.pool_id:x}{_os.urandom(6).hex()}"
        # header FIRST, name claim SECOND: a crash in between leaves
        # only an invisible orphan header (garbage, reclaimable name) —
        # the reverse order left a claimed name with no header that
        # could never be recreated
        features = ["exclusive-lock"] if exclusive_lock else []
        if object_map:
            if not exclusive_lock:
                # the reference gates object-map on exclusive-lock:
                # an unserialized bitmap would race its own writers
                raise RadosError(-22, "object-map requires"
                                      " exclusive-lock")
            features.append("object-map")
        if journaling:
            if not exclusive_lock:
                # librbd gates journaling on exclusive-lock too: the
                # event stream needs one writer ordering it
                raise RadosError(-22, "journaling requires"
                                      " exclusive-lock")
            features.append("journaling")
        meta = {"name": name, "size": size, "order": order,
                "snaps": {}, "snap_seq": 0, "data_pool": data_pool,
                "features": features}
        await ioctx.omap_set(_header(image_id),
                             {"rbd": json.dumps(meta).encode()})
        try:
            await ioctx.execute(
                RBD_DIRECTORY, "dir", "add",
                json.dumps({"key": f"name_{name}",
                            "value": image_id}).encode())
        except RadosError:
            # name taken — but a previous crash may have left a claim
            # whose header never landed (the old create order): that
            # name is RECLAIMABLE, anything else is a real EEXIST
            directory = await self._dir(ioctx)
            old_id = directory.get(name)
            stale = old_id is not None
            if stale:
                try:
                    await ioctx.omap_get(_header(old_id))
                    stale = False  # live image: real conflict
                except ObjectNotFound:
                    pass
            if not stale:
                await _ignore_enoent(ioctx.remove(_header(image_id)))
                raise RadosError(-17, f"image {name!r} exists")
            try:
                # value-checked removal: only the EXACT stale claim we
                # adjudicated dies — a racing reclaimer who already
                # replaced it must not lose its fresh claim
                await ioctx.execute(
                    RBD_DIRECTORY, "dir", "remove",
                    json.dumps({"key": f"name_{name}",
                                "value": old_id}).encode())
                await ioctx.execute(
                    RBD_DIRECTORY, "dir", "add",
                    json.dumps({"key": f"name_{name}",
                                "value": image_id}).encode())
            except RadosError:
                # lost the reclaim race: clean up our header, surface
                # EEXIST like any other conflict
                await _ignore_enoent(ioctx.remove(_header(image_id)))
                raise RadosError(-17, f"image {name!r} exists")
        return image_id

    async def clone(self, p_ioctx: IoCtx, parent_name: str,
                    snap_name: str, c_ioctx: IoCtx, clone_name: str,
                    data_pool: Optional[str] = None,
                    exclusive_lock: bool = False,
                    object_map: bool = False) -> str:
        """Clone from a PROTECTED parent snapshot (librbd clone v2,
        rbd_op clone).  The child starts with zero data objects;
        every read falls through to the parent at the snap until
        writes copy objects up."""
        parent = await self.open(p_ioctx, parent_name)
        snap = parent.meta["snaps"].get(snap_name)
        if snap is None:
            raise ObjectNotFound(-2, snap_name)
        if not snap.get("protected"):
            raise RadosError(-22, f"snap {snap_name!r} is not"
                                  " protected")
        child_id = await self.create(
            c_ioctx, clone_name, size=snap["size"],
            order=parent.meta["order"], data_pool=data_pool,
            exclusive_lock=exclusive_lock, object_map=object_map)
        child = Image(c_ioctx, clone_name, child_id)
        await child.refresh()
        child.meta["parent"] = {
            "pool_id": p_ioctx.pool_id, "image_id": parent.id,
            "snap_name": snap_name, "snap_id": snap["id"],
            "overlap": snap["size"]}
        child.meta["features"] = sorted(
            set(child.meta["features"]) | {"layering"})
        await child._save()
        # children bookkeeping on the parent (cls_rbd children role),
        # under the parent header lock: concurrent clones or an
        # unprotect racing this registration must serialize, or a
        # child record is lost and unprotect orphans the clone
        cookie = await _header_lock(p_ioctx, parent.id)
        try:
            await parent.refresh()
            snap = parent.meta["snaps"].get(snap_name)
            if snap is None or not snap.get("protected"):
                raise RadosError(
                    -22, f"snap {snap_name!r} lost protection during"
                         " clone")
            kids = parent.meta.setdefault("children", [])
            kids.append({"pool_id": c_ioctx.pool_id,
                         "image_id": child_id,
                         "snap_name": snap_name})
            await parent._save()
        except Exception:
            await _ignore_enoent(self.remove(c_ioctx, clone_name))
            raise
        finally:
            await _header_unlock(p_ioctx, parent.id, cookie)
        return child_id

    async def remove(self, ioctx: IoCtx, name: str) -> None:
        directory = await self._dir(ioctx)
        image_id = directory.get(name)
        if image_id is None:
            raise ObjectNotFound(-2, name)
        img = await self.open(ioctx, name)
        if img.meta["snaps"]:
            raise RadosError(-39, "image has snapshots")  # ENOTEMPTY
        if img.meta.get("children"):
            raise RadosError(-39, "image has dependent clones")
        await self._destroy(ioctx, img)
        try:
            # value-checked: if a concurrent create already reclaimed
            # the name with a fresh id, its claim must survive
            await ioctx.execute(
                RBD_DIRECTORY, "dir", "remove",
                json.dumps({"key": f"name_{name}",
                            "value": image_id}).encode())
        except RadosError:
            pass

    @staticmethod
    async def _destroy(ioctx: IoCtx, img: "Image") -> None:
        """Delete an image's data/map/journal/header (shared by
        remove() and trash_rm(); directory/trash bookkeeping is the
        caller's)."""
        image_id = img.id
        objects = (img.size() + img.object_size - 1) // img.object_size
        todo = range(objects)
        if img._om_enabled():
            # object-map acceleration: delete only objects the map
            # says exist instead of probing every index
            om = await img._om_load()
            todo = [i for i in range(objects)
                    if img._om_get(om, i) == OM_EXISTS]
        await asyncio.gather(*(
            _ignore_enoent(img.data_ioctx.remove(_data(image_id, i)))
            for i in todo))
        await _ignore_enoent(ioctx.remove(_object_map(image_id)))
        if img._journal is not None:
            await img._journal.destroy()
        parent = img.meta.get("parent")
        if parent is not None:
            await img._deregister_child()
        await _ignore_enoent(ioctx.remove(_header(image_id)))

    # -- trash (librbd api/Trash.cc role) ----------------------------------
    #
    # `rbd trash mv` detaches the NAME (the image becomes invisible to
    # open/ls) but keeps every object; restore re-claims a name, rm
    # destroys for real once the deferment window has passed.  The
    # safety property: an accidental delete is reversible until purge.

    async def trash_mv(self, ioctx: IoCtx, name: str,
                       delay: float = 0.0) -> str:
        directory = await self._dir(ioctx)
        image_id = directory.get(name)
        if image_id is None:
            raise ObjectNotFound(-2, name)
        img = await self.open(ioctx, name)
        try:
            if img.meta.get("children"):
                raise RadosError(-39, "image has dependent clones")
            if img.meta.get("migration"):
                raise RadosError(-16, "image is migrating")  # EBUSY
        finally:
            # the open may have acquired the exclusive lock (journal
            # replay); never leak it past the mv
            await img.close()
        now = time.time()
        # trash entry FIRST, then drop the name: a crash in between
        # leaves the image findable in BOTH (restore converges);
        # the reverse order would leave it findable in NEITHER
        await ioctx.omap_set(RBD_TRASH, {image_id: json.dumps({
            "name": name, "moved_at": now,
            "deferment_end": now + max(0.0, delay)}).encode()})
        try:
            await ioctx.execute(
                RBD_DIRECTORY, "dir", "remove",
                json.dumps({"key": f"name_{name}",
                            "value": image_id}).encode())
        except RadosError:
            pass  # name already re-claimed: trash entry still valid
        return image_id

    async def trash_ls(self, ioctx: IoCtx) -> List[Dict[str, Any]]:
        try:
            omap = await ioctx.omap_get(RBD_TRASH)
        except ObjectNotFound:
            return []
        out = []
        for image_id, raw in sorted(omap.items()):
            doc = json.loads(raw.decode())
            out.append(dict(doc, id=image_id))
        return out

    async def _trash_entry(self, ioctx: IoCtx,
                           image_id: str) -> Dict[str, Any]:
        try:
            omap = await ioctx.omap_get(RBD_TRASH)
        except ObjectNotFound:
            omap = {}
        raw = omap.get(image_id)
        if raw is None:
            raise ObjectNotFound(-2, f"no trash entry {image_id}")
        return json.loads(raw.decode())

    async def trash_restore(self, ioctx: IoCtx, image_id: str,
                            new_name: Optional[str] = None) -> str:
        doc = await self._trash_entry(ioctx, image_id)
        name = new_name or doc["name"]
        try:
            await ioctx.execute(
                RBD_DIRECTORY, "dir", "add",
                json.dumps({"key": f"name_{name}",
                            "value": image_id}).encode())
        except RadosError:
            # trash_mv's crash window leaves the image findable in
            # BOTH the directory and the trash; if the existing claim
            # already maps this exact id, restore just converges
            if (await self._dir(ioctx)).get(name) != image_id:
                raise RadosError(-17, f"name {name!r} is taken")
        await ioctx.omap_rm_keys(RBD_TRASH, [image_id])
        return name

    async def trash_rm(self, ioctx: IoCtx, image_id: str,
                       force: bool = False) -> None:
        doc = await self._trash_entry(ioctx, image_id)
        await self._trash_rm_doc(ioctx, image_id, doc, force)

    async def _trash_rm_doc(self, ioctx: IoCtx, image_id: str,
                            doc: Dict[str, Any],
                            force: bool) -> None:
        if not force and time.time() < doc.get("deferment_end", 0):
            raise RadosError(
                -1, "deferment window has not passed"
                    " (use force)")  # EPERM
        img = Image(ioctx, doc["name"], image_id)
        try:
            await img.refresh()
        except ObjectNotFound:
            # a prior trash_rm crashed after destroying the header:
            # the entry is the only leftover — drop it and converge
            await ioctx.omap_rm_keys(RBD_TRASH, [image_id])
            return
        if img.meta.get("children"):
            raise RadosError(-39, "image has dependent clones")
        for snap_name, snap in list(img.meta["snaps"].items()):
            if snap.get("protected"):
                raise RadosError(-16,
                                 f"snap {snap_name!r} is protected")
        for snap_name in list(img.meta["snaps"]):
            await img.snap_remove(snap_name)
        await self._destroy(ioctx, img)
        try:
            # the trash_mv crash window can leave the NAME claimed in
            # the directory too; value-checked removal so a phantom
            # entry never outlives the destroyed image
            await ioctx.execute(
                RBD_DIRECTORY, "dir", "remove",
                json.dumps({"key": f"name_{doc['name']}",
                            "value": image_id}).encode())
        except RadosError:
            pass  # name not claimed (the normal case) or re-claimed
        await ioctx.omap_rm_keys(RBD_TRASH, [image_id])

    async def trash_purge(self, ioctx: IoCtx) -> int:
        """Destroy every entry whose deferment has expired; returns
        how many were reclaimed."""
        n = 0
        for entry in await self.trash_ls(ioctx):
            if time.time() < entry.get("deferment_end", 0):
                continue
            try:
                await self._trash_rm_doc(ioctx, entry["id"], entry,
                                         force=False)
                n += 1
            except RadosError as e:
                if e.rc not in (-16, -39):
                    raise  # real I/O failure — surface it
                continue  # protected snaps / clones: left in trash
        return n

    async def list(self, ioctx: IoCtx) -> List[str]:
        return sorted(await self._dir(ioctx))

    async def open(self, ioctx: IoCtx, name: str) -> "Image":
        directory = await self._dir(ioctx)
        image_id = directory.get(name)
        if image_id is None:
            raise ObjectNotFound(-2, name)
        img = Image(ioctx, name, image_id)
        try:
            await img.refresh()
        except ObjectNotFound:
            # a half-created image (claim without header, pre-crash):
            # clear error instead of a raw header miss; create() can
            # reclaim the name
            raise RadosError(
                -5, f"image {name!r} has no header (interrupted"
                    " create?); re-create to reclaim the name")
        # journaling feature: replay events a crashed writer appended
        # but never applied (librbd::Journal open-time replay)
        await img._journal_replay()
        return img

    async def _dir(self, ioctx: IoCtx) -> Dict[str, str]:
        try:
            omap = await ioctx.omap_get(RBD_DIRECTORY)
        except ObjectNotFound:
            return {}
        return {k[len("name_"):]: v.decode()
                for k, v in omap.items() if k.startswith("name_")}


async def _ignore_enoent(coro) -> None:
    try:
        await coro
    except ObjectNotFound:
        pass


META_LOCK = "rbd_meta_lock"


async def _header_lock(ioctx: IoCtx, image_id: str,
                       timeout: float = 10.0) -> str:
    """Exclusive cls lock serializing header-metadata RMWs that span
    HANDLES (children registration, protection adjudication) — the
    cls_rbd single-writer discipline.  Expires (duration) so a crashed
    holder cannot brick the image."""
    import time as _time
    import uuid as _uuid

    cookie = _uuid.uuid4().hex[:12]
    req = json.dumps({"name": META_LOCK, "type": "exclusive",
                      "cookie": cookie, "duration": 15.0,
                      "owner": f"rbdmeta.{cookie}"}).encode()
    deadline = _time.monotonic() + timeout
    while True:
        try:
            await ioctx.execute(_header(image_id), "lock", "lock", req)
            return cookie
        except RadosError as e:
            if e.rc != -16 or _time.monotonic() > deadline:
                raise
            await asyncio.sleep(0.02)


async def _header_unlock(ioctx: IoCtx, image_id: str,
                         cookie: str) -> None:
    req = json.dumps({"name": META_LOCK, "cookie": cookie,
                      "owner": f"rbdmeta.{cookie}"}).encode()
    try:
        await ioctx.execute(_header(image_id), "lock", "unlock", req)
    except (ObjectNotFound, RadosError):
        pass  # header removed with the image: lock died with it


class Image:
    """An open image (librbd::Image): byte-addressed I/O + snaps."""

    LOCK_NAME = "rbd_lock"
    LOCK_RENEW = 1.0       # holder renewal period (seconds)
    LOCK_STALE = 5         # challenger: renewals missed before break

    def __init__(self, ioctx: IoCtx, name: str, image_id: str):
        # a dedicated ioctx: image snap context must not leak into the
        # caller's other I/O
        self.ioctx = IoCtx(ioctx.client, ioctx.pool_id)
        # data objects may live on a separate (EC) pool; bound in
        # refresh() once the header names it
        self.data_ioctx = self.ioctx
        self.name = name
        self.id = image_id
        self.meta: Dict[str, Any] = {}
        self._read_snap: Optional[str] = None
        # exclusive-lock state (feature-gated); per-HANDLE cookie so
        # two handles of one client contend like strangers (librbd's
        # cookie role) and closing one cannot unlock the other
        import uuid as _uuid

        self._lock_owned = False
        self._lock_cookie = _uuid.uuid4().hex[:12]
        self._lock_task: Optional[asyncio.Task] = None
        self._renew_n = 0
        self._seen_renewal = None  # (raw, my monotonic) for staleness
        # layering: parent reader handle, bound lazily in _parent()
        self._parent_img: Optional["Image"] = None
        # object map: in-memory bitmap cache (authoritative while the
        # exclusive lock is held, the reference's in-memory ObjectMap);
        # _om_lock serializes load+mutate so parallel per-object write
        # tasks can never fork the bitmap and lose marks
        self._om_cache: Optional[bytearray] = None
        self._om_lock = lockdep.Lock("rbd.om")
        # serializes absent-check + copyup: without it two concurrent
        # partial writes to one absent object both copy up and the
        # second copyup erases the first write's chunk (librbd guards
        # this with a server-side object-absent condition)
        self._copyup_lock = lockdep.Lock("rbd.copyup")
        # journaling (feature-gated): write-ahead event log; see
        # ceph_tpu.rbd.journal.  _replaying suppresses re-journaling
        # while replay applies events through the ordinary op methods
        self._journal = None
        self._replaying = False

    # -- metadata ----------------------------------------------------------

    async def refresh(self) -> None:
        omap = await self.ioctx.omap_get(_header(self.id))
        self.meta = json.loads(omap["rbd"].decode())
        # derived caches follow the header: a peer may have changed
        # the map or the parent link since they were filled
        self._om_cache = None
        self._parent_img = None
        data_pool = self.meta.get("data_pool")
        if data_pool and self.data_ioctx is self.ioctx:
            self.data_ioctx = self.ioctx.client.open_ioctx(data_pool)
        self._apply_snapc()
        if "journaling" in self.meta.get("features", []):
            from ceph_tpu.rbd.journal import ImageJournal

            if self._journal is None:
                self._journal = ImageJournal(self.ioctx, self.id)
            await self._journal.open()

    async def _save(self) -> None:
        await self.ioctx.omap_set(
            _header(self.id), {"rbd": json.dumps(self.meta).encode()})

    def _apply_snapc(self) -> None:
        snaps = sorted((s["id"] for s in self.meta["snaps"].values()),
                       reverse=True)
        self.data_ioctx.set_snap_context(self.meta["snap_seq"], snaps)

    # -- journaling (librbd::Journal role) ---------------------------------

    async def _j_append(self, ev) -> Optional[int]:
        """Write-ahead: journal the event before applying it (no-op
        without the feature, and during replay)."""
        if self._journal is None or self._replaying:
            return None
        return await self._journal.append(ev)

    async def _j_commit(self, seq: Optional[int]) -> None:
        if seq is not None and self._journal is not None:
            await self._journal.commit(seq)

    async def _journal_replay(self) -> None:
        """Apply events a crashed writer journaled but never applied
        (seq above the commit position).  Events are idempotent
        full-state mutations, so at-least-once re-application is
        safe; snap ops tolerate already-done errors."""
        if self._journal is None:
            return
        committed = int(self._journal.hdr.get("committed", 0))
        events = await self._journal.events_since(committed)
        if not events:
            return
        self._replaying = True
        try:
            for ev in events:
                try:
                    await self._apply_event(ev)
                except RadosError:
                    pass  # snap already created/removed, etc.
                await self._journal.commit(ev["seq"])
        finally:
            self._replaying = False

    async def _apply_event(self, ev) -> None:
        op = ev["op"]
        if op == "write":
            await self.write(ev["offset"], ev["data"])
        elif op == "discard":
            await self.discard(ev["offset"], ev["length"])
        elif op == "resize":
            await self.resize(ev["size"])
        elif op == "snap_create":
            await self.snap_create(ev["snap_name"])
        elif op == "snap_remove":
            await self.snap_remove(ev["snap_name"])
        elif op == "snap_rollback":
            await self.snap_rollback(ev["snap_name"])

    @property
    def object_size(self) -> int:
        return 1 << self.meta["order"]

    def size(self) -> int:
        if self._read_snap is not None:
            return self.meta["snaps"][self._read_snap]["size"]
        return self.meta["size"]

    async def stat(self) -> Dict[str, Any]:
        return {"size": self.size(), "order": self.meta["order"],
                "obj_size": self.object_size,
                "num_objs": (self.size() + self.object_size - 1)
                // self.object_size}

    # -- extent mapping (Striper::file_to_extents role) --------------------

    def _extents(self, offset: int, length: int):
        """(objectno, in-object offset, length) covering the range."""
        out = []
        end = offset + length
        while offset < end:
            objectno = offset // self.object_size
            in_off = offset % self.object_size
            span = min(self.object_size - in_off, end - offset)
            out.append((objectno, in_off, span))
            offset += span
        return out

    # -- layering (parent I/O, librbd ImageCtx parent role) ---------------

    def _has_parent(self) -> bool:
        return self.meta.get("parent") is not None

    async def _parent(self) -> "Image":
        """The parent image opened read-only AT THE CLONE SNAP."""
        if self._parent_img is None:
            p = self.meta["parent"]
            p_ioctx = IoCtx(self.ioctx.client, p["pool_id"])
            # open by id: the parent may have been renamed since
            img = Image(p_ioctx, "", p["image_id"])
            await img.refresh()
            img.snap_set(p["snap_name"])
            self._parent_img = img
        return self._parent_img

    def _effective_overlap(self) -> int:
        if self._read_snap is not None:
            snap = self.meta["snaps"][self._read_snap]
            return snap.get("parent_overlap",
                            self.meta["parent"]["overlap"])
        return self.meta["parent"]["overlap"]

    async def _parent_read(self, objectno: int, in_off: int,
                           span: int) -> bytes:
        """Read the byte range from the parent at the snap, clamped to
        the overlap (the READ snap's recorded overlap when reading at
        a snapshot); beyond-overlap bytes are zeros."""
        start = objectno * self.object_size + in_off
        end = min(start + span, self._effective_overlap())
        if end <= start:
            return bytes(span)
        parent = await self._parent()
        buf = await parent.read(start, end - start)
        return buf + bytes(span - len(buf))

    async def _copyup(self, objectno: int) -> None:
        """First partial write to an absent child object: copy the
        parent's content for that object down (librbd CopyupRequest).
        Idempotent — re-running after a crash converges."""
        content = await self._parent_read(objectno, 0,
                                          self.object_size)
        content = content.rstrip(b"\x00")
        await self.data_ioctx.write_full(_data(self.id, objectno),
                                         content)
        await self._om_mark(objectno, OM_EXISTS)

    async def _child_object_absent(self, objectno: int) -> bool:
        if self._om_enabled():
            om = await self._om_load()
            return self._om_get(om, objectno) == OM_NONEXISTENT
        try:
            await self.data_ioctx.stat(_data(self.id, objectno))
            return False
        except ObjectNotFound:
            return True

    async def _deregister_child(self) -> None:
        p = self.meta.get("parent")
        if p is None:
            return
        p_ioctx = IoCtx(self.ioctx.client, p["pool_id"])
        parent = Image(p_ioctx, "", p["image_id"])
        try:
            cookie = await _header_lock(p_ioctx, p["image_id"])
        except ObjectNotFound:
            return
        try:
            await parent.refresh()
            kids = [c for c in parent.meta.get("children", [])
                    if c["image_id"] != self.id]
            parent.meta["children"] = kids
            await parent._save()
        except ObjectNotFound:
            pass
        finally:
            await _header_unlock(p_ioctx, p["image_id"], cookie)

    async def flatten(self) -> None:
        """Copy every still-parent-backed object down, then sever the
        parent link (librbd flatten)."""
        if not self._has_parent():
            return
        await self._ensure_lock()
        overlap = self.meta["parent"]["overlap"]
        objects = -(-overlap // self.object_size)
        sem = asyncio.Semaphore(8)

        async def one(objectno: int) -> None:
            async with sem:
                if await self._child_object_absent(objectno):
                    await self._copyup(objectno)

        await asyncio.gather(*(one(i) for i in range(objects)))
        await self._deregister_child()
        self.meta["parent"] = None
        self.meta["features"] = [f for f in self.meta["features"]
                                 if f != "layering"]
        await self._save()
        self._parent_img = None

    # -- object map (librbd::ObjectMap role) ------------------------------

    def _om_enabled(self) -> bool:
        return "object-map" in self.meta.get("features", [])

    async def _om_load(self) -> bytearray:
        if self._om_cache is not None:
            return self._om_cache
        objects = -(-self.meta["size"] // self.object_size)
        nbytes = -(-objects // 4)  # 2 bits per object
        try:
            raw = bytearray(await self.ioctx.read(
                _object_map(self.id)))
        except ObjectNotFound:
            raw = bytearray()
        if len(raw) < nbytes:
            raw.extend(bytes(nbytes - len(raw)))
        self._om_cache = raw
        return raw

    @staticmethod
    def _om_get(om: bytearray, objectno: int) -> int:
        return (om[objectno // 4] >> ((objectno % 4) * 2)) & 3

    async def _om_mark(self, objectno: int, state: int) -> None:
        if not self._om_enabled():
            return
        async with self._om_lock:
            om = await self._om_load()
            if objectno // 4 >= len(om):
                om.extend(bytes(objectno // 4 + 1 - len(om)))
            shift = (objectno % 4) * 2
            om[objectno // 4] = (om[objectno // 4] & ~(3 << shift)) \
                | (state << shift)
            await self.ioctx.write_full(_object_map(self.id),
                                        bytes(om))

    async def rebuild_object_map(self) -> None:
        """Scan actual data objects and rewrite the map (rbd
        object-map rebuild)."""
        await self._ensure_lock()
        objects = -(-self.meta["size"] // self.object_size)
        om = bytearray(-(-objects // 4))
        sem = asyncio.Semaphore(8)

        async def probe(i: int) -> None:
            async with sem:
                try:
                    await self.data_ioctx.stat(_data(self.id, i))
                    om[i // 4] |= OM_EXISTS << ((i % 4) * 2)
                except ObjectNotFound:
                    pass

        await asyncio.gather(*(probe(i) for i in range(objects)))
        self._om_cache = om
        await self.ioctx.write_full(_object_map(self.id), bytes(om))

    async def diff_objects(self) -> List[int]:
        """Object indexes with data (fast-diff lite): straight from
        the map when enabled, probe otherwise."""
        objects = -(-self.meta["size"] // self.object_size)
        if self._om_enabled():
            om = await self._om_load()
            return [i for i in range(objects)
                    if self._om_get(om, i) == OM_EXISTS]
        sem = asyncio.Semaphore(8)

        async def probe(i: int) -> bool:
            async with sem:
                try:
                    await self.data_ioctx.stat(_data(self.id, i))
                    return True
                except ObjectNotFound:
                    return False

        hits = await asyncio.gather(*(probe(i)
                                      for i in range(objects)))
        return [i for i, hit in enumerate(hits) if hit]

    # -- I/O ---------------------------------------------------------------

    async def read(self, offset: int, length: int) -> bytes:
        size = self.size()
        if offset >= size:
            return b""
        length = min(length, size - offset)
        om = await self._om_load() if self._om_enabled() and \
            self._read_snap is None else None

        async def one(objectno: int, in_off: int, span: int) -> bytes:
            if om is not None and \
                    self._om_get(om, objectno) == OM_NONEXISTENT:
                # map says absent: skip the data round-trip entirely
                if self._has_parent():
                    return await self._parent_read(objectno, in_off,
                                                   span)
                return bytes(span)
            try:
                buf = await self.data_ioctx.read(
                    _data(self.id, objectno), in_off, span)
            except ObjectNotFound:
                if self._has_parent():
                    # clone fallthrough: the parent provides content
                    # until a write copies the object up (also for
                    # reads at a CHILD snap — the parent is frozen at
                    # its own snap, so its content is time-invariant)
                    return await self._parent_read(objectno, in_off,
                                                   span)
                return bytes(span)  # sparse: absent object reads zeros
            if len(buf) < span:  # short object tail is sparse too
                buf += bytes(span - len(buf))
            return buf

        parts = await asyncio.gather(
            *(one(*ext) for ext in self._extents(offset, length)))
        return b"".join(parts)

    # -- exclusive lock (librbd::ExclusiveLock role) -----------------------

    def _exclusive_enabled(self) -> bool:
        return "exclusive-lock" in self.meta.get("features", [])

    async def _ensure_lock(self) -> None:
        """Lock-on-write policy: the first mutation acquires; a live
        peer holder means EBUSY; a stale holder (renewal counter
        unchanged for LOCK_STALE periods of OUR clock) is broken."""
        if not self._exclusive_enabled() or self._lock_owned:
            return
        import time

        req = json.dumps({"name": self.LOCK_NAME, "type": "exclusive",
                          "owner": self.ioctx.client.msgr.entity_name,
                          "cookie": self._lock_cookie,
                          "tag": "rbd"}).encode()
        deadline = time.monotonic() + \
            self.LOCK_RENEW * (self.LOCK_STALE + 2)
        while True:
            try:
                await self.ioctx.execute(_header(self.id), "lock",
                                         "lock", req)
                break
            except RadosError:
                pass
            if time.monotonic() > deadline:
                raise RadosError(
                    -16, f"image {self.name!r} is exclusively"
                         " locked by a live client")  # EBUSY
            try:
                raw = await self.ioctx.getxattr(
                    _header(self.id), "rbd.lock.renewal")
            except Exception:
                raw = b""
            now = time.monotonic()
            if self._seen_renewal is None or \
                    self._seen_renewal[0] != raw:
                self._seen_renewal = (raw, now)
            elif now - self._seen_renewal[1] > \
                    self.LOCK_RENEW * self.LOCK_STALE:
                # holder dead: break (by its full locker identity from
                # the cls lock state, not just the stamp) and retry
                try:
                    info = json.loads((await self.ioctx.execute(
                        _header(self.id), "lock", "get_info",
                        json.dumps({"name": self.LOCK_NAME})
                        .encode())).decode())
                    for locker in info.get("lockers", {}).values():
                        await self.ioctx.execute(
                            _header(self.id), "lock", "break_lock",
                            json.dumps({
                                "name": self.LOCK_NAME,
                                "locker": locker["owner"],
                                "cookie": locker.get("cookie", ""),
                            }).encode())
                except (RadosError, ValueError, KeyError):
                    pass
                self._seen_renewal = None
            await asyncio.sleep(self.LOCK_RENEW / 2)
        self._lock_owned = True
        # the header may have moved while someone else held the lock:
        # re-read it UNDER the lock so our read-modify-writes (snapc,
        # size, snaps) start from the current state
        await self.refresh()
        # the refresh may have just revealed a migration fence set
        # since we opened — fail the acquiring mutation, not the ones
        # after it
        try:
            self._fence_migration_source()
        except RadosError:
            await self.release_exclusive_lock()
            raise
        await self._renew_lock_stamp()
        self._lock_task = asyncio.get_running_loop().create_task(
            self._lock_renew_loop())

    async def _renew_lock_stamp(self) -> None:
        self._renew_n += 1
        await self.ioctx.setxattr(
            _header(self.id), "rbd.lock.renewal",
            json.dumps([self.ioctx.client.msgr.entity_name,
                        self._lock_cookie, self._renew_n]).encode())

    async def _lock_renew_loop(self) -> None:
        misses = 0
        try:
            while self._lock_owned:
                await asyncio.sleep(self.LOCK_RENEW)
                try:
                    await self._renew_lock_stamp()
                    misses = 0
                except Exception:
                    misses += 1
                    if misses * 2 >= self.LOCK_STALE:
                        # cannot prove liveness anymore: DEMOTE before
                        # a challenger breaks the lock, or two writers
                        # would interleave the header RMW — the next
                        # mutation re-acquires cleanly
                        self._lock_owned = False
                        return
        except asyncio.CancelledError:
            pass

    async def release_exclusive_lock(self) -> None:
        if not self._lock_owned:
            return
        self._lock_owned = False
        if self._lock_task is not None:
            self._lock_task.cancel()
            self._lock_task = None
        try:
            await self.ioctx.execute(
                _header(self.id), "lock", "unlock",
                json.dumps({
                    "name": self.LOCK_NAME,
                    "owner": self.ioctx.client.msgr.entity_name,
                    "cookie": self._lock_cookie,
                }).encode())
        except RadosError:
            pass

    async def close(self) -> None:
        """Release the exclusive lock (librbd close)."""
        await self.release_exclusive_lock()

    # -- I/O (mutators) ----------------------------------------------------

    def _fence_migration_source(self) -> None:
        """A migration source is write-fenced (Migration.cc prepare
        semantics re-designed as a header flag): clients must switch
        to the destination image, whose writes are the live ones."""
        if self.meta.get("migration"):
            raise RadosError(-30, "image is a migration source"
                                  " (write-fenced)")  # EROFS

    async def write(self, offset: int, data: bytes) -> int:
        if self._read_snap is not None:
            raise RadosError(-30, "image is open at a snapshot")  # EROFS
        if offset + len(data) > self.meta["size"]:
            raise RadosError(-27, "write past image size")  # EFBIG
        self._fence_migration_source()
        await self._ensure_lock()
        seq = await self._j_append({"op": "write", "offset": offset,
                                    "data": data})
        pos = 0
        jobs = []
        for objectno, in_off, span in self._extents(offset, len(data)):
            chunk = data[pos:pos + span]
            pos += span
            jobs.append(self._write_object(objectno, in_off, span,
                                           chunk))
        await asyncio.gather(*jobs)
        await self._j_commit(seq)
        return len(data)

    async def _write_object(self, objectno: int, in_off: int,
                            span: int, chunk: bytes) -> None:
        """One object's slice of a write: copyup-then-write for
        partial writes into a parent-backed absent object (librbd
        AbstractObjectWriteRequest copyup path), object-map EXISTS
        before data lands."""
        full = in_off == 0 and span == self.object_size
        if self._has_parent() and not full and \
                objectno * self.object_size \
                < self.meta["parent"]["overlap"]:
            async with self._copyup_lock:
                if await self._child_object_absent(objectno):
                    await self._copyup(objectno)
        await self._om_mark(objectno, OM_EXISTS)
        await self.data_ioctx.write(_data(self.id, objectno), chunk,
                                    in_off)

    async def discard(self, offset: int, length: int) -> None:
        """Deallocate a range: whole objects are removed (returning
        them to sparse), partial spans are zeroed."""
        if self._read_snap is not None:
            raise RadosError(-30, "image is open at a snapshot")
        self._fence_migration_source()
        await self._ensure_lock()
        seq = await self._j_append({"op": "discard", "offset": offset,
                                    "length": length})
        overlap = self.meta["parent"]["overlap"] \
            if self._has_parent() else 0
        jobs = []
        for objectno, in_off, span in self._extents(offset, length):
            name = _data(self.id, objectno)
            full = in_off == 0 and span == self.object_size
            if full and objectno * self.object_size >= overlap:
                jobs.append(self._discard_object(objectno, name))
            else:
                # parent-backed range (or partial span): removal would
                # EXPOSE the parent's bytes again — zero instead
                jobs.append(self._write_object(objectno, in_off, span,
                                               bytes(span)))
        await asyncio.gather(*jobs)
        await self._j_commit(seq)

    async def _discard_object(self, objectno: int, name: str) -> None:
        await _ignore_enoent(self.data_ioctx.remove(name))
        await self._om_mark(objectno, OM_NONEXISTENT)

    async def resize(self, new_size: int) -> None:
        if self._read_snap is not None:
            raise RadosError(-30, "image is open at a snapshot")
        self._fence_migration_source()
        await self._ensure_lock()
        seq = await self._j_append({"op": "resize", "size": new_size})
        old = self.meta["size"]
        if new_size < old:
            # drop whole objects past the end; zero the partial tail
            first_dead = (new_size + self.object_size - 1) \
                // self.object_size
            last = (old + self.object_size - 1) // self.object_size
            dead = range(first_dead, last)
            if self._om_enabled():
                om = await self._om_load()
                dead = [i for i in dead
                        if self._om_get(om, i) == OM_EXISTS]
            await asyncio.gather(*(
                self._discard_object(i, _data(self.id, i))
                for i in dead))
            if new_size % self.object_size:
                # through the copyup-aware path: a raw zero-write
                # would CREATE the tail object and cut off parent
                # fallthrough for its still-live head bytes
                tail = new_size % self.object_size
                await self._write_object(
                    new_size // self.object_size, tail,
                    self.object_size - tail,
                    bytes(self.object_size - tail))
            if self._has_parent():
                # shrink shrinks the parent overlap permanently
                # (librbd: overlap = min(overlap, size))
                self.meta["parent"]["overlap"] = min(
                    self.meta["parent"]["overlap"], new_size)
        self.meta["size"] = new_size
        await self._save()
        await self._j_commit(seq)

    # -- snapshots (librbd snap_create/list/remove/set) --------------------

    async def snap_create(self, snap_name: str) -> int:
        if snap_name in self.meta["snaps"]:
            raise RadosError(-17, f"snap {snap_name!r} exists")
        self._fence_migration_source()
        await self._ensure_lock()
        jseq = await self._j_append({"op": "snap_create",
                                     "snap_name": snap_name})
        snap_id = await self.data_ioctx.create_selfmanaged_snap()
        entry = {"id": snap_id, "size": self.meta["size"]}
        if self._has_parent():
            # snapshot-time parent overlap: a later shrink clamps the
            # HEAD overlap, but reads at this snap must keep seeing
            # what was parent-visible when it was taken (librbd
            # parent_overlap per snap)
            entry["parent_overlap"] = self.meta["parent"]["overlap"]
        self.meta["snaps"][snap_name] = entry
        self.meta["snap_seq"] = max(self.meta["snap_seq"], snap_id)
        self._apply_snapc()
        await self._save()
        await self._j_commit(jseq)
        return snap_id

    async def snap_list(self) -> List[Dict[str, Any]]:
        return [{"name": n, **s}
                for n, s in sorted(self.meta["snaps"].items(),
                                   key=lambda kv: kv[1]["id"])]

    async def snap_protect(self, snap_name: str) -> None:
        """Protect a snap so clones may reference it (librbd
        snap_protect; unprotect refuses while children exist)."""
        snap = self.meta["snaps"].get(snap_name)
        if snap is None:
            raise ObjectNotFound(-2, snap_name)
        snap["protected"] = True
        await self._save()

    async def snap_unprotect(self, snap_name: str) -> None:
        # children check + protection clear under the header lock:
        # a clone() registering concurrently must either land before
        # (we refuse) or after (it sees protection gone and aborts)
        cookie = await _header_lock(self.ioctx, self.id)
        try:
            await self.refresh()
            snap = self.meta["snaps"].get(snap_name)
            if snap is None:
                raise ObjectNotFound(-2, snap_name)
            kids = [c for c in self.meta.get("children", [])
                    if c.get("snap_name") == snap_name]
            if kids:
                raise RadosError(
                    -16, f"snap {snap_name!r} has"
                         f" {len(kids)} clone(s)")  # EBUSY
            snap["protected"] = False
            await self._save()
        finally:
            await _header_unlock(self.ioctx, self.id, cookie)

    async def snap_is_protected(self, snap_name: str) -> bool:
        snap = self.meta["snaps"].get(snap_name)
        if snap is None:
            raise ObjectNotFound(-2, snap_name)
        return bool(snap.get("protected"))

    async def snap_remove(self, snap_name: str) -> None:
        snap = self.meta["snaps"].get(snap_name)
        if snap is None:
            raise ObjectNotFound(-2, snap_name)
        if snap.get("protected"):
            raise RadosError(-16, f"snap {snap_name!r} is protected")
        jseq = await self._j_append({"op": "snap_remove",
                                     "snap_name": snap_name})
        self.meta["snaps"].pop(snap_name)
        self._apply_snapc()
        await self._save()
        await self.data_ioctx.remove_selfmanaged_snap(snap["id"])
        await self._j_commit(jseq)

    def snap_set(self, snap_name: Optional[str]) -> None:
        """Open the image read-only at a snapshot (None = head)."""
        if snap_name is None:
            self._read_snap = None
            self.data_ioctx.snap_set_read(0)
            return
        snap = self.meta["snaps"].get(snap_name)
        if snap is None:
            raise ObjectNotFound(-2, snap_name)
        self._read_snap = snap_name
        self.data_ioctx.snap_set_read(snap["id"])

    async def snap_rollback(self, snap_name: str) -> None:
        """Copy the snap's content back over the head (librbd
        snap_rollback: reads at the snap, writes to the head)."""
        snap = self.meta["snaps"].get(snap_name)
        if snap is None:
            raise ObjectNotFound(-2, snap_name)
        jseq = await self._j_append({"op": "snap_rollback",
                                     "snap_name": snap_name})
        # the rollback's internal resize/writes re-journal unless
        # suppressed: ONE rollback event stands for the whole copy
        was_replaying, self._replaying = self._replaying, True
        try:
            await self._snap_rollback_inner(snap_name, snap)
        finally:
            self._replaying = was_replaying
        await self._j_commit(jseq)

    async def _snap_rollback_inner(self, snap_name: str,
                                   snap) -> None:
        reader = Image(self.ioctx, self.name, self.id)
        await reader.refresh()  # binds data_ioctx (data_pool images)
        reader.snap_set(snap_name)
        if self.meta["size"] != snap["size"]:
            await self.resize(snap["size"])
        step = self.object_size
        for off in range(0, snap["size"], step):
            span = min(step, snap["size"] - off)
            buf = await reader.read(off, span)
            await self.write(off, buf)

"""rbd-mirror role: journal-based one-way image replication.

Reference parity: /root/reference/src/tools/rbd_mirror/ — the mirror
daemon registers as a client of the primary image's journal, bootstraps
a secondary image (full sync), then tails the journal and replays each
event onto the secondary (ImageReplayer), persisting its position so
replication resumes where it left off and the primary's journal is
only trimmed past every peer's position.

Re-design notes: the reference mirrors across CLUSTERS over its own
RPC; here source and destination are (pool) ioctxs — a second cluster
is just a second RadosClient's ioctx, same code path.  Replay applies
events through the ordinary Image ops (write/discard/resize/snap_*),
so the secondary stays a plain image readable at any moment.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ceph_tpu.common.periodic import PeriodicDaemon
from ceph_tpu.rados.client import IoCtx, RadosError
from ceph_tpu.rbd import RBD, Image
from ceph_tpu.rbd.journal import ImageJournal

log = logging.getLogger("rbd.mirror")


class MirrorReplayer(PeriodicDaemon):
    """Replicates ONE image src -> dst (ImageReplayer role)."""

    def __init__(self, src_ioctx: IoCtx, dst_ioctx: IoCtx,
                 image_name: str, peer_name: str = "mirror"):
        self.src_ioctx = src_ioctx
        self.dst_ioctx = dst_ioctx
        self.image_name = image_name
        self.peer_name = peer_name
        self._rbd = RBD()
        self._tick_what = f"rbd-mirror {image_name}"

    async def _tick(self) -> None:
        await self.replay_once()

    async def bootstrap(self) -> None:
        """Full sync: create the secondary image and copy current
        content, having FIRST registered our journal position — events
        that land during the copy replay afterwards (idempotent), so
        nothing between position-grab and copy-end is lost."""
        src = await self._rbd.open(self.src_ioctx, self.image_name)
        if src._journal is None:
            raise RadosError(-22, f"{self.image_name}: journaling"
                                  " feature required for mirroring")
        # position BEFORE the copy (at-least-once handoff)
        await src._journal.peer_set(self.peer_name,
                                    src._journal.hdr.get("committed",
                                                         0))
        try:
            await self._rbd.open(self.dst_ioctx, self.image_name)
            exists = True
        except Exception:
            exists = False
        if not exists:
            await self._rbd.create(
                self.dst_ioctx, self.image_name, src.size(),
                order=src.meta["order"])
        dst = await self._rbd.open(self.dst_ioctx, self.image_name)
        if dst.size() != src.size():
            await dst.resize(src.size())
        # sparse-aware copy: only objects that exist on the primary
        step = src.object_size
        for objectno in await src.diff_objects():
            off = objectno * step
            span = min(step, src.size() - off)
            if span <= 0:
                continue
            data = await src.read(off, span)
            await dst.write(off, data)
        await src.close()
        await dst.close()

    async def replay_once(self) -> int:
        """One tail-and-apply pass; returns events applied."""
        journal = ImageJournal(self.src_ioctx, await self._image_id())
        pos = await journal.peer_get(self.peer_name)
        events = await journal.events_since(pos)
        if not events:
            return 0
        dst = await self._rbd.open(self.dst_ioctx, self.image_name)
        applied = 0
        try:
            for ev in events:
                await self._apply(dst, ev)
                pos = ev["seq"]
                applied += 1
        finally:
            await dst.close()
            await journal.peer_set(self.peer_name, pos)
        return applied

    async def _image_id(self) -> str:
        directory = await self._rbd._dir(self.src_ioctx)
        image_id = directory.get(self.image_name)
        if image_id is None:
            raise RadosError(-2, self.image_name)
        return image_id

    async def _apply(self, dst: Image, ev) -> None:
        op = ev["op"]
        try:
            if op == "write":
                if ev["offset"] + len(ev["data"]) > dst.size():
                    # a replayed prefix can momentarily lag a resize
                    await dst.resize(ev["offset"] + len(ev["data"]))
                await dst.write(ev["offset"], ev["data"])
            elif op == "discard":
                await dst.discard(ev["offset"], ev["length"])
            elif op == "resize":
                await dst.resize(ev["size"])
            elif op == "snap_create":
                await dst.snap_create(ev["snap_name"])
            elif op == "snap_remove":
                await dst.snap_remove(ev["snap_name"])
            elif op == "snap_rollback":
                await dst.snap_rollback(ev["snap_name"])
        except RadosError as e:
            # at-least-once replay: snap already there / already gone
            # after a crash between apply and position save
            if op.startswith("snap"):
                log.debug("mirror %s: replay %s tolerated: %s",
                          self.image_name, op, e)
            else:
                raise

    # -- continuous mode (the rbd-mirror daemon loop) ----------------------

    # continuous mode: start(interval)/stop() from PeriodicDaemon

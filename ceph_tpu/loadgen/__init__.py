"""Open-loop multi-tenant load harness (the million-client front
door's measuring instrument).

- workload.py: tenant specs, deterministic Poisson/deterministic
  arrival schedules, zipf object popularity, op blends.
- stats.py: bounded-memory streaming latency histograms + goodput.
- targets.py: embedded-rados / networked-rados / S3 op drivers.
- runner.py: the open-loop engine (arrival-rate-driven, latency
  measured from scheduled arrival so queueing delay is counted).

CLI front door: `python -m ceph_tpu.tools.rados ... bench <secs> seq
--tenants N --arrival-rate R --blend read=0.7,write=0.3`.
"""

from ceph_tpu.loadgen.runner import run_embedded, run_open_loop  # noqa: F401
from ceph_tpu.loadgen.stats import (                             # noqa: F401
    GoodputMeter, LatencyHistogram,
)
from ceph_tpu.loadgen.targets import (                           # noqa: F401
    EmbeddedTarget, RadosTarget, S3Target, SheddedOp, Target,
)
from ceph_tpu.loadgen.workload import (                          # noqa: F401
    DEFAULT_BLEND, OP_KINDS, OpEvent, TenantSpec, make_tenants,
    merged_schedule, parse_blend, schedule_fingerprint,
    tenant_events,
)

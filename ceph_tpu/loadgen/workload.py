"""Tenant specs + deterministic open-loop arrival schedules.

Each simulated tenant is an independent client: its own arrival rate
(Poisson or deterministic), its own op blend (read/write/stat/ranged
GET), and its own zipf object-popularity stream (the deterministic
`zipf_indices` sampler from ceph_tpu/tools/rados.py, so bench legs
and regression tests replay bit-identical schedules).  Schedules are
generated lazily per tenant and merged time-ordered, so a
10,000-tenant sweep holds one event per tenant in memory, not the
whole cross product.
"""

from __future__ import annotations

import heapq
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List

import numpy as np

from ceph_tpu.tools.rados import zipf_indices

OP_KINDS = ("read", "write", "stat", "ranged", "infer")

#: default blend: read-mostly with a write/stat/ranged tail — the
#: object-store shape the north star describes
DEFAULT_BLEND: Dict[str, float] = {
    "read": 0.70, "write": 0.15, "stat": 0.10, "ranged": 0.05}


def parse_blend(spec: str) -> Dict[str, float]:
    """'read=0.7,write=0.2,stat=0.1' -> normalized weight dict.
    Unknown kinds raise; missing kinds weigh 0."""
    if not spec:
        return dict(DEFAULT_BLEND)
    out: Dict[str, float] = {}
    for part in spec.split(","):
        name, _, w = part.strip().partition("=")
        if name not in OP_KINDS:
            raise ValueError(
                f"unknown op kind {name!r} (want {OP_KINDS})")
        out[name] = float(w) if w else 1.0
    total = sum(out.values())
    if total <= 0:
        raise ValueError(f"blend {spec!r} sums to zero")
    return {k: v / total for k, v in out.items()}


@dataclass(frozen=True)
class TenantSpec:
    """One simulated tenant's workload shape."""

    name: str
    arrival_rate: float                 # ops/sec offered (open loop)
    blend: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_BLEND))
    zipf_theta: float = 1.0             # object popularity skew
    objects: int = 64                   # shared hot-set size addressed
    object_size: int = 4096             # write payload / read size
    poisson: bool = True                # False: deterministic spacing
    infer_batch: int = 8                # queries per `infer` op

    def seed_for(self, base_seed: int) -> int:
        """Stable per-tenant seed: crc32 of the name folded with the
        run seed (hash() is salted per process — useless here)."""
        return (zlib.crc32(self.name.encode()) ^ (base_seed * 0x9E3779B1)) \
            & 0x7FFFFFFF


@dataclass(frozen=True)
class OpEvent:
    """One scheduled operation: fire at t (seconds from run start)
    regardless of completions — that is what makes the loop open."""

    t: float
    tenant: str
    kind: str
    obj: int
    size: int


def make_tenants(n: int, rate: float = 2.0,
                 blend: Dict[str, float] = None,
                 zipf_theta: float = 1.0, objects: int = 64,
                 object_size: int = 4096,
                 name_prefix: str = "t") -> List[TenantSpec]:
    blend = dict(blend or DEFAULT_BLEND)
    return [TenantSpec(name=f"{name_prefix}{i}", arrival_rate=rate,
                       blend=blend, zipf_theta=zipf_theta,
                       objects=objects, object_size=object_size)
            for i in range(n)]


def tenant_events(spec: TenantSpec, duration: float,
                  seed: int = 0) -> Iterator[OpEvent]:
    """Lazy, deterministic event stream for one tenant over
    [0, duration).  Poisson mode draws exponential inter-arrivals;
    deterministic mode spaces ops evenly with a seeded phase (so
    thousands of same-rate tenants don't fire in lockstep)."""
    rate = float(spec.arrival_rate)
    if rate <= 0 or duration <= 0:
        return
    rng = np.random.default_rng(spec.seed_for(seed))
    # expected count with headroom; Poisson tails are cut at duration
    est = max(4, int(rate * duration * 2) + 8)
    if spec.poisson:
        gaps = rng.exponential(1.0 / rate, size=est)
        times = np.cumsum(gaps)
    else:
        phase = rng.random() / rate
        times = phase + np.arange(est) / rate
    times = times[times < duration]
    count = len(times)
    if count == 0:
        return
    kinds = list(spec.blend.keys())
    weights = np.array([spec.blend[k] for k in kinds], dtype=np.float64)
    kind_idx = rng.choice(len(kinds), size=count, p=weights)
    objs = zipf_indices(spec.zipf_theta, spec.objects, count,
                        seed=spec.seed_for(seed) ^ 0x5F5E5F)
    for i in range(count):
        kind = kinds[int(kind_idx[i])]
        # infer ops size in QUERIES (the per-tenant batch knob), not
        # payload bytes — goodput credits scored queries for them
        yield OpEvent(t=float(times[i]), tenant=spec.name, kind=kind,
                      obj=int(objs[i]),
                      size=spec.infer_batch if kind == "infer"
                      else spec.object_size)


def merged_schedule(tenants: Iterable[TenantSpec], duration: float,
                    seed: int = 0) -> Iterator[OpEvent]:
    """All tenants' event streams merged time-ordered, lazily: the
    heap holds ONE pending event per tenant.  Ties break on tenant
    name so the merge itself is deterministic."""
    streams = [tenant_events(t, duration, seed) for t in tenants]
    keyed = (((ev.t, ev.tenant, ev) for ev in s) for s in streams)
    for _t, _name, ev in heapq.merge(*keyed):
        yield ev


def schedule_fingerprint(tenants: Iterable[TenantSpec],
                         duration: float, seed: int = 0) -> int:
    """crc32 over the full merged schedule — the cheap determinism
    proof (same seed -> same fingerprint, across processes)."""
    crc = 0
    for ev in merged_schedule(tenants, duration, seed):
        crc = zlib.crc32(
            f"{ev.t:.9f}|{ev.tenant}|{ev.kind}|{ev.obj}".encode(), crc)
    return crc

"""Op targets the open-loop runner can drive.

One async interface, three substrates:

- EmbeddedTarget: the in-process `rados/embedded.py` LocalCluster —
  the whole storage slice with no wire, the shape the smoke tier and
  the bench knee-sweep use.
- RadosTarget: the networked `rados/client.py` IoCtx — ops carry the
  tenant identity in MOSDOp v4, so the OSD-side mClock tenant classes
  and the admission gate see exactly who is asking.
- S3Target: raw HTTP/1.1 + sigv4 against `rgw/s3_frontend.py` (the
  stock-client shape; the gateway maps the authenticated access key
  to the rados tenant).

`op()` returns payload bytes moved; a QoS shed (EBUSY from the
admission gate / a full scheduler queue, or S3 503) raises SheddedOp
so the runner accounts it as shed, not error.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

EBUSY = -16


class SheddedOp(Exception):
    """The service refused the op under QoS pressure (not a failure:
    the admission gate doing its job)."""


class Target:
    async def setup(self, objects: int, object_size: int) -> None:
        raise NotImplementedError

    async def op(self, tenant: str, kind: str, obj: int,
                 size: int) -> int:
        raise NotImplementedError

    async def close(self) -> None:
        pass


def _payload(size: int, seed: int = 0) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


@functools.lru_cache(maxsize=64)
def _write_payload(size: int, slot: int) -> bytes:
    """Write payloads memoized by (size, slot): writers address only
    `obj & 7` slots, and regenerating an rng + size bytes per op
    would bill generator overhead as service latency (open-loop
    latency is measured from scheduled arrival)."""
    return _payload(size, seed=slot + 2)


# -- coded inference serving (the `infer` blend op) -----------------------

#: the one shared model every infer op scores against (stored lazily
#: on the first infer op, so blends without `infer` pay nothing)
INFER_MODEL = "lg-model"
INFER_DIM = 32
INFER_OUT = 48


@functools.lru_cache(maxsize=256)
def _infer_queries(nq: int, slot: int) -> np.ndarray:
    """Deterministic query batches memoized by (batch, object slot) —
    same rationale as _write_payload: the generator must not bill
    query synthesis as service latency."""
    return np.random.default_rng(0xC0DE ^ slot).standard_normal(
        (nq, INFER_DIM)).astype(np.float32)


def _infer_blobs():
    """(spec, blobs) for the shared loadgen model with a fixed
    host-side layout — the read-then-infer substrate for targets
    whose pool has no coded serving layout (replicated pools, the
    embedded slice)."""
    from ceph_tpu.inference import registry

    return registry.build(
        INFER_MODEL, "linear",
        registry.make_model("linear", INFER_DIM, INFER_OUT, seed=7),
        k=2, m=1, chunk=1024)


class EmbeddedTarget(Target):
    """Drives an embedded LocalCluster IoCtx (synchronous calls; the
    embedded slice has no event loop of its own to starve)."""

    def __init__(self, io) -> None:
        self.io = io
        self._objects = 0
        self._infer_spec = None

    async def setup(self, objects: int, object_size: int) -> None:
        data = _payload(object_size, seed=1)
        for i in range(objects):
            self.io.write_full(f"lg-{i}", data)
        self._objects = objects

    def _infer(self, obj: int, nq: int) -> int:
        """The embedded slice has no compute wire, so infer ops take
        the read-then-infer shape (the CEPH_TPU_INFERENCE=0 path):
        read the params object, host exact forward, credit the score
        bytes.  The model is stored lazily on the first infer op."""
        from ceph_tpu.inference import model as inf_model
        from ceph_tpu.inference import registry as inf_registry

        if self._infer_spec is None:
            spec, blobs = _infer_blobs()
            for oid, blob in blobs.items():
                self.io.write_full(oid, blob)
            self._infer_spec = spec
        spec = self._infer_spec
        data = self.io.read(inf_registry.params_oid(INFER_MODEL))
        scores = inf_model.exact_forward(
            spec, data, _infer_queries(max(nq, 1), obj & 7))
        return scores.nbytes

    async def op(self, tenant: str, kind: str, obj: int,
                 size: int) -> int:
        io = self.io
        name = f"lg-{obj % max(self._objects, 1)}"
        if kind == "read":
            return len(io.read(name))
        if kind == "ranged":
            return len(io.read(name, offset=size // 4,
                               length=max(size // 4, 1)))
        if kind == "stat":
            io.stat(name)
            return 0
        if kind == "infer":
            return self._infer(obj, size)
        # write: per-tenant namespace so writers never collide with
        # the shared read set
        io.write_full(f"lg-w-{tenant}-{obj & 7}",
                      _write_payload(size, obj & 7))
        return size


class RadosTarget(Target):
    """Drives a networked RadosClient IoCtx with the tenant identity
    threaded per op (MOSDOp v4)."""

    def __init__(self, io) -> None:
        self.io = io
        self._objects = 0
        self._infer_spec = None
        self._infer_via_read = False
        self._infer_lock = asyncio.Lock()

    async def setup(self, objects: int, object_size: int) -> None:
        data = _payload(object_size, seed=1)
        await asyncio.gather(*(self.io.write_full(f"lg-{i}", data)
                               for i in range(objects)))
        self._objects = objects

    async def _infer_model(self):
        """Lazily store the shared model: through the coded layout
        (store_model) when the pool is EC — infer ops then ride the
        MOSDCompute serving path — else as raw objects served by the
        client-side read-then-infer shape."""
        from ceph_tpu.rados.client import RadosError

        async with self._infer_lock:
            if self._infer_spec is None:
                from ceph_tpu.inference import registry
                try:
                    self._infer_spec = await self.io.store_model(
                        INFER_MODEL, "linear",
                        registry.make_model("linear", INFER_DIM,
                                            INFER_OUT, seed=7))
                except RadosError:
                    spec, blobs = _infer_blobs()
                    for oid, blob in blobs.items():
                        await self.io.write_full(oid, blob)
                    self._infer_spec = spec
                    self._infer_via_read = True
        return self._infer_spec

    async def _infer(self, obj: int, nq: int) -> int:
        from ceph_tpu.inference import model as inf_model
        from ceph_tpu.inference import registry as inf_registry

        spec = self._infer_spec or await self._infer_model()
        queries = _infer_queries(max(nq, 1), obj & 7)
        if self._infer_via_read:
            data = await self.io.read(
                inf_registry.params_oid(INFER_MODEL))
            return inf_model.exact_forward(spec, data, queries).nbytes
        res = await self.io.infer(spec, queries)
        return res["scores"].nbytes

    async def op(self, tenant: str, kind: str, obj: int,
                 size: int) -> int:
        from ceph_tpu.rados.client import RadosError, tenant_scope

        io = self.io
        name = f"lg-{obj % max(self._objects, 1)}"
        try:
            with tenant_scope(tenant):
                if kind == "read":
                    return len(await io.read(name))
                if kind == "ranged":
                    return len(await io.read(
                        name, offset=size // 4,
                        length=max(size // 4, 1)))
                if kind == "stat":
                    await io.stat(name)
                    return 0
                if kind == "infer":
                    return await self._infer(obj, size)
                await io.write_full(f"lg-w-{tenant}-{obj & 7}",
                                    _write_payload(size, obj & 7))
                return size
        except RadosError as e:
            if e.rc == EBUSY:
                raise SheddedOp(tenant) from e
            raise


class S3Target(Target):
    """Raw-socket S3 driver (sigv4 per request, the MiniS3 shape from
    the http test tier) with a small connection pool — open-loop
    concurrency must not serialize on one socket."""

    def __init__(self, addr: str, access: str, secret: str,
                 bucket: str = "loadgen", pool: int = 16) -> None:
        host, _, port = addr.rpartition(":")
        self.host, self.port = host, int(port)
        self.access, self.secret = access, secret
        self.bucket = bucket
        self._free: List[Tuple[asyncio.StreamReader,
                               asyncio.StreamWriter]] = []
        self._pool_cap = pool
        self._objects = 0

    async def _request(self, method: str, path: str,
                       headers: Optional[Dict[str, str]] = None,
                       body: bytes = b"") -> Tuple[int, bytes]:
        # one retry on a fresh connection: a pooled keep-alive socket
        # the server closed since its last use answers with EOF
        for attempt in (0, 1):
            pooled = bool(self._free) and attempt == 0
            try:
                return await self._request_once(method, path,
                                                headers, body,
                                                use_pool=pooled)
            except (ConnectionError, OSError,
                    asyncio.IncompleteReadError):
                if attempt or not pooled:
                    raise
        raise AssertionError("unreachable")

    async def _request_once(self, method: str, path: str,
                            headers: Optional[Dict[str, str]],
                            body: bytes,
                            use_pool: bool) -> Tuple[int, bytes]:
        from ceph_tpu.rgw.s3_frontend import sign_request

        if use_pool and self._free:
            reader, writer = self._free.pop()
        else:
            reader, writer = await asyncio.open_connection(
                self.host, self.port, limit=8 << 20)
        try:
            hdrs = {"Host": f"{self.host}:{self.port}",
                    **(headers or {})}
            hdrs = sign_request(method, path, {}, hdrs, body,
                                self.access, self.secret)
            hdrs["Content-Length"] = str(len(body))
            req = [f"{method} {path} HTTP/1.1\r\n"]
            for k, v in hdrs.items():
                req.append(f"{k}: {v}\r\n")
            req.append("\r\n")
            writer.write("".join(req).encode() + body)
            await writer.drain()
            status_line = await reader.readline()
            if not status_line.strip():
                # EOF: the peer closed this (stale pooled) connection
                raise ConnectionError("connection closed by peer")
            status = int(status_line.split()[1])
            rhdrs: Dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                rhdrs[k.strip().lower()] = v.strip()
            length = int(rhdrs.get("content-length", "0"))
            # HEAD replies carry Content-Length but NO body bytes
            rbody = await reader.readexactly(length) \
                if length and method != "HEAD" else b""
            if len(self._free) < self._pool_cap and \
                    rhdrs.get("connection", "").lower() != "close":
                self._free.append((reader, writer))
            else:
                writer.close()
            return status, rbody
        except BaseException:
            writer.close()
            raise

    def _key(self, obj: int) -> str:
        return f"/{self.bucket}/lg-{obj % max(self._objects, 1)}"

    async def setup(self, objects: int, object_size: int) -> None:
        status, _ = await self._request("PUT", f"/{self.bucket}")
        if status not in (200, 409):
            raise RuntimeError(f"bucket create failed: {status}")
        data = _payload(object_size, seed=1)
        for i in range(objects):
            status, _ = await self._request(
                "PUT", f"/{self.bucket}/lg-{i}", body=data)
            if status != 200:
                raise RuntimeError(f"prefill failed: {status}")
        self._objects = objects

    async def op(self, tenant: str, kind: str, obj: int,
                 size: int) -> int:
        if kind == "infer":
            # no S3 verb maps to coded scoring; misconfigured blends
            # must surface, not silently count as writes
            raise RuntimeError("s3 target does not serve infer ops")
        if kind == "read":
            status, body = await self._request("GET", self._key(obj))
        elif kind == "ranged":
            lo = size // 4
            hi = lo + max(size // 4, 1) - 1
            status, body = await self._request(
                "GET", self._key(obj),
                headers={"Range": f"bytes={lo}-{hi}"})
        elif kind == "stat":
            status, body = await self._request("HEAD", self._key(obj))
            body = b""
        else:
            body = b""
            status, _ = await self._request(
                "PUT", f"/{self.bucket}/lg-w-{tenant}-{obj & 7}",
                body=_payload(size, obj))
        if status == 503:
            raise SheddedOp(tenant)
        if status not in (200, 206):
            raise RuntimeError(f"s3 {kind} -> {status}")
        return len(body) if kind != "write" else size

    async def close(self) -> None:
        for _r, w in self._free:
            try:
                w.close()
            except Exception:
                pass
        self._free.clear()

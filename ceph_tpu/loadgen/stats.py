"""Streaming latency + goodput accounting for the open-loop harness.

HdrHistogram role: latency samples land in log-spaced buckets
(~4.4% relative resolution from 1 us to ~200 s) held in a few hundred
integer counters — memory is CONSTANT in the op count, so a
million-op sweep accounts exactly like a ten-op one and the
`unbounded-latency-buffer` lint rule has nothing to flag here.
Percentiles come from a cumulative walk over the buckets; merging two
histograms is element-wise addition, which is how per-tenant
recorders roll up into the aggregate report.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

# bucket geometry: shared by every histogram so merge() is plain
# element-wise addition
_LO = 1e-6            # 1 us floor: everything faster lands in bin 0
_HI = 200.0           # 200 s ceiling: everything slower saturates
_PER_OCTAVE = 16      # 2^(1/16) growth => ~4.4% relative error
_NBINS = int(math.log2(_HI / _LO) * _PER_OCTAVE) + 2


class LatencyHistogram:
    """Bounded-memory latency recorder with percentile queries."""

    __slots__ = ("bins", "count", "total", "max")

    def __init__(self) -> None:
        self.bins: List[int] = [0] * _NBINS
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    @staticmethod
    def _index(seconds: float) -> int:
        if seconds <= _LO:
            return 0
        return min(_NBINS - 1,
                   int(math.log2(seconds / _LO) * _PER_OCTAVE) + 1)

    @staticmethod
    def _edge(index: int) -> float:
        """Upper edge of a bucket (what percentile() reports): the
        true sample is within ~4.4% below it."""
        if index <= 0:
            return _LO
        return _LO * 2.0 ** (index / _PER_OCTAVE)

    def record(self, seconds: float) -> None:
        if seconds < 0.0:
            seconds = 0.0
        self.bins[self._index(seconds)] += 1
        self.count += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds

    def merge(self, other: "LatencyHistogram") -> None:
        for i, n in enumerate(other.bins):
            if n:
                self.bins[i] += n
        self.count += other.count
        self.total += other.total
        if other.max > self.max:
            self.max = other.max

    def percentile(self, q: float) -> Optional[float]:
        """Latency (seconds) at quantile q in [0, 1]; None when
        empty.  Reports the bucket's upper edge, capped at the
        observed max so p100 of one sample is that sample."""
        if self.count == 0:
            return None
        want = max(1, math.ceil(q * self.count))
        cum = 0
        for i, n in enumerate(self.bins):
            cum += n
            if cum >= want:
                return min(self._edge(i), self.max) if self.max \
                    else self._edge(i)
        return self.max

    def mean(self) -> Optional[float]:
        return (self.total / self.count) if self.count else None

    def to_perf_histogram(self) -> Dict[str, object]:
        """Prometheus-shaped export ({bounds, buckets, count, sum}):
        the fine log buckets fold per-octave so a stage histogram
        costs ~28 exposition rows, not ~450.  Bounds are upper edges
        in SECONDS; the mgr flattener renders cumulative
        `_bucket{le=...}` rows plus `_count`/`_sum`."""
        bounds: List[float] = []
        buckets: List[int] = []
        i = 1
        while i < _NBINS:
            j = min(i + _PER_OCTAVE, _NBINS)
            bounds.append(round(self._edge(j - 1), 9))
            buckets.append(self.bins[0] + sum(self.bins[i:j])
                           if i == 1 else sum(self.bins[i:j]))
            i = j
        return {"bounds": bounds, "buckets": buckets,
                "count": self.count, "sum": round(self.total, 6)}

    def to_dict(self) -> Dict[str, float]:
        """Percentile summary in milliseconds (report shape)."""
        out: Dict[str, float] = {"count": self.count}
        for q, name in ((0.5, "p50_ms"), (0.95, "p95_ms"),
                        (0.99, "p99_ms")):
            v = self.percentile(q)
            out[name] = round(v * 1e3, 3) if v is not None else None
        out["max_ms"] = round(self.max * 1e3, 3) if self.count else None
        out["mean_ms"] = round(self.mean() * 1e3, 3) \
            if self.count else None
        return out


class GoodputMeter:
    """Completed-work accounting: ops and payload bytes that finished
    SUCCESSFULLY (sheds, errors and drops are counted, not credited —
    goodput is the metric the north star is judged by, not offered
    throughput)."""

    __slots__ = ("ops", "bytes", "queries", "shed", "errors",
                 "dropped")

    def __init__(self) -> None:
        self.ops = 0
        self.bytes = 0
        self.queries = 0
        self.shed = 0
        self.errors = 0
        self.dropped = 0

    def ok(self, nbytes: int) -> None:
        self.ops += 1
        self.bytes += int(nbytes)

    def scored(self, nqueries: int, nbytes: int) -> None:
        """One completed `infer` op: credit the scored-query payload
        (queries are the goodput unit of the serving workload; the
        score bytes still count toward byte goodput)."""
        self.ops += 1
        self.queries += int(nqueries)
        self.bytes += int(nbytes)

    def merge(self, other: "GoodputMeter") -> None:
        self.ops += other.ops
        self.bytes += other.bytes
        self.queries += other.queries
        self.shed += other.shed
        self.errors += other.errors
        self.dropped += other.dropped

    def to_dict(self, elapsed_s: float) -> Dict[str, float]:
        dt = max(elapsed_s, 1e-9)
        out = {
            "completed": self.ops,
            "shed": self.shed,
            "errors": self.errors,
            "dropped": self.dropped,
            "ops_per_sec": round(self.ops / dt, 2),
            "goodput_mib_s": round(self.bytes / dt / (1 << 20), 3),
        }
        if self.queries:
            out["queries"] = self.queries
            out["queries_per_sec"] = round(self.queries / dt, 2)
        return out

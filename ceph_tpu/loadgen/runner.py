"""The open-loop engine.

Closed-loop load (issue -> await -> issue) hides queueing delay: when
the system slows down, the generator slows down with it and the
latency numbers stay flattering.  Open loop fires every op at its
SCHEDULED arrival time regardless of completions, and measures
latency from that scheduled instant — so a backlog shows up as tail
latency, which is the number a million independent clients actually
experience.

Memory discipline: latencies stream into bounded log-bucket
histograms (loadgen/stats.py), the schedule is merged lazily (one
pending event per tenant), and in-flight tasks are capped — an op
past the cap is counted `dropped` (overload accounting), never
silently queued without bound.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Iterable, Sequence

from ceph_tpu.loadgen.stats import GoodputMeter, LatencyHistogram
from ceph_tpu.loadgen.targets import SheddedOp, Target
from ceph_tpu.loadgen.workload import TenantSpec, merged_schedule


async def run_open_loop(target: Target,
                        tenants: Sequence[TenantSpec],
                        duration: float, seed: int = 0,
                        max_outstanding: int = 10_000,
                        per_tenant: Iterable[str] = (),
                        drain_timeout: float = 30.0) -> Dict:
    """Drive `target` with every tenant's merged schedule; returns the
    report dict (aggregate goodput + streaming percentiles, plus a
    per-tenant breakdown for the names in `per_tenant` — tracking
    every tenant of a 10k sweep would itself be an unbounded
    buffer)."""
    agg_h = LatencyHistogram()
    agg_g = GoodputMeter()
    tracked = {name: (LatencyHistogram(), GoodputMeter())
               for name in per_tenant}
    offered = 0
    inflight: set = set()
    loop = asyncio.get_running_loop()
    t0 = loop.time()

    async def fire(ev, sched_abs: float) -> None:
        t = tracked.get(ev.tenant)
        try:
            moved = await target.op(ev.tenant, ev.kind, ev.obj,
                                    ev.size)
        except SheddedOp:
            agg_g.shed += 1
            if t is not None:
                t[1].shed += 1
        except asyncio.CancelledError:
            raise
        except Exception:
            agg_g.errors += 1
            if t is not None:
                t[1].errors += 1
        else:
            lat = loop.time() - sched_abs
            agg_h.record(lat)
            # infer events carry their QUERY batch in ev.size: credit
            # scored queries (the serving goodput unit) alongside the
            # score bytes the target moved
            if ev.kind == "infer":
                agg_g.scored(ev.size, moved)
            else:
                agg_g.ok(moved)
            if t is not None:
                t[0].record(lat)
                if ev.kind == "infer":
                    t[1].scored(ev.size, moved)
                else:
                    t[1].ok(moved)

    for ev in merged_schedule(tenants, duration, seed):
        sched_abs = t0 + ev.t
        delay = sched_abs - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        offered += 1
        if len(inflight) >= max_outstanding:
            agg_g.dropped += 1
            t = tracked.get(ev.tenant)
            if t is not None:
                t[1].dropped += 1
            continue
        task = loop.create_task(fire(ev, sched_abs))
        inflight.add(task)
        task.add_done_callback(inflight.discard)

    if inflight:
        _done, pending = await asyncio.wait(set(inflight),
                                            timeout=drain_timeout)
        for p in pending:
            p.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
            agg_g.dropped += len(pending)
    elapsed = loop.time() - t0

    report: Dict = {
        "tenants": len(tenants),
        "offered": offered,
        "elapsed_s": round(elapsed, 3),
        **agg_g.to_dict(elapsed),
        **agg_h.to_dict(),
    }
    if tracked:
        report["per_tenant"] = {
            name: {**g.to_dict(elapsed), **h.to_dict()}
            for name, (h, g) in tracked.items()}
    return report


async def run_embedded(tenants: Sequence[TenantSpec],
                       duration: float, seed: int = 0,
                       objects: int = 64, object_size: int = 4096,
                       num_osds: int = 6,
                       per_tenant: Iterable[str] = (),
                       cluster=None) -> Dict:
    """One-call harness over the embedded LocalCluster (the smoke /
    bench-probe substrate): builds the cluster + pool, prefills the
    shared hot set, runs the open loop, tears down."""
    from ceph_tpu.loadgen.targets import EmbeddedTarget
    from ceph_tpu.rados.embedded import LocalCluster

    own = cluster is None
    if own:
        cluster = LocalCluster(num_osds=num_osds)
    try:
        if cluster.osdmap.lookup_pool("loadgen") < 0:
            cluster.create_replicated_pool("loadgen", size=2,
                                           pg_num=16)
        io = cluster.open_ioctx("loadgen")
        target = EmbeddedTarget(io)
        await target.setup(objects, object_size)
        return await run_open_loop(target, tenants, duration,
                                   seed=seed, per_tenant=per_tenant)
    finally:
        if own:
            cluster.shutdown()

"""Fisher-weighted parameter fusion and the approximate combine.

The algebra (arXiv:2409.01420 shape): data shard i holds parameter
block theta_i; fused shard j holds

    phi_j = sum_i A[j,i] * theta_i,        A = C * diag(omega)

where omega is the Fisher-normalized importance of each shard (fusion
distorts the least-important parameters most) and C is a Cauchy
matrix row-normalized so every fused block is a weighted AVERAGE of
the data blocks.  Cauchy structure is the load-bearing choice: every
square submatrix of a (positively row/column scaled) Cauchy matrix is
nonsingular, so ANY missing-shard pattern with enough fused results
is solvable — the rateless any-sufficient-set property
(arXiv:1804.10331) in the parameter domain.

For a LINEAR scorer the forward pass commutes with the fusion
exactly: r_j = Q @ phi_j^T = sum_i A[j,i] y_i up to float rounding,
so the combine is exact for any k-subset.  For the MLP the
nonlinearity opens a Jensen gap: r_j = f(phi_j) only approximates
sum_i A[j,i] f(theta_i).  The registry CALIBRATES that gap at store
time (per-fused-shard residual rho_j per unit query scale), and the
combine turns (which shards are missing) x (which fused rows answer)
into a STRUCTURAL error bound — computable before any result bytes
arrive, which is what lets the hedged gather's sufficiency predicate
decide "this arrival set can serve within budget" without waiting.

Every approximate-combine return MUST consult `check_budget` — the
`unbudgeted-approx-result` lint rule fails the build otherwise.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: safety margin over the calibrated worst residual: on-distribution
#: queries stay under the bound with room for float noise
RHO_MARGIN = 2.0

_TINY = 1e-12


def check_budget(est_error: float, budget: Optional[float]) -> bool:
    """THE error-budget gate: True when an approximate result with
    estimated relative error `est_error` may be served under
    `budget` (None = caller accepts any estimate).  Single choke
    point so the lint rule has one symbol to look for."""
    if budget is None:
        return True
    return float(est_error) <= float(budget)


def fisher_weights(blocks: Sequence[np.ndarray],
                   fisher: Optional[Sequence[float]] = None
                   ) -> np.ndarray:
    """Per-shard fusion weights omega (sum 1).  `fisher` supplies the
    per-shard Fisher information when the caller has calibration
    gradients; absent that, the empirical proxy is the parameter
    second moment (large-magnitude blocks carry more of the function
    and should dominate the average)."""
    if fisher is not None:
        f = np.asarray(fisher, dtype=np.float64)
    else:
        f = np.array([float(np.mean(np.square(
            np.asarray(b, dtype=np.float64)))) for b in blocks])
    f = np.maximum(f, _TINY)
    return f / f.sum()


def fusion_coeff(k: int, m: int, omega: np.ndarray) -> np.ndarray:
    """(m x k) fusion matrix A: Cauchy nodes x Fisher column scaling,
    rows normalized to sum 1 (each fused block is a weighted average,
    so fused forward passes live on the data shards' activation
    scale).  Positive scalings preserve the all-minors-nonsingular
    Cauchy property, so any |missing| <= |fused answered| pattern
    solves."""
    x = np.arange(1, m + 1, dtype=np.float64)
    y = np.arange(m + 1, m + k + 1, dtype=np.float64)
    cauchy = 1.0 / (x[:, None] + y[None, :])
    a = cauchy * np.asarray(omega, dtype=np.float64)[None, :]
    return a / a.sum(axis=1, keepdims=True)


def fuse_blocks(blocks: Sequence[Dict[str, np.ndarray]],
                coeff: np.ndarray) -> List[Dict[str, np.ndarray]]:
    """k same-shape parameter dicts -> m fused parameter dicts
    (element-wise weighted averages; float32 like the stored
    streams)."""
    out: List[Dict[str, np.ndarray]] = []
    for row in np.asarray(coeff, dtype=np.float64):
        fused: Dict[str, np.ndarray] = {}
        for name in blocks[0]:
            acc = np.zeros(blocks[0][name].shape, dtype=np.float64)
            for w, blk in zip(row, blocks):
                acc += w * np.asarray(blk[name], dtype=np.float64)
            fused[name] = acc.astype(np.float32)
        out.append(fused)
    return out


def query_scale(queries: np.ndarray) -> float:
    """RMS of the query batch — the unit the calibrated residuals are
    expressed per, so the bound tracks query magnitude."""
    q = np.asarray(queries, dtype=np.float64)
    return float(np.sqrt(np.mean(np.square(q))) + _TINY)


def _solver(coeff: np.ndarray, data_present: Sequence[int],
            fused_present: Sequence[int], k: int
            ) -> Optional[Tuple[np.ndarray, float]]:
    """(pseudo-inverse of the missing-block system, its spectral
    norm) for the arrival pattern, or None when the pattern cannot
    determine the missing contributions."""
    missing = [i for i in range(k) if i not in set(data_present)]
    if not missing:
        return np.zeros((0, 0)), 0.0
    if len(fused_present) < len(missing):
        return None
    a = np.asarray(coeff, dtype=np.float64)
    sub = a[np.asarray(fused_present)][:, np.asarray(missing)]
    pinv = np.linalg.pinv(sub)
    return pinv, float(np.linalg.norm(pinv, 2))


def _accum(spec: Dict[str, Any], nmissing: int) -> float:
    """Contribution-error -> output-error accumulation factor: the
    mlp combine SUMS contributions, so errors of the substituted
    shards can add coherently (sqrt(|missing|) worst case under the
    Frobenius bound); the linear combine concatenates, which
    preserves the aggregate RMS."""
    if spec.get("kind") == "mlp" and nmissing > 1:
        return float(np.sqrt(nmissing))
    return 1.0


def structural_error(spec: Dict[str, Any],
                     data_present: Sequence[int],
                     fused_present: Sequence[int],
                     qscale: float) -> Optional[float]:
    """Relative error bound for serving from this arrival pattern —
    a pure function of WHICH streams answered (plus the calibrated
    rho/yscale in the manifest), so the hedged gather's sufficiency
    predicate can price an arrival set before combining anything.
    None = pattern cannot serve at all."""
    k = int(spec["k"])
    solved = _solver(np.asarray(spec["coeff"], dtype=np.float64),
                     data_present, fused_present, k)
    if solved is None:
        return None
    _pinv, gain = solved
    if gain == 0.0:
        return 0.0
    nmissing = k - len(set(data_present))
    rho = np.asarray(spec["rho"], dtype=np.float64)
    eps = np.sqrt(np.sum(np.square(
        rho[np.asarray(fused_present)] * qscale)))
    yscale = float(spec.get("yscale", 1.0)) * qscale
    return float(_accum(spec, nmissing) * gain * eps /
                 max(yscale, _TINY))


def combine(spec: Dict[str, Any],
            data_parts: Dict[int, np.ndarray],
            fused_parts: Dict[int, np.ndarray],
            queries: np.ndarray,
            budget: Optional[float]
            ) -> Optional[Tuple[np.ndarray, float, int]]:
    """Fisher-averaged approximate combine: solve the missing data
    contributions from the fused results, then run the SAME fixed
    combine the exact paths use.  Returns (scores, est_error,
    substituted) or None when the budget check refuses (caller takes
    the exact full-decode fallback).

    est_error folds two signals: the structural calibration bound,
    and — when more fused rows answered than shards are missing — the
    measured least-squares inconsistency of the overdetermined fit
    (an on-line residual the calibration cannot fake)."""
    from ceph_tpu.inference import model as model_mod

    k = int(spec["k"])
    present = sorted(data_parts)
    fused_ids = sorted(fused_parts)
    missing = [i for i in range(k) if i not in data_parts]
    qscale = query_scale(queries)
    est = structural_error(spec, present, fused_ids, qscale)
    if est is None or not check_budget(est, budget):
        return None
    parts: List[np.ndarray] = [None] * k  # type: ignore[list-item]
    for i, y in data_parts.items():
        parts[i] = np.asarray(y, dtype=np.float32)
    if missing:
        a = np.asarray(spec["coeff"], dtype=np.float64)
        shape = next(iter(data_parts.values())).shape \
            if data_parts else next(iter(fused_parts.values())).shape
        rhs = []
        for j in fused_ids:
            r = np.asarray(fused_parts[j], dtype=np.float64)
            for i in present:
                r = r - a[j, i] * np.asarray(data_parts[i],
                                             dtype=np.float64)
            rhs.append(r.reshape(-1))
        sub = a[np.asarray(fused_ids)][:, np.asarray(missing)]
        sol, resid, _rank, _sv = np.linalg.lstsq(
            sub, np.stack(rhs), rcond=None)
        if len(fused_ids) > len(missing):
            # overdetermined: the fit residual is a measured lower
            # bound on the fused rows' inconsistency — amplify it
            # through the solver gain onto the output scale and take
            # the worse of the two estimates
            fit = np.stack(rhs) - sub @ sol
            gain = float(np.linalg.norm(np.linalg.pinv(sub), 2))
            yscale = float(spec.get("yscale", 1.0)) * qscale
            measured = _accum(spec, len(missing)) * gain * float(
                np.sqrt(np.mean(np.square(fit)))) / max(yscale, _TINY)
            est = max(est, measured)
            if not check_budget(est, budget):
                return None
        for row, i in enumerate(missing):
            parts[i] = sol[row].reshape(shape).astype(np.float32)
    scores = model_mod.combine_contributions(spec, parts)
    return scores, float(est), len(missing)

"""Model shapes, stream packing, and the host reference forwards.

Two architectures, both sharded k ways into SAME-SHAPE parameter
blocks (element-wise fusable, exactly like the codec fuses same-size
chunks):

- ``linear``  an embedding/scoring table row-partitioned: data shard
              i holds rows block P_i (rows x dim, zero-padded to a
              common row count); its contribution to query batch Q is
              y_i = Q @ P_i^T and the full answer is the concat of
              the un-padded row blocks.
- ``mlp``     a 2-layer MLP hidden-partitioned: shard i holds
              (W1_i: h x dim, b1_i: h, W2_i: out x h); its
              contribution is y_i = relu(Q @ W1_i^T + b1_i) @ W2_i^T
              and the full answer is the shard-ordered SUM plus the
              shared output bias b2 (carried in the manifest).

A serving STREAM is one shard's parameters packed as little-endian
float32 bytes — the exact bytes the OSD holding that chunk stream
reads back, so the per-shard forward runs on locally-held bytes with
no payload movement.  ``exact_forward`` (whole-object bytes -> final
scores, pure numpy, fixed op order) is the bit-exactness anchor: the
primary's full-decode fallback, the client-side kill switch, and the
compute-kill-switch reference all call it, so those three paths are
bit-identical by construction.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

ARCHS = ("linear", "mlp")


def _f32(buf) -> np.ndarray:
    return np.frombuffer(buf, dtype="<f4")


def stream_nbytes(spec: Dict[str, Any]) -> int:
    """Packed byte length of ONE serving stream (all streams equal —
    same-shape blocks are what makes them element-wise fusable)."""
    dim = int(spec["dim"])
    if spec["kind"] == "linear":
        return int(spec["rows"]) * dim * 4
    h, out = int(spec["hidden"]), int(spec["out"])
    return (h * dim + h + out * h) * 4


def pack_stream(spec: Dict[str, Any], params: Dict[str, np.ndarray]
                ) -> bytes:
    """One shard's parameter block -> stream bytes (little-endian
    float32, fixed member order)."""
    if spec["kind"] == "linear":
        table = np.ascontiguousarray(params["table"], dtype="<f4")
        assert table.shape == (int(spec["rows"]), int(spec["dim"]))
        return table.tobytes()
    w1 = np.ascontiguousarray(params["w1"], dtype="<f4")
    b1 = np.ascontiguousarray(params["b1"], dtype="<f4")
    w2 = np.ascontiguousarray(params["w2"], dtype="<f4")
    return w1.tobytes() + b1.tobytes() + w2.tobytes()


def unpack_stream(spec: Dict[str, Any], buf) -> Dict[str, np.ndarray]:
    """Stream bytes (possibly zero-padded past the packed length by
    the stripe interleave) -> parameter arrays."""
    need = stream_nbytes(spec)
    view = memoryview(buf)[:need]
    if len(view) < need:
        raise ValueError(
            f"short stream: {len(view)} < {need} bytes")
    dim = int(spec["dim"])
    if spec["kind"] == "linear":
        return {"table": _f32(view).reshape(int(spec["rows"]), dim)}
    h, out = int(spec["hidden"]), int(spec["out"])
    flat = _f32(view)
    w1 = flat[: h * dim].reshape(h, dim)
    b1 = flat[h * dim: h * dim + h]
    w2 = flat[h * dim + h:].reshape(out, h)
    return {"w1": w1, "b1": b1, "w2": w2}


def contribution_cols(spec: Dict[str, Any]) -> int:
    """Column count of one shard's contribution matrix (Q x cols):
    padded rows for linear, the output dim for mlp — IDENTICAL for
    data and fused streams, which is what lets a fused result
    substitute for a missing data result element-wise."""
    return int(spec["rows"] if spec["kind"] == "linear"
               else spec["out"])


def shard_forward(spec: Dict[str, Any], stream, queries: np.ndarray
                  ) -> np.ndarray:
    """Host forward pass of ONE stream's parameters over the query
    batch (Q x dim) -> (Q x cols) float32.  The bit-exact twin of the
    `inference` plan kind's device trace (ec/plan.py inference_eval)
    and the fallback when that dispatch degrades."""
    p = unpack_stream(spec, stream)
    q = np.asarray(queries, dtype=np.float32)
    if spec["kind"] == "linear":
        return q @ p["table"].T
    hid = np.maximum(q @ p["w1"].T + p["b1"][None, :],
                     np.float32(0.0))
    return hid @ p["w2"].T


def combine_contributions(spec: Dict[str, Any],
                          parts: List[np.ndarray]) -> np.ndarray:
    """k data-shard contributions (shard order) -> final scores.
    Fixed op order — every exact path funnels through here so the
    bit-parity contract holds across primary fallback, kill switch,
    and the compute-kill-switch reference."""
    if spec["kind"] == "linear":
        rows = [int(r) for r in spec["shard_rows"]]
        return np.concatenate(
            [np.asarray(p, dtype=np.float32)[:, :r]
             for p, r in zip(parts, rows)], axis=1)
    acc = np.zeros_like(np.asarray(parts[0], dtype=np.float32))
    for p in parts:
        acc = acc + np.asarray(p, dtype=np.float32)
    return acc + np.asarray(spec["b2"], dtype=np.float32)[None, :]


def object_streams(spec: Dict[str, Any], data) -> List[memoryview]:
    """Whole params-object logical bytes -> the k+m serving streams
    (the host twin of what each OSD's chunk stream holds; see
    registry.interleave_streams for the layout)."""
    from ceph_tpu.compute import data_shard_streams

    total = int(spec["k"]) + int(spec["m"])
    return data_shard_streams(data, total, int(spec["chunk"]))


def exact_forward(spec: Dict[str, Any], data,
                  queries: np.ndarray) -> np.ndarray:
    """THE exact oracle: whole-object logical bytes -> final scores,
    pure numpy, per-data-shard forward in shard order then the fixed
    combine.  Bit-identical across every exact execution path."""
    streams = object_streams(spec, data)
    k = int(spec["k"])
    parts = [shard_forward(spec, streams[i], queries)
             for i in range(k)]
    return combine_contributions(spec, parts)


def validate_spec(spec: Dict[str, Any]) -> None:
    """Wire manifest -> structural sanity (args come off the wire;
    malformed specs must surface as EINVAL, never a KeyError in the
    engine)."""
    if not isinstance(spec, dict) or spec.get("kind") not in ARCHS:
        raise ValueError(f"bad model kind {spec.get('kind')!r}")
    for key in ("dim", "k", "m", "rows", "chunk", "out"):
        if int(spec.get(key, 0)) <= 0:
            raise ValueError(f"bad model spec field {key!r}")
    if spec["kind"] == "mlp":
        if int(spec.get("hidden", 0)) <= 0:
            raise ValueError("mlp spec needs hidden")
        if len(spec.get("b2", ())) != int(spec["out"]):
            raise ValueError("mlp spec b2/out mismatch")
    else:
        rows = spec.get("shard_rows", ())
        if len(rows) != int(spec["k"]) or \
                sum(int(r) for r in rows) != int(spec["out"]):
            raise ValueError("linear spec shard_rows/out mismatch")
    coeff = np.asarray(spec.get("coeff", ()), dtype=np.float64)
    if coeff.shape != (int(spec["m"]), int(spec["k"])):
        raise ValueError("fusion coeff shape mismatch")

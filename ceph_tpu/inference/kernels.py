"""The inference kernels, registered through the compute seam.

Two kernels ride the existing compute wire ops:

- ``infer``        the object-level query kernel (MOSDCompute).  Its
  `eval_object` is the EXACT path: whole params object -> host
  reference forward -> canonical result blob.  Three different
  callers funnel into it — the primary's full-decode fallback, the
  CEPH_TPU_INFERENCE=0 client path, and the CEPH_TPU_COMPUTE=0
  reference — which is the bit-parity contract.  approx_capable=True
  routes its EC-pool waves to the InferenceEngine (osd/inference.py)
  instead of the GF pushdown.
- ``infer_shard``  the per-shard kernel the engine fans out with
  (MOSDSubCompute).  Its `shard_eval` runs one serving stream's
  forward pass over the query batch on the OSD holding it — through
  the plan cache's `inference` kind when a device tier is up, with
  the bit-exact numpy forward as the degraded path.

Both charge the `inference` mClock class, not `compute`.
"""

from __future__ import annotations

import base64
import binascii
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.compute import (
    ComputeError, ComputeKernel, EINVAL, canon_json,
)
from ceph_tpu.inference import (
    INFER_KERNEL, INFER_SHARD_KERNEL, model,
)


def encode_queries(queries: np.ndarray) -> str:
    """(nq, dim) float32 query batch -> wire text (b64 of the raw
    little-endian bytes)."""
    q = np.ascontiguousarray(queries, dtype="<f4")
    return base64.b64encode(q.tobytes()).decode("ascii")


def decode_queries(spec: Dict[str, Any], raw: Any) -> np.ndarray:
    """Wire text -> (nq, dim) float32, or ComputeError(EINVAL)."""
    try:
        buf = base64.b64decode(str(raw), validate=True)
    except (binascii.Error, ValueError):
        raise ComputeError(EINVAL, "bad query encoding")
    dim = int(spec["dim"])
    if len(buf) == 0 or len(buf) % (4 * dim):
        raise ComputeError(EINVAL, "query batch/dim mismatch")
    return np.frombuffer(buf, dtype="<f4").reshape(-1, dim)


def parse_infer_args(args: Dict[str, Any]
                     ) -> Tuple[Dict[str, Any], np.ndarray, bool,
                                Optional[float]]:
    """Wire args -> (spec, queries, exact, budget).  Args come off
    the wire: every malformed shape must surface as EINVAL, never as
    a KeyError inside the engine."""
    spec = args.get("model")
    try:
        model.validate_spec(spec)
    except (ValueError, TypeError) as e:
        raise ComputeError(EINVAL, f"bad model manifest: {e}")
    queries = decode_queries(spec, args.get("q"))
    budget = args.get("budget")
    if budget is not None:
        try:
            budget = float(budget)
        except (TypeError, ValueError):
            raise ComputeError(EINVAL, "bad budget")
        if not 0.0 <= budget < 1e6:
            raise ComputeError(EINVAL, "budget out of range")
    return spec, queries, bool(args.get("exact")), budget


def result_blob(scores: np.ndarray, mode: str, est_error: float,
                substituted: int) -> bytes:
    """Final scores -> the canonical result bytes.  Exact paths all
    build this from the same exact_forward float32 array with
    est_error 0.0, so their blobs are bit-identical."""
    s = np.ascontiguousarray(scores, dtype="<f4")
    return canon_json({
        "mode": mode,
        "est_error": float(est_error),
        "substituted": int(substituted),
        "nq": int(s.shape[0]),
        "out": int(s.shape[1]),
        "scores": base64.b64encode(s.tobytes()).decode("ascii"),
    })


def decode_result(blob: bytes) -> Dict[str, Any]:
    """Result bytes -> dict with `scores` decoded to (nq, out)."""
    import json

    out = json.loads(bytes(blob))
    buf = base64.b64decode(out["scores"])
    out["scores"] = np.frombuffer(buf, dtype="<f4").reshape(
        int(out["nq"]), int(out["out"]))
    return out


def plan_sig(spec: Dict[str, Any]) -> str:
    """Plan-cache signature for the `inference` kind: parameters are
    RUNTIME operands, so every dim must live here (only the query
    batch rides the key's bucketed axis)."""
    if spec["kind"] == "linear":
        return f"infer/linear/d{spec['dim']}/r{spec['rows']}"
    return (f"infer/mlp/d{spec['dim']}/h{spec['hidden']}"
            f"/o{spec['out']}")


def _device_contributions(spec: Dict[str, Any],
                          params: List[Dict[str, np.ndarray]],
                          queries: np.ndarray
                          ) -> Optional[np.ndarray]:
    """Stacked streams through the plan cache's `inference` kind;
    None -> caller takes the numpy forward."""
    from ceph_tpu.ec import plan

    if spec["kind"] == "linear":
        ops = (np.stack([p["table"] for p in params]),)
    else:
        ops = (np.stack([p["w1"] for p in params]),
               np.stack([p["b1"] for p in params]),
               np.stack([p["w2"] for p in params]))
    return plan.inference_eval(spec["kind"], ops, queries,
                               plan_sig(spec))


class InferKernel(ComputeKernel):
    """Object-level coded inference: EC-pool waves route to the
    InferenceEngine (approx_capable pushdown with the Fisher
    combine); `eval_object` is THE exact path every fallback and
    kill switch shares."""

    name = INFER_KERNEL
    linear = False
    approx_capable = True
    qos_class = "inference"

    def validate_args(self, args: Dict[str, Any]) -> None:
        parse_infer_args(args)

    def eval_object(self, data, args: Dict[str, Any]) -> bytes:
        spec, queries, _exact, _budget = parse_infer_args(args)
        scores = model.exact_forward(spec, data, queries)
        return result_blob(scores, "exact", 0.0, 0)


class InferShardKernel(ComputeKernel):
    """Per-shard forward pass over one serving stream: the fan-out
    body of the engine's dispatch stage.  Results are raw float32
    contribution matrices (nq x cols) — the engine combines them in
    the result domain."""

    name = INFER_SHARD_KERNEL
    linear = False
    approx_capable = True
    qos_class = "inference"

    def validate_args(self, args: Dict[str, Any]) -> None:
        spec, _q, _e, _b = parse_infer_args(args)
        stream = args.get("stream")
        try:
            stream = int(stream)
        except (TypeError, ValueError):
            raise ComputeError(EINVAL, "bad stream index")
        if not 0 <= stream < int(spec["k"]) + int(spec["m"]):
            raise ComputeError(EINVAL, "stream index out of range")

    def eval_object(self, data, args: Dict[str, Any]) -> bytes:
        raise ComputeError(
            EINVAL, "infer_shard is shard-level only (use infer)")

    def shard_eval(self, payloads: Sequence,
                   args: Dict[str, Any]) -> List[bytes]:
        self.validate_args(args)
        spec, queries, _exact, _budget = parse_infer_args(args)
        params: List[Dict[str, np.ndarray]] = []
        bad: Dict[int, bool] = {}
        for i, payload in enumerate(payloads):
            try:
                params.append(model.unpack_stream(spec, payload))
            except ValueError:
                bad[i] = True
                params.append(None)  # type: ignore[arg-type]
        good = [p for p in params if p is not None]
        contrib = _device_contributions(spec, good, queries) \
            if good else None
        out: List[bytes] = []
        row = 0
        for i in range(len(payloads)):
            if bad.get(i):
                # a short stream is this shard's failure, not the
                # wave's: an empty result the primary's collate drops
                out.append(b"")
                continue
            if contrib is not None:
                y = np.asarray(contrib[row], dtype="<f4")
            else:
                # degraded/absent device tier: bit-exact numpy twin
                y = np.ascontiguousarray(model.shard_forward(
                    spec, payloads[i], queries), dtype="<f4")
            row += 1
            out.append(y.tobytes())
        return out


def register_defaults(register) -> None:
    register(InferKernel())
    register(InferShardKernel())

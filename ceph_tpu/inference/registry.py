"""Model registry: store-time Fisher fusion and the coded layout.

Storing a model named N in an EC(k+m, m_pool) pool produces two
objects:

- ``N.manifest``  the canonical-JSON spec: shapes, the fusion
  coefficient matrix (Fisher weights already folded in), the
  calibrated per-fused-shard residuals rho, and the output scale —
  everything the engine and the client kill-switch path need.
- ``N.params``    ONE logical object whose k+m DATA chunk streams are
  the k data parameter shards followed by the m Fisher-fused shards,
  interleaved stripe-by-stripe exactly like ECUtil does, so each
  serving stream lands whole as one OSD's locally-held chunk stream
  and the per-shard forward runs on bytes that never move.  The pool
  codec's GF parity shards ride behind for durability.

Calibration happens HERE, once, at store time: a fixed seeded query
batch measures each fused shard's true Jensen-gap residual (zero up
to float rounding for the linear scorer), and rho carries that —
times a safety margin — into every future query's structural error
bound.  No query-time calibration, no drift.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.inference import fisher, model

#: queries in the store-time calibration batch
CAL_QUERIES = 64
_CAL_SEED = 0x1F15


def manifest_oid(name: str) -> str:
    return f"{name}.manifest"


def params_oid(name: str) -> str:
    return f"{name}.params"


def split_rows(total: int, k: int) -> List[int]:
    """Balanced row partition (first shards take the remainder)."""
    base, extra = divmod(total, k)
    return [base + (1 if i < extra else 0) for i in range(k)]


def make_model(kind: str, dim: int, out: int, *, seed: int = 0,
               hidden: int = 0) -> Dict[str, np.ndarray]:
    """Deterministic random model for tests, loadgen, and the bench
    probe (float32, unit-ish scale)."""
    rng = np.random.default_rng(seed)
    if kind == "linear":
        return {"table": rng.standard_normal(
            (out, dim)).astype(np.float32)}
    if kind != "mlp":
        raise ValueError(f"bad model kind {kind!r}")
    scale = np.float32(1.0 / np.sqrt(dim))
    return {
        "w1": (rng.standard_normal((hidden, dim)) * scale
               ).astype(np.float32),
        "b1": (0.1 * rng.standard_normal(hidden)).astype(np.float32),
        "w2": (rng.standard_normal((out, hidden)) /
               np.sqrt(hidden)).astype(np.float32),
        "b2": (0.1 * rng.standard_normal(out)).astype(np.float32),
    }


def shard_params(kind: str, params: Dict[str, np.ndarray], k: int
                 ) -> Tuple[List[Dict[str, np.ndarray]],
                            Dict[str, Any]]:
    """Whole model -> k SAME-SHAPE parameter blocks + the shape
    metadata the manifest carries.  linear: row partition zero-padded
    to a common row count; mlp: hidden partition (hidden % k == 0 so
    the blocks fuse element-wise)."""
    if kind == "linear":
        table = np.asarray(params["table"], dtype=np.float32)
        out, dim = table.shape
        shard_rows = split_rows(out, k)
        rows = max(shard_rows)
        blocks, start = [], 0
        for r in shard_rows:
            blk = np.zeros((rows, dim), dtype=np.float32)
            blk[:r] = table[start:start + r]
            blocks.append({"table": blk})
            start += r
        return blocks, {"rows": rows, "shard_rows": shard_rows,
                        "dim": dim, "out": out}
    w1 = np.asarray(params["w1"], dtype=np.float32)
    hidden, dim = w1.shape
    if hidden % k:
        raise ValueError(f"mlp hidden {hidden} not divisible by k={k}")
    h = hidden // k
    w2 = np.asarray(params["w2"], dtype=np.float32)
    out = w2.shape[0]
    b1 = np.asarray(params["b1"], dtype=np.float32)
    blocks = [{"w1": w1[i * h:(i + 1) * h],
               "b1": b1[i * h:(i + 1) * h],
               "w2": np.ascontiguousarray(w2[:, i * h:(i + 1) * h])}
              for i in range(k)]
    return blocks, {"rows": h, "hidden": h, "dim": dim, "out": out,
                    "b2": [float(v) for v in params["b2"]]}


def interleave_streams(streams: Sequence[bytes], chunk: int) -> bytes:
    """k+m equal-length chunk streams -> the logical object bytes
    whose ECUtil split hands each stream back whole (the exact
    inverse of compute.data_shard_streams)."""
    total = len(streams)
    stripes = len(streams[0]) // chunk
    cube = np.empty((stripes, total, chunk), dtype=np.uint8)
    for t, s in enumerate(streams):
        cube[:, t, :] = np.frombuffer(s, dtype=np.uint8
                                      ).reshape(stripes, chunk)
    return cube.tobytes()


def _calibrate(spec: Dict[str, Any], streams: Sequence[bytes]
               ) -> Tuple[List[float], float]:
    """Measure each fused shard's combine residual on a fixed seeded
    query batch -> (rho per fused shard, output scale), both per unit
    query RMS.  Conservative by construction: rho is the WORST
    per-query residual (times the safety margin) and yscale the
    SMALLEST per-query output magnitude, so the structural bound
    stays an upper bound for on-distribution queries it never saw."""
    k, m = int(spec["k"]), int(spec["m"])
    rng = np.random.default_rng(_CAL_SEED)
    q = rng.standard_normal(
        (CAL_QUERIES, int(spec["dim"]))).astype(np.float32)
    qrms = np.sqrt(np.mean(np.square(
        q.astype(np.float64)), axis=1)) + 1e-12
    parts = [model.shard_forward(spec, streams[i], q)
             for i in range(k)]
    coeff = np.asarray(spec["coeff"], dtype=np.float64)
    rho: List[float] = []
    for j in range(m):
        got = np.asarray(model.shard_forward(spec, streams[k + j], q),
                         dtype=np.float64)
        want = np.zeros_like(got)
        for i in range(k):
            want += coeff[j, i] * np.asarray(parts[i],
                                             dtype=np.float64)
        per_q = np.sqrt(np.mean(np.square(got - want), axis=1))
        rho.append(fisher.RHO_MARGIN *
                   max(float(np.max(per_q / qrms)), 1e-9))
    exact = np.asarray(model.combine_contributions(spec, parts),
                       dtype=np.float64)
    yscale = float(np.min(
        np.sqrt(np.mean(np.square(exact), axis=1)) / qrms)) + 1e-12
    return rho, yscale


def build(name: str, kind: str, params: Dict[str, np.ndarray],
          k: int, m: int, chunk: int,
          fisher_info: Optional[Sequence[float]] = None
          ) -> Tuple[Dict[str, Any], Dict[str, bytes]]:
    """Whole model -> (spec, {oid: object bytes}) ready to write into
    an EC(k+m, ...) pool whose codec chunk size is `chunk`.  The
    heavy lifting: shard, Fisher-fuse, pack k+m serving streams,
    calibrate rho/yscale against the packed bytes (the exact bytes
    the OSDs will serve), and interleave the params object."""
    blocks, meta = shard_params(kind, params, k)
    omega = fisher.fisher_weights(
        [np.concatenate([np.ravel(b[n]) for n in sorted(b)])
         for b in blocks], fisher_info)
    coeff = fisher.fusion_coeff(k, m, omega)
    fused = fisher.fuse_blocks(blocks, coeff)
    spec: Dict[str, Any] = {
        "name": name, "kind": kind, "k": k, "m": m,
        "chunk": int(chunk), "dtype": "float32",
        "coeff": [[float(v) for v in row] for row in coeff],
        "params_oid": params_oid(name),
    }
    spec.update(meta)
    spec["stream_bytes"] = model.stream_nbytes(spec)
    padded = -spec["stream_bytes"] % chunk
    streams = [model.pack_stream(spec, b) + bytes(padded)
               for b in blocks + fused]
    rho, yscale = _calibrate(spec, streams)
    spec["rho"] = rho
    spec["yscale"] = yscale
    model.validate_spec(spec)
    from ceph_tpu.compute import canon_json

    return spec, {
        manifest_oid(name): canon_json(spec),
        params_oid(name): interleave_streams(streams, chunk),
    }

"""Coded inference serving: Fisher-fused approximate ML scoring over
erasure-coded shards.

ROADMAP item 5, the serving half: embedding tables and small model
shards (linear scorer / small MLP) are STORED erasure-coded and
QUERIED through the code, so the one workload the north star names —
serving ML features off the object store — never pays k whole-shard
reads plus a decode per query, and is straggler-flat by construction.

The load-bearing ideas:

* arXiv:2409.01420 "Erasure Coded Neural Network Inference via Fisher
  Averaging": a nonlinear model does NOT commute with a GF parity
  chunk, but parameters fused in a Fisher-weighted space do commute
  APPROXIMATELY in the result domain — a fused shard's forward pass
  approximates the same weighted combination of the per-shard forward
  passes that its parameters are of the per-shard parameters, with a
  Jensen-gap error that Fisher weighting minimizes where it matters.
  The registry (inference/registry.py) derives m such fused parameter
  shards at STORE time, alongside the codec's k+m data/parity shards.

* arXiv:1804.10331 rateless coded matmul: the query completes on ANY
  sufficient shard-result set.  The primary fans the per-shard
  forward passes over the OSDs holding the serving streams (the PR-14
  MOSDSubCompute wire op) through the PR-6 HedgeTracker with need=k,
  and combines the FIRST sufficient arrival set — all k data results
  give the exact answer; fused results substitute for stragglers with
  a Fisher-averaged approximate combine (inference/fisher.py).

Layout: the registry interleaves the k data parameter shards AND the
m fused parameter shards as the k+m data chunk streams of ONE params
object in an EC(k+m, m_pool) pool — the pool codec's GF parity rides
behind them for durability, and every serving stream is exactly one
OSD's locally-held shard chunk stream (the same bytes
`eval_local_shards` reads for the linear compute kernels).

Error discipline: every query carries an error budget
(`osd_inference_error_budget` by default).  The combine path may only
return an approximate result after consulting `fisher.check_budget`
(the `unbudgeted-approx-result` lint rule enforces this); a budget
the structural error bound cannot meet — or a caller demanding
exactness — takes the exact full-decode fallback (hedged first-k
read of the whole params object + the host reference forward pass).

Kill switch: CEPH_TPU_INFERENCE=0 restores client-side
read-then-infer with the same host reference forward — bit-identical
to the exact fallback (the parity leg tests/test_inference.py
drives).
"""

from __future__ import annotations

import os

from ceph_tpu.common import flags

#: default per-query relative error budget (osd_inference_error_budget)
DEFAULT_ERROR_BUDGET = 0.05

#: the one client-visible kernel name (IoCtx.infer sends it) and the
#: per-shard kernel the engine fans out with
INFER_KERNEL = "infer"
INFER_SHARD_KERNEL = "infer_shard"


def env_enabled() -> bool:
    """CEPH_TPU_INFERENCE=0 restores client-side read-then-infer."""
    return flags.enabled("CEPH_TPU_INFERENCE")

"""Run an MDS as a real process: python -m ceph_tpu.mds

Prints `MDS_ADDR <host:port>` once bound (ceph-helpers run_mds role).
The daemon starts standby and becomes active when it wins the
mds_lock; standbys take over from a dead active automatically.
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from ceph_tpu.mds import MDSDaemon


async def _main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mon", type=str, required=True)
    ap.add_argument("--name", type=str, default="a")
    ap.add_argument("--metadata-pool", type=str, default="cephfs.meta")
    ap.add_argument("--data-pool", type=str, default="cephfs.data")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--secret", type=str, default="",
                    help="cluster cephx keyring")
    ap.add_argument("--secure", action="store_true",
                    help="on-wire encryption (requires --secret)")
    args = ap.parse_args()
    mds = MDSDaemon(args.mon, args.metadata_pool, args.data_pool,
                    name=args.name, secret=args.secret or None,
                    secure=args.secure)
    addr = await mds.start(port=args.port)
    print(f"MDS_ADDR {addr}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await mds.stop()


if __name__ == "__main__":
    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        sys.exit(0)

"""MDS role: the filesystem metadata server.

Reference parity: the ceph-mds daemon
(/root/reference/src/mds/MDSDaemon.cc, MDCache.cc, Server.cc) — a
single ACTIVE metadata server owns the namespace, serializes every
metadata mutation, and stores directories as objects in a METADATA
pool (one object per directory fragment, dentries in omap —
CDir::commit, src/mds/CDir.cc).  Clients send MClientRequest ops for
metadata and do file DATA I/O directly against the data pool.

Re-designs vs the reference, deliberate:

- WRITE-THROUGH metadata instead of the MDS journal: every mutation
  lands in the directory object's omap (replicated, logged, recovered
  by RADOS) before the client sees an ack, so RADOS is the journal.
  The reference's MDLog exists to batch and reorder updates for
  latency; correctness comes from the same place (rados durability).
  An MDS restart recovers by lazily reloading directory objects — no
  replay phase.
- Active/standby election rides cls_lock: the active MDS holds an
  exclusive lock on the `mds_lock` object (renewed on a heartbeat
  interval, stored with its address); a standby polls, breaks a stale
  lock, and takes over (the mon's MDSMap beacon machinery, collapsed
  onto the object-lock it ultimately implements).
- Inode numbers come from an atomic numops counter object (InoTable
  role, src/mds/InoTable.h).

Layout in the metadata pool:
  mds_lock                 cls_lock state + active MDS addr (xattr)
  mds_ino                  omap: {"next": counter}
  dir.<ino:x>              omap: dentry name -> inode JSON
File data objects (data pool): fsdata.<ino:x>.<blockno:016x>
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Dict, Optional, Tuple

from ceph_tpu.msg import Connection, Messenger
from ceph_tpu.msg.messages import (
    MClientReply,
    MClientRequest,
    Message,
)
from ceph_tpu.rados.client import (
    IoCtx,
    ObjectNotFound,
    RadosClient,
    RadosError,
)

log = logging.getLogger("mds")

EPERM = -1
ENOENT = -2
EIO = -5
EEXIST = -17
ENOTDIR = -20
EISDIR = -21
EINVAL = -22
ENOTEMPTY = -39
ESTALE = -116

ROOT_INO = 1
LOCK_OBJ = "mds_lock"
INO_OBJ = "mds_ino"
ADDR_ATTR = "mds.addr"


def dir_obj(ino: int) -> str:
    return f"dir.{ino:x}"


def data_obj(ino: int, blockno: int) -> str:
    return f"fsdata.{ino:x}.{blockno:016x}"


class MDSDaemon:
    """Single-active metadata server with standby takeover."""

    def __init__(self, mon_addr: str, metadata_pool: str,
                 data_pool: str, name: str = "a",
                 lock_interval: float = 1.0,
                 secret: "Optional[str]" = None):
        self.mon_addr = mon_addr
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool
        self.name = name
        self.lock_interval = lock_interval
        from ceph_tpu.common.auth import parse_secret

        self.client = RadosClient(mon_addr, name=f"mds.{name}",
                                  secret=secret)
        self.msgr = Messenger(f"mds.{name}",
                              secret=parse_secret(secret))
        self.msgr.dispatcher = self._dispatch
        self.meta: Optional[IoCtx] = None
        self.state = "standby"
        # dirty-free write-through cache: dir ino -> {name: inode dict}
        self._dirs: Dict[int, Dict[str, dict]] = {}
        self._lock_task: Optional[asyncio.Task] = None
        self._stopping = False
        # namespace mutations serialize through one lock (the MDS's
        # whole reason to exist); reads go lock-free off the cache
        self._mutation_lock = asyncio.Lock()

    # -- lifecycle ---------------------------------------------------------

    async def start(self, port: int = 0) -> str:
        await self.client.connect()
        self.meta = self.client.open_ioctx(self.metadata_pool)
        addr = await self.msgr.bind(port=port)
        self._lock_task = asyncio.get_running_loop().create_task(
            self._lock_loop())
        return addr

    async def stop(self) -> None:
        self._stopping = True
        if self._lock_task is not None:
            self._lock_task.cancel()
            try:
                await self._lock_task
            except asyncio.CancelledError:
                pass
        if self.state == "active":
            try:
                await self.meta.execute(LOCK_OBJ, "lock", "unlock",
                                        json.dumps({
                                            "name": "active",
                                            "owner": self.name,
                                        }).encode())
            except Exception:
                pass
        await self.msgr.shutdown()
        await self.client.shutdown()

    # -- active/standby via cls_lock (MDSMap beacon role) ------------------

    async def _lock_loop(self) -> None:
        while not self._stopping:
            try:
                await self._try_acquire_or_renew()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("mds.%s: lock loop error", self.name)
            await asyncio.sleep(self.lock_interval)

    async def _try_acquire_or_renew(self) -> None:
        req = json.dumps({"name": "active", "type": "exclusive",
                          "owner": self.name,
                          "tag": "mds"}).encode()
        try:
            await self.meta.execute(LOCK_OBJ, "lock", "lock", req)
        except RadosError:
            # someone else is active: stale-ness check — if their
            # renewal stamp is old, break the lock and take over
            if self.state == "active":
                # lost our own lock (e.g. broken by a standby while we
                # were partitioned): step down, drop caches
                log.warning("mds.%s: lost the active lock, standby",
                            self.name)
                self.state = "standby"
                self._dirs.clear()
            try:
                raw = await self.meta.getxattr(LOCK_OBJ, "renewal")
                holder, stamp = json.loads(raw)
                if time.time() - stamp < self.lock_interval * 5:
                    return  # holder is live
                await self.meta.execute(
                    LOCK_OBJ, "lock", "break_lock",
                    json.dumps({"name": "active",
                                "locker": holder}).encode())
                log.warning("mds.%s: broke stale lock of mds.%s",
                            self.name, holder)
            except (RadosError, ObjectNotFound, ValueError):
                pass
            return
        # lock held (fresh or renewal): stamp + publish the address
        await self.meta.setxattr(
            LOCK_OBJ, "renewal",
            json.dumps([self.name, time.time()]).encode())
        await self.meta.setxattr(LOCK_OBJ, ADDR_ATTR,
                                 self.msgr.addr.encode())
        if self.state != "active":
            log.info("mds.%s: ACTIVE at %s", self.name, self.msgr.addr)
            self.state = "active"
            self._dirs.clear()  # cold cache: reload from rados
            await self._ensure_root()

    async def _ensure_root(self) -> None:
        try:
            await self.meta.omap_get(dir_obj(ROOT_INO))
        except ObjectNotFound:
            await self.meta.omap_set(dir_obj(ROOT_INO), {})
            await self.meta.omap_set(INO_OBJ,
                                     {"next": str(ROOT_INO + 1).encode()})

    async def _alloc_ino(self) -> int:
        out = await self.meta.execute(
            INO_OBJ, "numops", "add",
            json.dumps({"key": "next", "value": 1}).encode())
        return int(float(out.decode()))

    # -- directory cache (write-through; CDir::fetch/commit roles) ---------

    async def _load_dir(self, ino: int) -> Dict[str, dict]:
        cached = self._dirs.get(ino)
        if cached is not None:
            return cached
        try:
            omap = await self.meta.omap_get(dir_obj(ino))
        except ObjectNotFound:
            raise MDSError(ENOENT, f"no directory {ino:x}")
        entries = {name: json.loads(raw.decode())
                   for name, raw in omap.items()}
        self._dirs[ino] = entries
        return entries

    async def _store_dentry(self, dir_ino: int, name: str,
                            inode: Optional[dict]) -> None:
        if inode is None:
            await self.meta.omap_rm_keys(dir_obj(dir_ino), [name])
            self._dirs.get(dir_ino, {}).pop(name, None)
        else:
            await self.meta.omap_set(
                dir_obj(dir_ino),
                {name: json.dumps(inode).encode()})
            self._dirs.setdefault(dir_ino, {})[name] = inode

    # -- path resolution (MDCache::path_traverse role) ---------------------

    async def _resolve(self, path: str) -> Tuple[int, str,
                                                 Optional[dict]]:
        """path -> (parent dir ino, leaf name, inode | None).
        '/' resolves to (0, '', root-pseudo-inode)."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            return 0, "", {"ino": ROOT_INO, "type": "dir", "mode": 0o755,
                           "size": 0, "mtime": 0}
        cur = ROOT_INO
        for i, part in enumerate(parts[:-1]):
            entries = await self._load_dir(cur)
            inode = entries.get(part)
            if inode is None:
                raise MDSError(ENOENT, "/".join(parts[:i + 1]))
            if inode["type"] != "dir":
                raise MDSError(ENOTDIR, part)
            cur = inode["ino"]
        entries = await self._load_dir(cur)
        return cur, parts[-1], entries.get(parts[-1])

    # -- request dispatch (Server::handle_client_request role) -------------

    async def _dispatch(self, conn: Connection, msg: Message) -> None:
        if not isinstance(msg, MClientRequest):
            return
        if self.state != "active":
            await conn.send(MClientReply(msg.tid, ESTALE,
                                         {"error": "not active"}))
            return
        handler = getattr(self, f"_op_{msg.op}", None)
        if handler is None:
            await conn.send(MClientReply(msg.tid, EINVAL,
                                         {"error": f"bad op {msg.op}"}))
            return
        try:
            if msg.op in ("lookup", "readdir", "stat", "readlink"):
                rc, out = await handler(msg.args)   # lock-free reads
            else:
                async with self._mutation_lock:
                    rc, out = await handler(msg.args)
        except MDSError as e:
            rc, out = e.rc, {"error": str(e)}
        except ObjectNotFound:
            rc, out = ENOENT, {}
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("mds.%s: op %s failed", self.name, msg.op)
            rc, out = EIO, {}
        try:
            await conn.send(MClientReply(msg.tid, rc, out))
        except (ConnectionError, OSError):
            pass

    # -- metadata ops ------------------------------------------------------

    @staticmethod
    def _now() -> float:
        return time.time()

    async def _op_mkdir(self, args) -> Tuple[int, Dict[str, Any]]:
        parent, name, existing = await self._resolve(args["path"])
        if not name:
            return EEXIST, {}
        if existing is not None:
            return EEXIST, {}
        ino = await self._alloc_ino()
        await self.meta.omap_set(dir_obj(ino), {})
        inode = {"ino": ino, "type": "dir",
                 "mode": args.get("mode", 0o755),
                 "size": 0, "mtime": self._now()}
        await self._store_dentry(parent, name, inode)
        return 0, {"inode": inode}

    async def _op_create(self, args) -> Tuple[int, Dict[str, Any]]:
        parent, name, existing = await self._resolve(args["path"])
        if not name:
            return EISDIR, {}
        if existing is not None:
            if existing["type"] == "dir":
                return EISDIR, {}
            if args.get("exclusive"):
                return EEXIST, {}
            return 0, {"inode": existing}
        ino = await self._alloc_ino()
        inode = {"ino": ino, "type": "file",
                 "mode": args.get("mode", 0o644),
                 "size": 0, "mtime": self._now(),
                 "block_size": int(args.get("block_size", 1 << 22))}
        await self._store_dentry(parent, name, inode)
        return 0, {"inode": inode}

    async def _op_symlink(self, args) -> Tuple[int, Dict[str, Any]]:
        parent, name, existing = await self._resolve(args["path"])
        if not name or existing is not None:
            return EEXIST, {}
        ino = await self._alloc_ino()
        inode = {"ino": ino, "type": "symlink",
                 "mode": 0o777, "size": len(args["target"]),
                 "mtime": self._now(), "target": args["target"]}
        await self._store_dentry(parent, name, inode)
        return 0, {"inode": inode}

    async def _op_lookup(self, args) -> Tuple[int, Dict[str, Any]]:
        _parent, _name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        return 0, {"inode": inode}

    _op_stat = _op_lookup

    async def _op_readlink(self, args) -> Tuple[int, Dict[str, Any]]:
        _p, _n, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] != "symlink":
            return EINVAL, {}
        return 0, {"target": inode["target"]}

    async def _op_readdir(self, args) -> Tuple[int, Dict[str, Any]]:
        _parent, _name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] != "dir":
            return ENOTDIR, {}
        entries = await self._load_dir(inode["ino"])
        return 0, {"entries": {n: i for n, i in sorted(entries.items())}}

    async def _op_unlink(self, args) -> Tuple[int, Dict[str, Any]]:
        parent, name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] == "dir":
            return EISDIR, {}
        await self._store_dentry(parent, name, None)
        return 0, {"inode": inode}  # client purges the data objects

    async def _op_rmdir(self, args) -> Tuple[int, Dict[str, Any]]:
        parent, name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] != "dir":
            return ENOTDIR, {}
        entries = await self._load_dir(inode["ino"])
        if entries:
            return ENOTEMPTY, {}
        await self._store_dentry(parent, name, None)
        try:
            await self.meta.remove(dir_obj(inode["ino"]))
        except ObjectNotFound:
            pass
        self._dirs.pop(inode["ino"], None)
        return 0, {}

    async def _op_rename(self, args) -> Tuple[int, Dict[str, Any]]:
        src_parent, src_name, inode = await self._resolve(args["src"])
        if inode is None:
            return ENOENT, {}
        dst_parent, dst_name, existing = await self._resolve(
            args["dst"])
        if not dst_name:
            return EINVAL, {}
        if existing is not None:
            if existing["type"] == "dir":
                if inode["type"] != "dir":
                    return EISDIR, {}
                if await self._load_dir(existing["ino"]):
                    return ENOTEMPTY, {}
            elif inode["type"] == "dir":
                return ENOTDIR, {}
        # link target first, unlink source second: a crash between the
        # two leaves an extra (visible, fsck-able) link rather than a
        # lost file — the MDS journal's EUpdate would make this atomic
        await self._store_dentry(dst_parent, dst_name, inode)
        if (src_parent, src_name) != (dst_parent, dst_name):
            await self._store_dentry(src_parent, src_name, None)
        return 0, {"inode": inode}

    async def _op_setattr(self, args) -> Tuple[int, Dict[str, Any]]:
        parent, name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        changed = False
        for key in ("size", "mode", "mtime"):
            if key in args:
                inode[key] = args[key]
                changed = True
        if args.get("size_max") is not None:
            # concurrent writers race size updates: take the max
            # (the size-extending cap flush discipline)
            new = max(inode.get("size", 0), int(args["size_max"]))
            changed = changed or new != inode.get("size")
            inode["size"] = new
        if changed:
            inode["mtime"] = args.get("mtime", self._now())
            await self._store_dentry(parent, name, inode)
        return 0, {"inode": inode}


class MDSError(Exception):
    def __init__(self, rc: int, what: str = ""):
        super().__init__(f"rc={rc} {what}")
        self.rc = rc

"""MDS role: the filesystem metadata server.

Reference parity: the ceph-mds daemon
(/root/reference/src/mds/MDSDaemon.cc, MDCache.cc, Server.cc) — a
single ACTIVE metadata server owns the namespace, serializes every
metadata mutation, and stores directories as objects in a METADATA
pool (one object per directory fragment, dentries in omap —
CDir::commit, src/mds/CDir.cc).  Clients send MClientRequest ops for
metadata and do file DATA I/O directly against the data pool.

Re-designs vs the reference, deliberate:

- The MDS JOURNAL (MDLog/EUpdate role, src/mds/journal.cc): every
  metadata mutation — including compound ones like rename — is first
  appended as ONE fenced journal entry (cls_journal on `mds_journal`),
  then applied write-through to the directory objects.  Takeover
  replays entries past the applied watermark before serving, so a
  crash mid-compound-op always converges to the journaled state:
  a SIGKILL mid-rename yields exactly-src (append never landed) or
  exactly-dst (append landed, replay finishes it) — never both, never
  neither.
- FENCING (the mon-blocklist role): the journal object carries an
  epoch; takeover bumps it (cls `take_over`) and every append/trim
  from the deposed epoch fails EPERM server-side — a partitioned
  ex-active physically cannot mutate metadata, with no cross-host
  clock comparison anywhere.  Staleness detection for lock takeover
  uses RENEWAL COUNTERS aged by the standby's own monotonic clock.
- Active/standby election rides cls_lock: the active MDS holds an
  exclusive lock on the `mds_lock` object (renewed on a heartbeat
  interval, stored with its address); a standby polls, breaks a stale
  lock, and takes over (the mon's MDSMap beacon machinery, collapsed
  onto the object-lock it ultimately implements).
- Inode numbers come from an atomic numops counter object (InoTable
  role, src/mds/InoTable.h).
- MULTI-ACTIVE (the multimds/Migrator/MDBalancer role, re-designed):
  N ranks each own a static namespace partition — root-parented
  entries at rank 0, everything under top-level dir c at hash(c)
  (the export-pin shape, src/mds/MDSMap.h, as a hash rule instead of
  an operator attribute).  Because metadata lives in SHARED rados
  behind guarded per-object ops, ranks are serialization domains, not
  data silos: each rank has its own lock object, journal and standby
  chain; foreign directories are readable by any rank (uncached);
  exactly one rank ever mutates a given directory object.  Cross-rank
  FILE renames are a durable-intent protocol: the src rank journals a
  rename_intent, the DST rank links the dentry under its own lock,
  journal and fencing epoch (peer_link, idempotent), then the src
  rank commits the removal + a rename_finish marker — takeover
  re-drives unfinished intents.  Top-level rmdir asks the owner rank
  to adjudicate emptiness and fence creates (peer_rmdir_begin/done,
  TTL-bounded dying mark); the owner removes the dir object under its
  own epoch.  DIRECTORY renames that re-home a subtree run the
  SUBTREE EXPORT protocol (the Migrator role, src/mds/Migrator.cc):
  the importer rank re-creates the tree under fresh inos in its own
  fencing domain and the exporter purges the old objects — no
  cross-rank epoch comparison anywhere (see _export_subtree).
  Clients route by the same rule from the published mds_map object.

Layout in the metadata pool:
  mds_lock[.r]             cls_lock state + rank r's MDS addr (xattr)
  mds_journal[.r]          fenced journal (cls_journal omap entries)
  mds_map                  JSON: {"num_ranks": N}
  mds_ino                  omap: {"next": counter} (shared, atomic)
  dir.<ino:x>              omap: dentry name -> inode JSON
File data objects (data pool): fsdata.<ino:x>.<blockno:016x>
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Dict, Optional, Tuple

from ceph_tpu.common import lockdep
from ceph_tpu.msg import Connection, Messenger
from ceph_tpu.msg.messages import (
    MClientCaps,
    MClientReply,
    MClientRequest,
    Message,
)
from ceph_tpu.rados.client import (
    IoCtx,
    ObjectNotFound,
    RadosClient,
    RadosError,
)

log = logging.getLogger("mds")

EPERM = -1
ENOENT = -2
EIO = -5
EEXIST = -17
EXDEV = -18
ENOTDIR = -20
EISDIR = -21
EINVAL = -22
ENOTEMPTY = -39
ESTALE = -116

EROFS = -30
EAGAIN = -11
EFBIG = -27
EBUSY = -16

ROOT_INO = 1
LOCK_OBJ = "mds_lock"
INO_OBJ = "mds_ino"
JOURNAL_OBJ = "mds_journal"
MDSMAP_OBJ = "mds_map"
# SnapServer role (src/mds/SnapServer.h): the cluster-wide snapshot
# table.  One omap key per snapshot (never read-modify-written, so
# ranks write concurrently without coordination): key = the data-pool
# snapid zero-padded, value = JSON {name, ino, meta_snap, data_snap,
# ctime}.  COW itself is the RADOS self-managed snap machinery: each
# CephFS snapshot allocates ONE snapid per pool (metadata + data);
# every writer (MDS dir-omap mutations, client file-data writes)
# carries the union of live snapids as its snap context, so the OSDs
# clone heads before the first post-snap mutation.  ".snap" paths
# resolve by reading dir objects AT the metadata snapid and file
# blocks AT the data snapid.
SNAPTABLE_OBJ = "mds_snaptable"
# version counter key inside the snap table's omap (NUL prefix keeps
# it clear of the 16-hex-digit snapshot keys): bumped atomically (cls
# numops) on every table mutation.  Snap contexts published to clients
# carry it, and clients REFUSE to regress — a reply from a rank that
# missed the fan-out can no longer downgrade a fresher context.
SNAPVER_KEY = "\x00ver"
SNAP_DIR = ".snap"
ADDR_ATTR = "mds.addr"
# advance the applied watermark (and trim) after this many entries
APPLIED_BATCH = 16


def rank_lock_obj(rank: int) -> str:
    """Per-rank active/standby lock object (rank 0 keeps the legacy
    name so single-active layouts survive an upgrade)."""
    return LOCK_OBJ if rank == 0 else f"{LOCK_OBJ}.{rank}"


def rank_journal_obj(rank: int) -> str:
    return JOURNAL_OBJ if rank == 0 else f"{JOURNAL_OBJ}.{rank}"


def owner_rank(path: str, num_ranks: int) -> int:
    """Subtree partitioning rule shared by MDS daemons and clients
    (the export-pin role, /root/reference/src/mds/MDSMap.h mds_export
    pinning re-designed as static hashing): an op belongs to the rank
    owning the MUTATED PARENT directory — root-parented ops (top-level
    dentries) at rank 0, deeper ops at hash(first component).  With
    metadata in shared rados behind guarded per-object ops, ranks are
    serialization domains, not data silos: foreign dirs are readable
    by anyone (uncached), and exactly one rank mutates any given
    directory object."""
    if num_ranks <= 1:
        return 0
    parts = [p for p in path.split("/") if p]
    if len(parts) <= 1:
        return 0
    from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

    return ceph_str_hash_rjenkins(parts[0].encode()) % num_ranks


def dir_obj(ino: int) -> str:
    return f"dir.{ino:x}"


def data_obj(ino: int, blockno: int) -> str:
    return f"fsdata.{ino:x}.{blockno:016x}"


class MDSDaemon:
    """Single-active metadata server with standby takeover."""

    def __init__(self, mon_addr: str, metadata_pool: str,
                 data_pool: str, name: str = "a",
                 lock_interval: float = 1.0,
                 secret: "Optional[str]" = None,
                 secure: bool = False,
                 config: "Optional[dict]" = None,
                 rank: int = 0, num_ranks: int = 1):
        self.mon_addr = mon_addr
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool
        self.name = name
        self.lock_interval = lock_interval
        # multi-active: this daemon serves ONE rank (standbys for a
        # rank run with the same rank number); see owner_rank()
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self.lock_obj = rank_lock_obj(self.rank)
        self.journal_obj = rank_journal_obj(self.rank)
        self._peer_tid = 0
        self._peer_futs: Dict[int, asyncio.Future] = {}
        self.ops_served = 0  # client ops this daemon executed
        # cross-rank rename intents journaled but not yet finished
        # (crash recovery drives them to completion on takeover)
        self._pending_intents: Dict[int, Dict[str, Any]] = {}
        # subtree exports in flight (Migrator role): intent seq ->
        # {"intent": op, "imported": op?}; re-driven on takeover
        self._pending_exports: Dict[int, Dict[str, Any]] = {}
        # imports WE completed, by intent id -> new root ino (the
        # importer-side idempotency record, rebuilt from the journal)
        self._imports: Dict[str, int] = {}
        # frozen subtree path prefixes (normalized) -> expiry: client
        # mutations under them bounce EAGAIN while a dump is the
        # authoritative copy (TTL-bounded: a crashed coordinator
        # cannot wedge the subtree)
        self._frozen_subtrees: Dict[str, float] = {}
        # top-level dirs another rank is removing: our creates into
        # them bounce until the mark clears or expires (peer_rmdir)
        self._dying_dirs: Dict[int, float] = {}
        from ceph_tpu.common.auth import parse_secret

        self.client = RadosClient(mon_addr, name=f"mds.{name}",
                                  secret=secret, secure=secure,
                                  config=config)
        self.msgr = Messenger(f"mds.{name}",
                              secret=parse_secret(secret))
        self.msgr.secure = secure
        self.msgr.local_fastpath = True
        self.msgr.dispatcher = self._dispatch
        # ms_compress_* applies to the MDS service messenger too
        self.msgr.apply_compress_config(config or {})
        self.meta: Optional[IoCtx] = None
        self.data_io: Optional[IoCtx] = None
        self.state = "standby"
        # dirty-free write-through cache: dir ino -> {name: inode dict}
        self._dirs: Dict[int, Dict[str, dict]] = {}
        self._lock_task: Optional[asyncio.Task] = None
        self._stopping = False
        # namespace mutations serialize through one lock (the MDS's
        # whole reason to exist); reads go lock-free off the cache
        self._mutation_lock = lockdep.Lock("mds.mutation")
        # journal state (valid while active)
        self._epoch = 0        # fencing epoch from journal take_over
        self._seq = 0          # next journal sequence
        self._applied_mark = 0  # last watermark pushed to the journal
        # renewal-counter staleness (no cross-host clocks): last seen
        # renewal blob + the LOCAL monotonic time it changed
        self._renew_counter = 0
        self._seen_renewal: Optional[Tuple[bytes, float]] = None
        # test failpoints (the reference's failpoint/killpoint role):
        # simulate a crash just before/after the journal append
        self._fail_before_journal = False
        self._fail_after_journal = False
        # -- client caps (the Locker.cc grant/recall role) ----------------
        # per-inode capability table keyed by the client's Connection:
        # a session IS its connection here (death of either evicts the
        # caps, so a reconnecting client starts capless and re-reads).
        # Modes: "r" (may cache attrs + serve reads locally; many
        # holders) and "rw" (may additionally buffer dirty size/mtime;
        # exclusive).  Grants ride metadata replies; recalls are
        # MClientCaps revoke/ack round trips whose acks carry the
        # holder's dirty attrs (the cap-flush discipline).
        self._caps: Dict[int, Dict[Any, str]] = {}
        self._caps_lock = lockdep.Lock("mds.caps")
        self._cap_tid = 0
        self._cap_acks: Dict[int, asyncio.Future] = {}
        self.cap_revoke_timeout = 3.0
        self.msgr.on_connection_fault = self._conn_fault
        # -- snapshots (SnapServer/SnapRealm role) ------------------------
        # data-pool snap context published to clients (rides replies
        # and cap revokes so writers COW against every live snap),
        # versioned by the table's counter (regression guard)
        self._data_snapc: Tuple[int, list] = (0, [])
        self._snapc_ver = 0
        # snap-table read cache: every .snap path op consults the
        # table; re-reading the omap per lookup would make snapshot
        # tree walks O(table) round trips each.  Invalidated by our
        # own mutations + peer fan-out; the TTL self-heals a missed
        # fan-out.
        self._snap_cache: Optional[Tuple[float, int,
                                         Dict[str, dict]]] = None
        self._snap_cache_ttl = 2.0
        # snapid -> metadata-pool IoCtx with read_snap set (immutable
        # once created; reads of dir omap at that snap)
        self._snap_ios: Dict[int, IoCtx] = {}
        # (dir ino, meta snapid) -> entries; immutable so cacheable,
        # bounded by wholesale eviction
        self._snap_dirs: Dict[Tuple[int, int], Dict[str, dict]] = {}

    # -- lifecycle ---------------------------------------------------------

    async def start(self, port: int = 0) -> str:
        await self.client.connect()
        self.meta = self.client.open_ioctx(self.metadata_pool)
        self.data_io = self.client.open_ioctx(self.data_pool)
        addr = await self.msgr.bind(port=port)
        self._lock_task = asyncio.get_running_loop().create_task(
            self._lock_loop())
        return addr

    async def stop(self) -> None:
        self._stopping = True
        if self._lock_task is not None:
            self._lock_task.cancel()
            try:
                await self._lock_task
            except asyncio.CancelledError:
                pass
        if self.state == "active":
            try:
                await self.meta.execute(self.lock_obj, "lock", "unlock",
                                        json.dumps({
                                            "name": "active",
                                            "owner": self.name,
                                        }).encode())
            except Exception:
                pass
        await self.msgr.shutdown()
        await self.client.shutdown()

    # -- active/standby via cls_lock (MDSMap beacon role) ------------------

    async def _lock_loop(self) -> None:
        while not self._stopping:
            try:
                await self._try_acquire_or_renew()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("mds.%s: lock loop error", self.name)
            await asyncio.sleep(self.lock_interval)

    async def _try_acquire_or_renew(self) -> None:
        req = json.dumps({"name": "active", "type": "exclusive",
                          "owner": self.name,
                          "tag": "mds"}).encode()
        try:
            await self.meta.execute(self.lock_obj, "lock", "lock", req)
        except RadosError:
            # someone else is active: stale-ness check via RENEWAL
            # COUNTERS aged by OUR monotonic clock — never comparing
            # wall clocks across hosts (a skewed clock must not
            # trigger a false takeover)
            if self.state == "active":
                # lost our own lock (broken by a standby while we were
                # partitioned): step down, drop caches.  The journal
                # epoch fence already made our writes impotent.
                log.warning("mds.%s: lost the active lock, standby",
                            self.name)
                self.state = "standby"
                self._dirs.clear()
                self._drop_all_caps()
            try:
                raw = await self.meta.getxattr(self.lock_obj, "renewal")
                now = time.monotonic()
                if self._seen_renewal is None or \
                        self._seen_renewal[0] != raw:
                    self._seen_renewal = (raw, now)
                    return  # counter moved: holder is live
                if now - self._seen_renewal[1] < \
                        self.lock_interval * 5:
                    return  # unchanged, but not for long enough
                holder = json.loads(raw)[0]
                await self.meta.execute(
                    self.lock_obj, "lock", "break_lock",
                    json.dumps({"name": "active",
                                "locker": holder}).encode())
                log.warning("mds.%s: broke stale lock of mds.%s",
                            self.name, holder)
            except (RadosError, ObjectNotFound, ValueError):
                pass
            return
        # lock held (fresh or renewal): stamp a counter + the address
        self._renew_counter += 1
        await self.meta.setxattr(
            self.lock_obj, "renewal",
            json.dumps([self.name, self._renew_counter]).encode())
        await self.meta.setxattr(self.lock_obj, ADDR_ATTR,
                                 self.msgr.addr.encode())
        if self.state != "active":
            await self._take_over()
            # publish the rank layout so clients route without
            # out-of-band config (the MDSMap role, one JSON object)
            await self.meta.write_full(
                MDSMAP_OBJ,
                json.dumps({"num_ranks": self.num_ranks}).encode())

    async def _take_over(self) -> None:
        """Fence the previous active, replay its journal tail, serve.
        (MDLog replay + the mon-blocklist fencing role.)"""
        out = await self.meta.execute(self.journal_obj, "journal",
                                      "take_over", b"")
        self._epoch = int(out.decode())
        self._dirs.clear()  # cold cache: reload from rados
        await self._ensure_root()
        # snap contexts BEFORE any replayed mutation: replayed dir
        # writes and purges must COW against every live snapshot
        await self._refresh_snapc()
        await self._sweep_pending_snaps()
        await self._replay_journal()
        log.info("mds.%s: ACTIVE at %s (epoch %d)", self.name,
                 self.msgr.addr, self._epoch)
        self.state = "active"
        if self._pending_intents:
            # crashed mid cross-rank rename: drive each intent to its
            # journaled conclusion (state must be active first — the
            # peer RPCs below go through live messengers)
            await self._finish_pending_renames()
        if self._pending_exports:
            await self._finish_pending_exports()

    async def _replay_journal(self) -> None:
        from ceph_tpu.cls.journal import ENTRY_PREFIX

        raw = await self.meta.execute(self.journal_obj, "journal",
                                      "get_state", b"")
        st = json.loads(raw.decode())
        applied = int(st["applied"])
        try:
            omap = await self.meta.omap_get(self.journal_obj)
        except ObjectNotFound:
            omap = {}
        entries = sorted(
            (int(k[len(ENTRY_PREFIX):]), v)
            for k, v in omap.items() if k.startswith(ENTRY_PREFIX))
        top = applied
        pending: Dict[int, Dict[str, Any]] = {}
        exports: Dict[int, Dict[str, Any]] = {}
        imports: Dict[str, int] = {}
        for seq, blob in entries:
            ops = json.loads(blob.decode())
            # intent/finish pairing spans the applied watermark: an
            # intent may be applied (and trimmed from replay's range)
            # while its finish never landed — scan ALL retained
            # entries for pairing, apply only the un-applied ones
            for op in ops:
                kind = op.get("op")
                if kind == "rename_intent":
                    pending[seq] = op
                elif kind == "rename_finish":
                    pending.pop(int(op.get("intent_seq", -1)), None)
                elif kind == "export_intent":
                    exports[seq] = {"intent": op}
                elif kind == "export_imported":
                    rec = exports.get(int(op.get("intent_seq", -1)))
                    if rec is not None:
                        rec["imported"] = op
                elif kind == "export_finish":
                    exports.pop(int(op.get("intent_seq", -1)), None)
                elif kind == "import_done":
                    imports[op["id"]] = int(op["root"])
                elif kind == "import_forget":
                    imports.pop(op.get("id", ""), None)
            if seq <= applied:
                continue
            await self._apply_ops(ops)
            top = seq
        self._pending_intents = pending
        self._pending_exports = exports
        self._imports = imports
        self._seq = max(top, applied) + 1
        self._applied_mark = top
        await self.meta.execute(
            self.journal_obj, "journal", "set_applied",
            json.dumps({"epoch": self._epoch, "applied": top,
                        "from": applied}).encode())
        if top > applied:
            log.info("mds.%s: replayed %d journal entries",
                     self.name, top - applied)

    async def _ensure_root(self) -> None:
        try:
            await self.meta.omap_get(dir_obj(ROOT_INO))
        except ObjectNotFound:
            await self.meta.omap_set(dir_obj(ROOT_INO), {})
            await self.meta.omap_set(INO_OBJ,
                                     {"next": str(ROOT_INO + 1).encode()})

    async def _alloc_ino(self) -> int:
        out = await self.meta.execute(
            INO_OBJ, "numops", "add",
            json.dumps({"key": "next", "value": 1}).encode())
        return int(float(out.decode()))

    # -- directory cache (write-through; CDir::fetch/commit roles) ---------

    async def _load_dir(self, ino: int,
                        owned: bool = True) -> Dict[str, dict]:
        """owned=False: a FOREIGN directory (another rank mutates it)
        — always read through to rados, never cache: the write-through
        cache is only coherent for dirs this rank exclusively
        mutates."""
        if owned:
            cached = self._dirs.get(ino)
            if cached is not None:
                return cached
        try:
            omap = await self.meta.omap_get(dir_obj(ino))
        except ObjectNotFound:
            raise MDSError(ENOENT, f"no directory {ino:x}")
        entries = {name: json.loads(raw.decode())
                   for name, raw in omap.items()}
        if owned:
            self._dirs[ino] = entries
        return entries

    async def _guarded(self, method: str, oid: str, req: dict) -> None:
        """Epoch-guarded apply write (cls journal guarded_*): the
        fence xattr on each object refuses any epoch OLDER than one
        that already touched it — the apply-phase half of fencing (a
        deposed active can at most re-apply state the new active
        already replayed, which is idempotent)."""
        req = dict(req, epoch=self._epoch)
        await self.meta.execute(oid, "journal", method,
                                json.dumps(req).encode())

    async def _apply_ops(self, ops) -> None:
        """Apply one journal entry's ops write-through (idempotent:
        absolute sets/removes, so replay after a partial apply
        converges).  Every write is epoch-guarded."""
        for op in ops:
            kind = op["op"]
            if kind == "dentry":
                dir_ino, name, inode = op["dir"], op["name"], op["inode"]
                val = None if inode is None else json.dumps(inode)
                await self._guarded("guarded_update",
                                    dir_obj(dir_ino),
                                    {"set": {name: val}})
                # update ONLY an already-loaded cache entry: seeding a
                # partial entry here would later be served as the
                # complete directory (lazy _load_dir fills cold dirs)
                if inode is None:
                    self._dirs.get(dir_ino, {}).pop(name, None)
                elif dir_ino in self._dirs:
                    self._dirs[dir_ino][name] = inode
            elif kind == "mkdirobj":
                await self._guarded("guarded_update",
                                    dir_obj(op["ino"]), {"set": {}})
            elif kind == "rmdirobj":
                try:
                    await self._guarded("guarded_remove",
                                        dir_obj(op["ino"]), {})
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
                self._dirs.pop(op["ino"], None)
            elif kind in ("rename_intent", "rename_finish",
                          "export_intent", "export_imported",
                          "export_finish", "import_done",
                          "import_forget"):
                # bookkeeping entries for the cross-rank rename and
                # subtree-export protocols: no object mutation —
                # replay pairs them up (_replay_journal) and the
                # takeover finishers drive unfinished ones
                pass
            elif kind == "purgefile":
                # a rename clobbered a file: its data objects have no
                # dentry left to purge them through — best-effort
                # server-side purge (the PurgeQueue role)
                size = int(op.get("size", 0))
                bs = max(1, int(op.get("block_size", 1 << 22)))
                for blk in range((size + bs - 1) // bs):
                    try:
                        await self.data_io.remove(
                            data_obj(op["ino"], blk))
                    except (ObjectNotFound, RadosError):
                        pass

    class _CrashPoint(Exception):
        """Test failpoint fired: simulate the daemon dying here."""

    async def _commit(self, ops) -> int:
        """One compound metadata update (the EUpdate role): fenced
        journal append FIRST, then write-through apply.  The append is
        the commit point — a crash after it is finished by the next
        active's replay; a fenced append (EPERM: a newer epoch took
        over) steps this MDS down without touching anything.
        Returns the entry's journal seq (rename intents reference
        it)."""
        if self._fail_before_journal:
            await self._simulate_crash()
            raise self._CrashPoint()
        seq = self._seq
        self._seq += 1
        try:
            await self.meta.execute(
                self.journal_obj, "journal", "append",
                json.dumps({"epoch": self._epoch, "seq": seq,
                            "entry": ops}).encode())
        except RadosError as e:
            if e.rc == EPERM:
                log.warning("mds.%s: journal append fenced — a newer"
                            " active exists; stepping down",
                            self.name)
                self.state = "standby"
                self._dirs.clear()
                self._drop_all_caps()
                raise MDSError(ESTALE, "fenced by a newer active")
            # transient rados failure: the mutation did NOT commit;
            # stay active (stepping down on EAGAIN would turn OSD
            # churn into MDS failover storms)
            raise MDSError(EIO, f"journal append failed ({e.rc})")
        if self._fail_after_journal:
            await self._simulate_crash()
            raise self._CrashPoint()
        try:
            await self._apply_ops(ops)
        except RadosError as e:
            if e.rc == EPERM:
                # a newer epoch fenced the APPLY phase mid-op: the
                # entry IS journaled — the new active's replay commits
                # it.  Step down and tell the client to re-resolve
                # (retrying against us would double-report failure for
                # an op that took effect).
                log.warning("mds.%s: apply fenced mid-op — stepping"
                            " down (the entry is journaled; the new"
                            " active replays it)", self.name)
                self.state = "standby"
                self._dirs.clear()
                self._drop_all_caps()
                raise MDSError(ESTALE, "fenced during apply")
            raise
        if seq - self._applied_mark >= APPLIED_BATCH:
            prev = self._applied_mark
            self._applied_mark = seq
            try:
                await self.meta.execute(
                    self.journal_obj, "journal", "set_applied",
                    json.dumps({"epoch": self._epoch, "applied": seq,
                                "from": prev}).encode())
            except RadosError:
                pass  # fenced trim: the new active owns the journal
        return seq

    async def _simulate_crash(self) -> None:
        """Failpoint: die like a SIGKILL — stop serving instantly,
        leave all rados state exactly as it is."""
        self._stopping = True
        self.state = "killed"
        if self._lock_task is not None:
            self._lock_task.cancel()
        await self.msgr.shutdown()

    @staticmethod
    def _dentry(dir_ino: int, name: str, inode) -> dict:
        return {"op": "dentry", "dir": dir_ino, "name": name,
                "inode": inode}

    # -- client caps (Locker grant/recall) ---------------------------------

    def _conn_fault(self, conn) -> None:
        """A client connection died: its session's caps die with it
        (the session-eviction role) — no ack will ever come."""
        for ino in list(self._caps):
            self._caps[ino].pop(conn, None)
            if not self._caps[ino]:
                del self._caps[ino]
        # unblock any revoke waiting on this conn
        for tid, fut in list(self._cap_acks.items()):
            if getattr(fut, "_cap_conn", None) is conn and \
                    not fut.done():
                fut.set_result({})

    async def _revoke_caps(self, ino: int,
                           keep: Any = None) -> Dict[str, Any]:
        """Recall every cap on ino except `keep`'s; returns the merged
        dirty attrs flushed back in the acks ({} if none).  An
        unresponsive holder is evicted after cap_revoke_timeout — a
        dead client must not wedge the namespace (Locker's
        session-autoclose discipline)."""
        return (await self._revoke_many([ino], keep=keep)).get(ino, {})

    async def _revoke_many(self, inos, keep: Any = None
                           ) -> Dict[int, Dict[str, Any]]:
        """Recall caps on every listed inode at once: ALL revokes go
        out first, then ALL acks are awaited under ONE shared timeout
        — a directory rename recalling thousands of inodes (or N
        unresponsive holders) costs one cap_revoke_timeout total, not
        one per inode, while this stall holds _caps_lock and usually
        the mutation lock."""
        merged: Dict[int, Dict[str, Any]] = {}
        async with self._caps_lock:
            waits = []
            for ino in inos:
                holders = self._caps.get(ino)
                if not holders:
                    continue
                for conn, _mode in list(holders.items()):
                    if conn is keep:
                        continue
                    self._cap_tid += 1
                    tid = self._cap_tid
                    fut: asyncio.Future = \
                        asyncio.get_running_loop().create_future()
                    fut._cap_conn = conn
                    self._cap_acks[tid] = fut
                    # the recall itself carries the (possibly empty)
                    # snap context: a recalled writer must COW its
                    # very next write — or stop cloning after the
                    # last rmsnap — before any MDS round trip
                    revoke_attrs = {"snapc": [
                        self._snapc_ver,
                        self._data_snapc[0],
                        list(self._data_snapc[1])]}
                    try:
                        await conn.send(MClientCaps("revoke", ino,
                                                    tid=tid,
                                                    attrs=revoke_attrs))
                    except (ConnectionError, OSError):
                        self._cap_acks.pop(tid, None)
                        holders.pop(conn, None)
                        continue
                    waits.append((ino, conn, tid, fut))
            if waits:
                await asyncio.wait([f for _i, _c, _t, f in waits],
                                   timeout=self.cap_revoke_timeout)
            for ino, conn, tid, fut in waits:
                holders = self._caps.get(ino, {})
                if fut.done():
                    attrs = fut.result()
                    if attrs.get("size_max") is not None:
                        m = merged.setdefault(ino, {})
                        m["size_max"] = max(
                            int(m.get("size_max", 0)),
                            int(attrs["size_max"]))
                        if attrs.get("mtime") is not None:
                            m["mtime"] = max(
                                float(m.get("mtime", 0)),
                                float(attrs["mtime"]))
                        if attrs.get("path"):
                            m["path"] = attrs["path"]
                else:
                    log.warning("mds.%s: cap revoke on %x timed out;"
                                " evicting session", self.name, ino)
                    try:
                        conn.close()
                    except Exception:
                        pass
                self._cap_acks.pop(tid, None)
                holders.pop(conn, None)
                if not holders:
                    self._caps.pop(ino, None)
        return merged

    async def _revoke_all_caps(self) -> list:
        """Recall EVERY outstanding cap (directory rename: all cached
        descendant paths go stale cluster-wide) in ONE batched round.
        Returns the flushed dirty attrs, each carrying the holder's
        path, for the caller to persist BEFORE the rename moves those
        paths."""
        merged = await self._revoke_many(list(self._caps))
        return [fl for fl in merged.values()
                if fl.get("size_max") is not None]

    async def _acquire_cap(self, conn, ino: int,
                           want: str) -> Tuple[str, Dict[str, Any]]:
        """Try to grant `want` to conn; returns (granted_mode,
        flushed_attrs_from_conflicting_holders)."""
        if conn is None or want not in ("r", "rw"):
            return "", {}
        flush: Dict[str, Any] = {}
        async with self._caps_lock:
            holders = self._caps.get(ino, {})
            conflict = any(
                c is not conn and (want == "rw" or m == "rw")
                for c, m in holders.items())
        if conflict:
            flush = await self._revoke_caps(ino, keep=conn)
        async with self._caps_lock:
            holders = self._caps.setdefault(ino, {})
            # re-check under the lock: a rival grant may have landed
            # between the revoke and here — then no cap this time
            # (correctness first; the client just doesn't cache)
            if any(c is not conn and (want == "rw" or m == "rw")
                   for c, m in holders.items()):
                if not holders:
                    self._caps.pop(ino, None)
                return "", flush
            holders[conn] = want
        return want, flush

    async def _apply_flush(self, flush: Dict[str, Any],
                           path: str) -> None:
        """Persist dirty attrs collected by a recall (the cap-flush
        commit): max-merge the size under the mutation lock."""
        if flush.get("size_max") is None or not path:
            return
        async with self._mutation_lock:
            await self._apply_flush_locked(flush, path)

    async def _apply_flush_locked(self, flush: Dict[str, Any],
                                  path: str) -> None:
        """As _apply_flush, for callers already holding the mutation
        lock (mutation handlers persisting bystander flushes)."""
        if flush.get("size_max") is None or not path:
            return
        try:
            parent, name, inode = await self._resolve(path)
        except MDSError:
            return  # path raced away; flush has nowhere to land
        if inode is None or inode.get("type") != "file":
            return
        new = max(inode.get("size", 0), int(flush["size_max"]))
        if new != inode.get("size"):
            inode["size"] = new
            inode["mtime"] = float(flush.get("mtime", self._now()))
            await self._commit([self._dentry(parent, name, inode)])

    def _drop_all_caps(self) -> None:
        """Step-down/shutdown: tell every holder to forget its caps
        (no ack expected — we may be fenced already), then clear."""
        sent = set()
        for ino, holders in self._caps.items():
            for conn in holders:
                if id(conn) in sent:
                    continue
                sent.add(id(conn))
                try:
                    self.msgr._spawn(conn.send(
                        MClientCaps("evict", 0)))
                except Exception:
                    pass
        self._caps.clear()

    # -- path resolution (MDCache::path_traverse role) ---------------------

    async def _resolve(self, path: str) -> Tuple[int, str,
                                                 Optional[dict]]:
        """path -> (parent dir ino, leaf name, inode | None).
        '/' resolves to (0, '', root-pseudo-inode)."""
        parts = [p for p in path.split("/") if p]
        if SNAP_DIR in parts:
            # every MUTATION resolves through here: snapshots are
            # read-only (snap-aware reads branch before _resolve)
            raise MDSError(EROFS, path)
        if not parts:
            return 0, "", {"ino": ROOT_INO, "type": "dir", "mode": 0o755,
                           "size": 0, "mtime": 0}
        # ownership per dir along the walk: the root object belongs to
        # rank 0; every dir under top-level component c belongs to
        # hash(c) — only OWNED dirs may be served from (and fill) the
        # write-through cache
        subtree_owned = self.num_ranks <= 1 or \
            self._subtree_rank(parts[0]) == self.rank
        cur = ROOT_INO
        for i, part in enumerate(parts[:-1]):
            owned = (self.rank == 0) if cur == ROOT_INO \
                else subtree_owned
            entries = await self._load_dir(cur, owned=owned)
            inode = entries.get(part)
            if inode is None:
                raise MDSError(ENOENT, "/".join(parts[:i + 1]))
            if inode["type"] != "dir":
                raise MDSError(ENOTDIR, part)
            cur = inode["ino"]
        owned = (self.rank == 0) if cur == ROOT_INO else subtree_owned
        entries = await self._load_dir(cur, owned=owned)
        return cur, parts[-1], entries.get(parts[-1])

    # -- multi-active plumbing (Migrator/peer coordination role) -----------

    def _subtree_rank(self, first_component: str) -> int:
        """The ONE rank serving every path under top-level component
        c — the single source of the partition rule (owner_rank and
        _dir_owned derive from it)."""
        if self.num_ranks <= 1:
            return 0
        from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

        return ceph_str_hash_rjenkins(
            first_component.encode()) % self.num_ranks

    def _dir_owned(self, path: str) -> bool:
        """Is the directory OBJECT addressed by path mutated by this
        rank?  (Root belongs to rank 0; dirs under top-level component
        c to hash(c).)"""
        parts = [p for p in path.split("/") if p]
        if not parts:
            return self.num_ranks <= 1 or self.rank == 0
        return self._subtree_rank(parts[0]) == self.rank

    async def _peer_request(self, rank: int, op: str, args: dict,
                            timeout: Optional[float] = None):
        """MDS-to-MDS RPC over the service messenger (the reference's
        MMDSPeerRequest role): address discovered from the peer rank's
        lock object.  NEVER call while holding the mutation lock — the
        peer's handler may take ITS mutation lock, and two ranks
        calling each other would deadlock.  Default timeout exceeds
        the peer's cap_revoke_timeout: a revoke waiting out a dead
        holder must not time out at the caller first."""
        if timeout is None:
            timeout = self.cap_revoke_timeout + 2.0
        raw = await self.meta.getxattr(rank_lock_obj(rank), ADDR_ATTR)
        addr = raw.decode()
        self._peer_tid += 1
        tid = self._peer_tid
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._peer_futs[tid] = fut
        try:
            conn = await self.msgr.connect(addr)
            await conn.send(MClientRequest(tid, op, args))
            reply = await asyncio.wait_for(fut, timeout)
            return reply.rc, reply.out
        finally:
            self._peer_futs.pop(tid, None)

    async def _op_peer_revoke(self, args,
                              conn=None) -> Tuple[int, Dict[str, Any]]:
        """Peer rank asks us to revoke caps / drop dir-cache entries
        it is about to invalidate (cross-rank rename coordination).
        MUST run without the mutation lock: two ranks cross-renaming
        into each other would deadlock otherwise."""
        if args.get("revoke_all"):
            await self._revoke_many(list(self._caps))
            self._dirs.clear()
        else:
            await self._revoke_many(list(args.get("inos", [])))
            for ino in args.get("invalidate_dirs", []):
                self._dirs.pop(int(ino), None)
        return 0, {}

    # -- request dispatch (Server::handle_client_request role) -------------

    async def _dispatch(self, conn: Connection, msg: Message) -> None:
        if isinstance(msg, MClientReply):
            # a peer rank answering our _peer_request
            fut = self._peer_futs.get(msg.tid)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return
        if isinstance(msg, MClientCaps):
            if msg.op == "ack":
                fut = self._cap_acks.get(msg.tid)
                if fut is not None and not fut.done():
                    fut.set_result(msg.attrs)
            elif msg.op == "release":
                # voluntary cap return (dirty attrs were flushed via a
                # regular setattr first): just drop the table entry
                holders = self._caps.get(msg.ino)
                if holders is not None:
                    holders.pop(conn, None)
                    if not holders:
                        self._caps.pop(msg.ino, None)
            return
        if not isinstance(msg, MClientRequest):
            return
        if self.state != "active":
            await conn.send(MClientReply(msg.tid, ESTALE,
                                         {"error": "not active"}))
            return
        handler = getattr(self, f"_op_{msg.op}", None)
        if handler is None:
            await conn.send(MClientReply(msg.tid, EINVAL,
                                         {"error": f"bad op {msg.op}"}))
            return
        if self.num_ranks > 1 and not msg.op.startswith("peer_"):
            # subtree routing guard: a misrouted op must bounce, not
            # execute — executing here would mutate a dir object a
            # DIFFERENT rank caches and serializes
            path = msg.args.get("path") or msg.args.get("src") or "/"
            want = owner_rank(path, self.num_ranks)
            if want != self.rank:
                await conn.send(MClientReply(
                    msg.tid, ESTALE,
                    {"error": "misrouted", "rank": want}))
                return
        if self._frozen_subtrees and msg.op in self.MUTATING_OPS:
            # a frozen subtree is mid-export: its dump is the
            # authoritative copy, so mutations under it must wait
            # (EAGAIN; clients retry) — reads stay fine
            now = time.monotonic()
            paths = [self._norm_path(msg.args.get(k, ""))
                     for k in ("path", "src", "dst")
                     if msg.args.get(k)]
            for pref, exp in list(self._frozen_subtrees.items()):
                if exp <= now:
                    self._frozen_subtrees.pop(pref, None)
                    continue
                if any(p == pref or p.startswith(pref + "/")
                       for p in paths):
                    await conn.send(MClientReply(
                        msg.tid, EAGAIN,
                        {"error": "subtree migrating; retry"}))
                    return
        self.ops_served += 1
        try:
            if msg.op in ("lookup", "readdir", "stat", "readlink",
                          "peer_revoke", "rename", "rmdir", "lssnap",
                          "peer_snap_refresh", "peer_subtree_thaw"):
                # reads are lock-free; rename/rmdir manage their own
                # locking (they must release it around peer RPCs);
                # peer_revoke must never wait on the mutation lock
                # (its caller holds its own — distributed deadlock)
                rc, out = await handler(msg.args, conn)
            else:
                async with self._mutation_lock:
                    rc, out = await handler(msg.args, conn)
        except MDSError as e:
            rc, out = e.rc, {"error": str(e)}
        except ObjectNotFound:
            rc, out = ENOENT, {}
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("mds.%s: op %s failed", self.name, msg.op)
            rc, out = EIO, {}
        if rc == 0 and isinstance(out, dict):
            # piggyback the data-pool snap context on every reply so
            # clients' direct-to-OSD file writes COW against every
            # live snapshot (the SnapRealm-propagation role).  An
            # EMPTY context is published too: after the last rmsnap
            # clients must STOP cloning against the removed snapid,
            # or post-trim clones leak unreclaimably
            out.setdefault("_dsnapc", [self._snapc_ver,
                                       self._data_snapc[0],
                                       list(self._data_snapc[1])])
        try:
            await conn.send(MClientReply(msg.tid, rc, out))
        except (ConnectionError, OSError):
            pass

    # -- metadata ops ------------------------------------------------------

    @staticmethod
    def _now() -> float:
        return time.time()

    async def _op_mkdir(self, args,
                        conn=None) -> Tuple[int, Dict[str, Any]]:
        parent, name, existing = await self._resolve(args["path"])
        if not name:
            return EEXIST, {}
        if self._dying(parent):
            return ESTALE, {"error": "parent dir is being removed"}
        if existing is not None:
            return EEXIST, {}
        ino = await self._alloc_ino()
        inode = {"ino": ino, "type": "dir",
                 "mode": args.get("mode", 0o755),
                 "size": 0, "mtime": self._now()}
        await self._commit([{"op": "mkdirobj", "ino": ino},
                            self._dentry(parent, name, inode)])
        return 0, {"inode": inode}

    async def _op_create(self, args,
                         conn=None) -> Tuple[int, Dict[str, Any]]:
        parent, name, existing = await self._resolve(args["path"])
        if not name:
            return EISDIR, {}
        if self._dying(parent):
            return ESTALE, {"error": "parent dir is being removed"}
        if existing is not None:
            if existing["type"] == "dir":
                return EISDIR, {}
            if args.get("exclusive"):
                return EEXIST, {}
            # open of an existing file: recall conflicting holders
            # (an opener wanting rw must flush/stop every caching
            # reader; a reader-opener must flush a foreign writer)
            cap, flush = await self._acquire_cap(
                conn, existing["ino"], args.get("want", ""))
            if flush.get("size_max") is not None:
                existing["size"] = max(existing.get("size", 0),
                                       int(flush["size_max"]))
                existing["mtime"] = float(
                    flush.get("mtime", self._now()))
                await self._commit([self._dentry(parent, name,
                                                 existing)])
            return 0, {"inode": existing, "cap": cap}
        ino = await self._alloc_ino()
        inode = {"ino": ino, "type": "file",
                 "mode": args.get("mode", 0o644),
                 "size": 0, "mtime": self._now(),
                 "block_size": int(args.get("block_size", 1 << 22))}
        await self._commit([self._dentry(parent, name, inode)])
        cap, _ = await self._acquire_cap(conn, ino,
                                         args.get("want", ""))
        return 0, {"inode": inode, "cap": cap}

    async def _op_symlink(self, args,
                          conn=None) -> Tuple[int, Dict[str, Any]]:
        parent, name, existing = await self._resolve(args["path"])
        if not name or existing is not None:
            return EEXIST, {}
        if self._dying(parent):
            return ESTALE, {"error": "parent dir is being removed"}
        ino = await self._alloc_ino()
        inode = {"ino": ino, "type": "symlink",
                 "mode": 0o777, "size": len(args["target"]),
                 "mtime": self._now(), "target": args["target"]}
        await self._commit([self._dentry(parent, name, inode)])
        return 0, {"inode": inode}

    async def _op_lookup(self, args,
                         conn=None) -> Tuple[int, Dict[str, Any]]:
        if self._split_snap_path(args["path"]) is not None:
            return await self._snap_lookup(args)
        _parent, _name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        want = args.get("want", "")
        if not want:
            return 0, {"inode": inode}
        # grant a cap so the client may cache this answer; recalling a
        # foreign writer first means the size we serve (and the flush
        # we persist) is current — the rdlock-revokes-Fw discipline
        cap, flush = await self._acquire_cap(conn, inode["ino"], want)
        if flush.get("size_max") is not None:
            await self._apply_flush(flush, args["path"])
            _p, _n, inode = await self._resolve(args["path"])
            if inode is None:
                return ENOENT, {}
        return 0, {"inode": inode, "cap": cap}

    _op_stat = _op_lookup

    async def _op_readlink(self, args,
                           conn=None) -> Tuple[int, Dict[str, Any]]:
        if self._split_snap_path(args["path"]) is not None:
            inode = await self._snap_resolve(args["path"])
        else:
            _p, _n, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] != "symlink":
            return EINVAL, {}
        return 0, {"target": inode["target"]}

    async def _op_readdir(self, args,
                          conn=None) -> Tuple[int, Dict[str, Any]]:
        if self._split_snap_path(args["path"]) is not None:
            return await self._snap_readdir(args)
        _parent, _name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] != "dir":
            return ENOTDIR, {}
        entries = await self._load_dir(
            inode["ino"], owned=self._dir_owned(args["path"]))
        return 0, {"entries": {n: i for n, i in sorted(entries.items())}}

    async def _op_unlink(self, args,
                         conn=None) -> Tuple[int, Dict[str, Any]]:
        parent, name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] == "dir":
            return EISDIR, {}
        # recall ALL caps (requester's too — the inode is dying); a
        # writer's flushed size feeds the purge block count
        flush = await self._revoke_caps(inode["ino"])
        if flush.get("size_max") is not None:
            inode["size"] = max(inode.get("size", 0),
                                int(flush["size_max"]))
        await self._commit([self._dentry(parent, name, None)])
        return 0, {"inode": inode}  # client purges the data objects

    async def _op_rmdir(self, args,
                        conn=None) -> Tuple[int, Dict[str, Any]]:
        """Manages its OWN locking (like rename): removing a TOP-LEVEL
        dir another rank owns runs the peer_rmdir protocol — the owner
        adjudicates emptiness under ITS mutation lock and fences new
        creates with a dying mark, so an empty-check here cannot race
        a create committing there; the owner also removes the dir
        object under its own fencing epoch (cross-rank epochs are
        incomparable)."""
        parts = [p for p in args["path"].split("/") if p]
        foreign = None
        if self.num_ranks > 1 and len(parts) == 1:
            r = self._subtree_rank(parts[0])
            if r != self.rank:
                foreign = r
        if foreign is None:
            async with self._mutation_lock:
                parent, name, inode = await self._resolve(
                    args["path"])
                if inode is None:
                    return ENOENT, {}
                if inode["type"] != "dir":
                    return ENOTDIR, {}
                entries = await self._load_dir(
                    inode["ino"],
                    owned=self._dir_owned(args["path"]))
                if entries:
                    return ENOTEMPTY, {}
                await self._revoke_caps(inode["ino"])
                await self._commit([
                    self._dentry(parent, name, None),
                    {"op": "rmdirobj", "ino": inode["ino"]}])
                return 0, {}
        async with self._mutation_lock:
            parent, name, inode = await self._resolve(args["path"])
            if inode is None:
                return ENOENT, {}
            if inode["type"] != "dir":
                return ENOTDIR, {}
        try:
            rc, out = await self._peer_request(
                foreign, "peer_rmdir_begin", {"ino": inode["ino"]})
        except (RadosError, ObjectNotFound, ConnectionError, OSError,
                asyncio.TimeoutError):
            return ESTALE, {"error": "owner rank unavailable"}
        if rc != 0:
            return rc, out
        removed = False
        async with self._mutation_lock:
            _p2, _n2, cur = await self._resolve(args["path"])
            if cur is not None and cur["ino"] == inode["ino"]:
                await self._revoke_caps(inode["ino"])
                # dentry removal only — the OWNER removes the dir
                # object in peer_rmdir_done under its epoch.  Crash
                # before done: the dying mark expires and the object
                # leaks invisibly (logged there), never corrupts.
                await self._commit([self._dentry(parent, name, None)])
                removed = True
        try:
            await self._peer_request(
                foreign, "peer_rmdir_done",
                {"ino": inode["ino"], "removed": removed})
        except (RadosError, ObjectNotFound, ConnectionError, OSError,
                asyncio.TimeoutError):
            log.warning("mds.%s: peer_rmdir_done to rank %d lost;"
                        " dir object %x may leak", self.name,
                        foreign, inode["ino"])
        return (0, {}) if removed else (ESTALE,
                                        {"error": "dentry raced away"})

    async def _op_rename(self, args,
                         conn=None) -> Tuple[int, Dict[str, Any]]:
        """Rename (manages its OWN locking — _dispatch leaves it
        lock-free so the cross-rank path can release the mutation lock
        around peer RPCs; two ranks cross-renaming into each other
        while holding their locks would deadlock).

        Cross-rank protocol (the Migrator handshake re-designed for
        shared rados): the SRC rank journals a durable rename_intent,
        then asks the DST rank to link the dentry UNDER ITS OWN
        mutation lock, journal and fencing epoch (peer_link) — only
        the object owner ever mutates a directory object, so dst-side
        concurrency and cache coherence are its own single-rank
        problem.  The src rank then commits the src-dentry removal
        plus a rename_finish marker.  A crash leaves the intent in the
        src journal; takeover re-drives it (peer_link is idempotent).

        DIRECTORY renames that would RE-HOME a subtree (src and dst
        top-level hashes differ) run the SUBTREE EXPORT protocol
        (_export_subtree — the Migrator role): the importer rank
        re-creates the tree under fresh inos in its own fencing
        domain, so no cross-rank epoch comparison ever happens."""
        src_parts = [p for p in args["src"].split("/") if p]
        dst_parts = [p for p in args["dst"].split("/") if p]
        if not src_parts or not dst_parts:
            return EINVAL, {}
        if self.num_ranks > 1 and \
                self._subtree_rank(src_parts[0]) != \
                self._subtree_rank(dst_parts[0]):
            # re-homing applies only to DIRECTORY renames: peek at the
            # src type (lock-free read; _export_subtree re-validates
            # under the lock and bounces ESTALE on a race)
            try:
                _p, _n, peek = await self._resolve(args["src"])
            except MDSError as e:
                return e.rc, {}
            if peek is not None and peek.get("type") == "dir":
                return await self._export_subtree(args, src_parts,
                                                  dst_parts)
        dst_rank = owner_rank(args["dst"], self.num_ranks)
        if self.num_ranks > 1 and dst_rank != self.rank:
            return await self._rename_cross_rank(args, dst_rank,
                                                 src_parts, dst_parts)
        async with self._mutation_lock:
            return await self._rename_local(args, src_parts,
                                            dst_parts)

    def _dir_move_ranks(self, src_parts, dst_parts,
                        is_dir: bool) -> Tuple[int, Optional[int]]:
        """For a DIRECTORY rename: (subtree rank serving the moved
        paths, or EXDEV-sentinel None if the move would re-home)."""
        s = self._subtree_rank(src_parts[0])
        d = self._subtree_rank(dst_parts[0])
        return s, (s if s == d else None)

    async def _rename_local(self, args, src_parts, dst_parts
                            ) -> Tuple[int, Dict[str, Any]]:
        src_parent, src_name, inode = await self._resolve(args["src"])
        if inode is None:
            return ENOENT, {}
        dst_parent, dst_name, existing = await self._resolve(
            args["dst"])
        if not dst_name:
            return EINVAL, {}
        # VALIDATE FIRST: recalls collect writers' dirty sizes, and an
        # error return after a recall would discard a flush that only
        # _commit can persist
        if existing is not None:
            if existing["type"] == "dir":
                if inode["type"] != "dir":
                    return EISDIR, {}
                if await self._load_dir(
                        existing["ino"],
                        owned=self._dir_owned(args["dst"])):
                    return ENOTEMPTY, {}
            elif inode["type"] == "dir":
                return ENOTDIR, {}
        if inode["type"] == "dir" and self.num_ranks > 1:
            sub, ok = self._dir_move_ranks(src_parts, dst_parts, True)
            if ok is None:
                # the src became a dir after _op_rename's peek: a
                # retry takes the subtree-export path
                return ESTALE, {"error": "src changed; retry"}
            if sub != self.rank:
                # paths under the moved dir are served by rank `sub`:
                # its clients' path caches (and its path-keyed state)
                # must flush.  Called WITHOUT our mutation lock?  No —
                # peer_revoke never takes the peer's mutation lock, so
                # holding ours here cannot deadlock.
                try:
                    await self._peer_request(
                        sub, "peer_revoke", {"revoke_all": True})
                except (RadosError, ObjectNotFound, ConnectionError,
                        OSError, asyncio.TimeoutError):
                    return ESTALE, {"error": "subtree rank"
                                             " unavailable"}
        # recall caps on the moved inode (cached paths go stale) and
        # fold a writer's dirty size into the dentry we re-link; the
        # clobbered target's caps go too (it is dying), its flushed
        # size feeding the purge.  Renaming a DIRECTORY invalidates
        # every descendant's cached PATH on every client — paths are
        # the cache key, so recall everything (dir renames are rare;
        # the reference's per-dentry lease recall is finer-grained)
        if inode["type"] == "dir":
            # bystander writers' flushed sizes must land while their
            # paths still resolve (we hold the mutation lock)
            for fl in await self._revoke_all_caps():
                await self._apply_flush_locked(fl, fl.get("path", ""))
        flush = await self._revoke_caps(inode["ino"])
        if flush.get("size_max") is not None:
            inode["size"] = max(inode.get("size", 0),
                                int(flush["size_max"]))
        if existing is not None and existing["ino"] != inode["ino"]:
            eflush = await self._revoke_caps(existing["ino"])
            if eflush.get("size_max") is not None:
                existing["size"] = max(existing.get("size", 0),
                                       int(eflush["size_max"]))
        # ONE journal entry carries both dentry ops: rename is
        # crash-atomic — the append is the commit point, replay
        # finishes a half-applied rename (journal.cc EUpdate role).
        # Clobbered targets are cleaned up in the same entry: an empty
        # dir's object is removed, a file's data objects purged.
        ops = [self._dentry(dst_parent, dst_name, inode)]
        if (src_parent, src_name) != (dst_parent, dst_name):
            ops.append(self._dentry(src_parent, src_name, None))
            if existing is not None and existing["ino"] != inode["ino"]:
                if existing["type"] == "dir":
                    ops.append({"op": "rmdirobj",
                                "ino": existing["ino"]})
                elif existing["type"] == "file":
                    ops.append({"op": "purgefile",
                                "ino": existing["ino"],
                                "size": existing.get("size", 0),
                                "block_size": existing.get(
                                    "block_size", 1 << 22)})
        await self._commit(ops)
        return 0, {"inode": inode}

    async def _rename_cross_rank(self, args, dst_rank, src_parts,
                                 dst_parts
                                 ) -> Tuple[int, Dict[str, Any]]:
        async with self._mutation_lock:
            src_parent, src_name, inode = await self._resolve(
                args["src"])
            if inode is None:
                return ENOENT, {}
            if inode["type"] == "dir":
                sub, ok = self._dir_move_ranks(src_parts, dst_parts,
                                               True)
                if ok is None:
                    # raced into a dir post-peek: retry re-routes to
                    # the subtree-export path
                    return ESTALE, {"error": "src changed; retry"}
            flush = await self._revoke_caps(inode["ino"])
            if flush.get("size_max") is not None:
                inode["size"] = max(inode.get("size", 0),
                                    int(flush["size_max"]))
            intent_seq = await self._commit([
                {"op": "rename_intent", "src_dir": src_parent,
                 "src_name": src_name, "dst": args["dst"],
                 "inode": inode}])
        # dir rename: the subtree rank's clients hold the moving
        # paths (no lock held: peer RPCs)
        if inode["type"] == "dir":
            target = self._subtree_rank(src_parts[0])
            if target != self.rank:
                try:
                    await self._peer_request(
                        target, "peer_revoke", {"revoke_all": True})
                except (RadosError, ObjectNotFound, ConnectionError,
                        OSError, asyncio.TimeoutError):
                    async with self._mutation_lock:
                        await self._commit([{
                            "op": "rename_finish",
                            "intent_seq": intent_seq}])
                    return ESTALE, {"error": "subtree rank"
                                             " unavailable"}
            else:
                for fl in await self._revoke_all_caps():
                    await self._apply_flush(fl, fl.get("path", ""))
        try:
            rc, out = await self._peer_request(
                dst_rank, "peer_link",
                {"dst": args["dst"], "inode": inode})
        except (RadosError, ObjectNotFound, ConnectionError, OSError,
                asyncio.TimeoutError):
            rc, out = ESTALE, {"error": "dst rank unavailable"}
        async with self._mutation_lock:
            if rc != 0:
                await self._commit([{"op": "rename_finish",
                                     "intent_seq": intent_seq}])
                return rc, out
            cur_p, cur_n, cur = await self._resolve(args["src"])
            if cur is not None and cur.get("ino") == inode["ino"]:
                await self._commit([
                    self._dentry(src_parent, src_name, None),
                    {"op": "rename_finish",
                     "intent_seq": intent_seq}])
                return 0, {"inode": inode}
        # the src dentry changed while the lock was released (a
        # concurrent op won the race): compensate — unlink the dst
        # dentry we just linked, value-checked so a NEWER dst write
        # survives
        try:
            await self._peer_request(
                dst_rank, "peer_unlink_ifmatch",
                {"dst": args["dst"], "ino": inode["ino"]})
        except (RadosError, ObjectNotFound, ConnectionError, OSError,
                asyncio.TimeoutError):
            log.warning("mds.%s: rename compensation to rank %d"
                        " failed; dst keeps the link", self.name,
                        dst_rank)
        async with self._mutation_lock:
            await self._commit([{"op": "rename_finish",
                                 "intent_seq": intent_seq}])
        return ESTALE, {"error": "src dentry raced away"}

    async def _finish_pending_renames(self) -> None:
        """Takeover recovery: every journaled rename_intent without a
        rename_finish is re-driven — peer_link again (idempotent at
        the dst), then src removal + finish.  If the src dentry no
        longer carries the ino the intent names, the rename already
        finished (or lost a race) — just close the intent."""
        for seq, intent in sorted(self._pending_intents.items()):
            args = {"src": None, "dst": intent["dst"]}
            inode = intent["inode"]
            dst_rank = owner_rank(intent["dst"], self.num_ranks)
            src_dir, src_name = intent["src_dir"], intent["src_name"]
            try:
                entries = await self._load_dir(src_dir)
                cur = entries.get(src_name)
            except MDSError:
                cur = None
            if cur is None or cur.get("ino") != inode["ino"]:
                await self._commit([{"op": "rename_finish",
                                     "intent_seq": seq}])
                continue
            try:
                rc, _out = await self._peer_request(
                    dst_rank, "peer_link",
                    {"dst": intent["dst"], "inode": inode})
            except (RadosError, ObjectNotFound, ConnectionError,
                    OSError, asyncio.TimeoutError):
                log.warning("mds.%s: pending rename intent %d: dst"
                            " rank %d unreachable; left pending",
                            self.name, seq, dst_rank)
                continue  # stays pending; next takeover retries
            ops = [{"op": "rename_finish", "intent_seq": seq}]
            if rc == 0:
                ops.insert(0, self._dentry(src_dir, src_name, None))
            await self._commit(ops)
        self._pending_intents.clear()

    # -- subtree migration (Migrator/MExportDir role) ----------------------
    #
    # A directory rename whose src and dst top-level components hash
    # to different ranks RE-HOMES the subtree.  Per-rank fencing
    # epochs are incomparable, so ownership of the existing dir
    # OBJECTS cannot move — instead, like the reference's Migrator
    # (/root/reference/src/mds/Migrator.cc: EXPORT serializes the
    # subtree metadata and the importer re-journals it as its own),
    # the importer re-creates the subtree under FRESH inos in its own
    # fencing domain and the exporter purges the old objects:
    #
    #   A (owner of the src dentry) journals export_intent
    #   S (subtree rank) dumps the tree and FREEZES it (EAGAIN
    #     to mutations under the prefix, TTL-bounded)
    #   T (new subtree rank) allocates new inos, rewrites dir
    #     entries (dir children remapped, file inos unchanged — data
    #     objects never move), journals ONE import entry
    #   D (owner of the dst dentry) links dst -> new root (peer_link)
    #   A removes the src dentry, S purges the old dir objects
    #     (snap-context aware: snapshots... see the EBUSY guard),
    #   A journals export_finish.
    #
    # Crash at any point re-drives from the journal: import is
    # idempotent (intent-id keyed), link is idempotent, purge is
    # ENOENT-tolerant.  Deposed-active writes stay harmless with NO
    # cross-rank epoch comparison: stale writes can only touch the
    # OLD objects (garbage awaiting purge) or A's own chain (same-
    # rank fencing).  Subtrees referenced by CephFS snapshots refuse
    # to migrate (EBUSY): snapshot resolution keys dirs by ino and
    # the re-created tree has new inos.

    EXPORT_FREEZE_TTL = 30.0
    EXPORT_MAX_DIRS = 2048
    MUTATING_OPS = frozenset((
        "mkdir", "create", "symlink", "unlink", "rmdir", "rename",
        "setattr", "mksnap", "rmsnap"))

    @staticmethod
    def _norm_path(path: str) -> str:
        return "/" + "/".join(p for p in path.split("/") if p)

    async def _export_subtree(self, args, src_parts, dst_parts
                              ) -> Tuple[int, Dict[str, Any]]:
        src_path = self._norm_path(args["src"])
        dst_path = self._norm_path(args["dst"])
        if dst_path == src_path or \
                dst_path.startswith(src_path + "/"):
            return EINVAL, {"error": "dst inside the moved subtree"}
        async with self._mutation_lock:
            src_parent, src_name, inode = await self._resolve(
                src_path)
            if inode is None:
                return ENOENT, {}
            if inode["type"] != "dir":
                # raced to a non-dir: the ordinary rename paths apply
                return ESTALE, {"error": "src changed; retry"}
            _dp, dn, existing = await self._resolve(dst_path)
            if not dn:
                return EINVAL, {}
            if existing is not None:
                # migration does not clobber (the reference freezes
                # only clean exports too); callers remove dst first
                return EEXIST, {"error": "dst exists"}
            intent_id = f"x{self.rank}.{self._epoch}.{self._seq}"
            seq = await self._commit([{
                "op": "export_intent", "id": intent_id,
                "src_dir": src_parent, "src_name": src_name,
                "src": src_path, "dst": dst_path, "inode": inode}])
            intent = {
                "seq": seq, "src_dir": src_parent,
                "src_name": src_name, "src": src_path,
                "dst": dst_path, "inode": inode, "id": intent_id}
        # freeze HERE too: ops on the src dentry itself (rename/
        # rmdir/mksnap of a TOP-LEVEL dir) route to the dentry owner
        # — this rank — not the subtree rank, and must bounce while
        # the export runs
        self._frozen_subtrees[src_path] = \
            time.monotonic() + self.EXPORT_FREEZE_TTL
        return await self._export_drive(intent)

    async def _export_drive(self, it: Dict[str, Any]
                            ) -> Tuple[int, Dict[str, Any]]:
        """Drive one export intent end to end (initial run and
        takeover re-drive share this).  NEVER called holding the
        mutation lock (peer RPCs inside)."""
        seq = it["seq"]
        inode = it["inode"]
        src_parts = [p for p in it["src"].split("/") if p]
        dst_parts = [p for p in it["dst"].split("/") if p]
        s_rank = self._subtree_rank(src_parts[0])
        t_rank = self._subtree_rank(dst_parts[0])
        d_rank = owner_rank(it["dst"], self.num_ranks)
        # every rank's clients hold soon-stale paths: recall all caps
        for r in range(self.num_ranks):
            try:
                if r == self.rank:
                    for fl in await self._revoke_all_caps():
                        await self._apply_flush(fl,
                                                fl.get("path", ""))
                else:
                    await self._peer_request(
                        r, "peer_revoke", {"revoke_all": True})
            except (RadosError, ObjectNotFound, ConnectionError,
                    OSError, asyncio.TimeoutError):
                return ESTALE, {"error": f"rank {r} unavailable"}
        # dump + freeze at the subtree rank
        rc, out = await self._peer_call(
            s_rank, "peer_subtree_dump",
            {"root": inode["ino"], "prefix": it["src"],
             "max_dirs": self.EXPORT_MAX_DIRS})
        if rc != 0:
            await self._close_export(seq, it["src"])
            return rc, out
        dirs = out["dirs"]
        old_inos = [d["ino"] for d in dirs]
        # import at the new subtree rank (idempotent by intent id)
        rc, iout = await self._peer_call(
            t_rank, "peer_subtree_import",
            {"id": it["id"], "dirs": dirs, "root": inode["ino"]})
        if rc != 0:
            await self._peer_call(s_rank, "peer_subtree_thaw",
                                  {"prefix": it["src"]})
            await self._close_export(seq, it["src"])
            return rc, iout
        new_root = int(iout["root"])
        async with self._mutation_lock:
            await self._commit([{
                "op": "export_imported", "intent_seq": seq,
                "id": it["id"], "old_inos": old_inos,
                "new_root": new_root,
                "created": list(iout.get("created", []))}])
        return await self._export_finish_phase(
            seq, it, old_inos, new_root, s_rank, d_rank)

    async def _export_finish_phase(self, seq: int, it: Dict[str, Any],
                                   old_inos, new_root: int,
                                   s_rank: int, d_rank: int
                                   ) -> Tuple[int, Dict[str, Any]]:
        new_inode = dict(it["inode"], ino=new_root)
        rc, out = await self._peer_call(
            d_rank, "peer_link", {"dst": it["dst"],
                                  "inode": new_inode})
        if rc != 0:
            # dst raced into existence: leave the intent open (a
            # takeover retries once the conflict clears) — the new
            # objects are unreachable garbage until then.  Thaw: the
            # re-drive DISCARDS the stale import and re-dumps, so the
            # src must stay usable.
            await self._peer_call(s_rank, "peer_subtree_thaw",
                                  {"prefix": it["src"]})
            self._frozen_subtrees.pop(it["src"], None)
            return rc, out
        async with self._mutation_lock:
            try:
                cur = (await self._load_dir(it["src_dir"])).get(
                    it["src_name"])
            except MDSError:
                cur = None
            if cur is not None and \
                    cur.get("ino") == it["inode"]["ino"]:
                await self._commit([self._dentry(
                    it["src_dir"], it["src_name"], None)])
        rc, _pout = await self._peer_call(
            s_rank, "peer_subtree_purge",
            {"inos": old_inos, "prefix": it["src"]})
        self._frozen_subtrees.pop(it["src"], None)
        if rc != 0:
            # old objects linger; the intent stays open so a takeover
            # re-purges (idempotent).  The rename itself is complete.
            log.warning("mds.%s: export purge at rank %d failed;"
                        " will re-drive", self.name, s_rank)
            return 0, {"inode": new_inode}
        await self._close_export(seq)
        return 0, {"inode": new_inode}

    async def _close_export(self, seq: int,
                            src_path: Optional[str] = None) -> None:
        async with self._mutation_lock:
            await self._commit([{"op": "export_finish",
                                 "intent_seq": seq}])
        self._pending_exports.pop(seq, None)
        if src_path is not None:
            self._frozen_subtrees.pop(src_path, None)

    async def _peer_call(self, rank: int, op: str, args: dict
                         ) -> Tuple[int, Dict[str, Any]]:
        """peer_request that treats self-rank uniformly (the local
        fastpath makes a self-RPC cheap) and folds transport errors
        into ESTALE."""
        try:
            return await self._peer_request(rank, op, args,
                                            timeout=20.0)
        except (RadosError, ObjectNotFound, ConnectionError, OSError,
                asyncio.TimeoutError):
            return ESTALE, {"error": f"rank {rank} unreachable"}

    async def _finish_pending_exports(self) -> None:
        """Takeover: re-drive every journaled export_intent without a
        matching export_finish."""
        for seq, rec in sorted(self._pending_exports.items()):
            op = rec["intent"]
            it = {"seq": seq, "src_dir": op["src_dir"],
                  "src_name": op["src_name"], "src": op["src"],
                  "dst": op["dst"], "inode": op["inode"],
                  "id": op["id"]}
            try:
                if "imported" in rec:
                    await self._redrive_imported(seq, it,
                                                 rec["imported"])
                else:
                    # not imported yet: if the src dentry still names
                    # the old ino, redo the whole drive; else the
                    # export never really started — close it
                    try:
                        cur = (await self._load_dir(
                            it["src_dir"])).get(it["src_name"])
                    except MDSError:
                        cur = None
                    if cur is not None and \
                            cur.get("ino") == it["inode"]["ino"]:
                        await self._export_drive(it)
                    else:
                        await self._close_export(seq, it["src"])
            except Exception:
                log.exception("mds.%s: export re-drive %d failed;"
                              " left pending", self.name, seq)

    async def _redrive_imported(self, seq: int, it: Dict[str, Any],
                                imp: Dict[str, Any]) -> None:
        """Re-drive an export that crashed after the import.  The
        imported copy may be STALE: if the source thawed and took
        mutations since, re-linking it would silently discard them —
        so the copy is only finished when the dst link already
        LANDED; otherwise it is discarded and the export redone from
        a fresh dump."""
        src_parts = [p for p in it["src"].split("/") if p]
        dst_parts = [p for p in it["dst"].split("/") if p]
        s_rank = self._subtree_rank(src_parts[0])
        t_rank = self._subtree_rank(dst_parts[0])
        d_rank = owner_rank(it["dst"], self.num_ranks)
        new_root = int(imp["new_root"])
        try:
            _dp, _dn, dst_cur = await self._resolve(it["dst"])
        except MDSError:
            dst_cur = None
        if dst_cur is not None and dst_cur.get("ino") == new_root:
            # the link landed before the crash: the imported tree IS
            # the live one — finish (src unlink + old purge)
            await self._export_finish_phase(
                seq, it, imp["old_inos"], new_root, s_rank, d_rank)
            return
        # link never landed: the imported copy is unreachable and
        # possibly stale — discard it (purge the new objects, drop
        # the importer's idempotency record)
        await self._peer_call(
            t_rank, "peer_subtree_forget",
            {"id": it["id"], "inos": list(imp.get("created", []))})
        try:
            cur = (await self._load_dir(it["src_dir"])).get(
                it["src_name"])
        except MDSError:
            cur = None
        if cur is not None and cur.get("ino") == it["inode"]["ino"]:
            # source intact: redo the export under a FRESH intent id
            # (the forget dropped the old id's record)
            it = dict(it, id=it["id"] + f".r{self._epoch}")
            await self._export_drive(it)
        else:
            # source moved on (post-thaw user activity): the export
            # is moot
            await self._close_export(seq, it["src"])

    async def _op_peer_subtree_dump(self, args, conn=None
                                    ) -> Tuple[int, Dict[str, Any]]:
        """Subtree-rank half: serialize the tree (the MExportDir
        payload role) and freeze the prefix.  Runs under OUR mutation
        lock, so the dump is a consistent cut."""
        root = int(args["root"])
        max_dirs = int(args.get("max_dirs", self.EXPORT_MAX_DIRS))
        out_dirs: List[Dict[str, Any]] = []
        todo = [root]
        while todo:
            if len(out_dirs) >= max_dirs:
                return EFBIG, {"error": "subtree too large to"
                                        " migrate"}
            ino = todo.pop()
            try:
                entries = await self._load_dir(ino, owned=True)
            except MDSError:
                entries = {}  # half-created dir object: export empty
            out_dirs.append({"ino": ino, "entries": entries})
            todo.extend(e["ino"] for e in entries.values()
                        if e.get("type") == "dir")
        # snapshots key dirs by ino; a migrated (re-inoed) subtree
        # would orphan them — refuse BEFORE freezing
        self._snap_invalidate()
        recs = await self._snap_records()
        inos = {d["ino"] for d in out_dirs}
        if any(r["ino"] in inos for r in recs.values()):
            return EBUSY, {"error": "subtree has snapshots"}
        self._frozen_subtrees[self._norm_path(args["prefix"])] = \
            time.monotonic() + self.EXPORT_FREEZE_TTL
        return 0, {"dirs": out_dirs}

    async def _op_peer_subtree_thaw(self, args, conn=None
                                    ) -> Tuple[int, Dict[str, Any]]:
        """Abort path: release the freeze early (lock-free — pure
        in-memory state; the TTL is the backstop)."""
        self._frozen_subtrees.pop(
            self._norm_path(args.get("prefix", "")), None)
        return 0, {}

    async def _op_peer_subtree_import(self, args, conn=None
                                      ) -> Tuple[int, Dict[str, Any]]:
        """New-subtree-rank half: re-create the dirs under fresh inos
        in OUR fencing domain (the importer re-journals the metadata
        as its own — Migrator.cc import).  Idempotent by intent id."""
        intent = args["id"]
        if intent in self._imports:
            return 0, {"root": self._imports[intent]}
        dirs = args["dirs"]
        mapping = {int(d["ino"]): await self._alloc_ino()
                   for d in dirs}
        ops: List[Dict[str, Any]] = []
        for d in dirs:
            new_ino = mapping[int(d["ino"])]
            ops.append({"op": "mkdirobj", "ino": new_ino})
            for name, ent in d["entries"].items():
                ent = dict(ent)
                if ent.get("type") == "dir":
                    ent["ino"] = mapping.get(int(ent["ino"]),
                                             ent["ino"])
                ops.append(self._dentry(new_ino, name, ent))
        root_new = mapping[int(args["root"])]
        ops.append({"op": "import_done", "id": intent,
                    "root": root_new})
        await self._commit(ops)
        self._imports[intent] = root_new
        return 0, {"root": root_new,
                   "created": sorted(mapping.values())}

    async def _op_peer_subtree_forget(self, args, conn=None
                                      ) -> Tuple[int, Dict[str, Any]]:
        """Discard a stale import: remove the created (never-linked)
        dir objects and drop the idempotency record, so the
        coordinator's re-drive can import a FRESH dump.  The forget
        is journaled — a takeover must not resurrect the record."""
        intent = args.get("id", "")
        ops = [{"op": "rmdirobj", "ino": int(i)}
               for i in args.get("inos", [])]
        ops.append({"op": "import_forget", "id": intent})
        await self._commit(ops)
        self._imports.pop(intent, None)
        return 0, {}

    async def _op_peer_subtree_purge(self, args, conn=None
                                     ) -> Tuple[int, Dict[str, Any]]:
        """Subtree-rank half: drop the exported (now garbage) dir
        objects and thaw the prefix.  guarded_remove is fenced by OUR
        chain and tolerant of already-gone objects."""
        ops = [{"op": "rmdirobj", "ino": int(i)}
               for i in args.get("inos", [])]
        if ops:
            await self._commit(ops)
        self._frozen_subtrees.pop(
            self._norm_path(args.get("prefix", "")), None)
        return 0, {}

    async def _op_peer_link(self, args,
                            conn=None) -> Tuple[int, Dict[str, Any]]:
        """Dst half of a cross-rank rename, executed by the OWNER of
        the dst directory under ITS mutation lock/journal/epoch.
        Idempotent: a replayed intent whose link already landed
        returns success without re-journaling."""
        inode = args["inode"]
        dst_parent, dst_name, existing = await self._resolve(
            args["dst"])
        if not dst_name:
            return EINVAL, {}
        if self._dying(dst_parent):
            return ESTALE, {"error": "dst dir is being removed"}
        if existing is not None and existing["ino"] == inode["ino"]:
            return 0, {}
        if existing is not None:
            if existing["type"] == "dir":
                if inode["type"] != "dir":
                    return EISDIR, {}
                if await self._load_dir(
                        existing["ino"],
                        owned=self._dir_owned(args["dst"])):
                    return ENOTEMPTY, {}
            elif inode["type"] == "dir":
                return ENOTDIR, {}
        inos = [inode["ino"]]
        if existing is not None:
            inos.append(existing["ino"])
        merged = await self._revoke_many(inos)
        if existing is not None and                 merged.get(existing["ino"], {}).get("size_max")                 is not None:
            existing["size"] = max(
                existing.get("size", 0),
                int(merged[existing["ino"]]["size_max"]))
        ops = [self._dentry(dst_parent, dst_name, inode)]
        if existing is not None and existing["ino"] != inode["ino"]:
            if existing["type"] == "dir":
                ops.append({"op": "rmdirobj", "ino": existing["ino"]})
            elif existing["type"] == "file":
                ops.append({"op": "purgefile", "ino": existing["ino"],
                            "size": existing.get("size", 0),
                            "block_size": existing.get("block_size",
                                                       1 << 22)})
        await self._commit(ops)
        return 0, {}

    async def _op_peer_unlink_ifmatch(self, args, conn=None
                                      ) -> Tuple[int, Dict[str, Any]]:
        """Compensation: remove the dst dentry IFF it still carries
        the ino a failed cross-rank rename linked (value-checked — a
        newer write to the same name survives)."""
        dst_parent, dst_name, cur = await self._resolve(args["dst"])
        if cur is not None and cur.get("ino") == args.get("ino"):
            await self._revoke_caps(cur["ino"])
            await self._commit([self._dentry(dst_parent, dst_name,
                                             None)])
        return 0, {}

    # -- top-level rmdir across ranks (owner-side adjudication) ------------

    def _dying(self, ino: int) -> bool:
        exp = self._dying_dirs.get(ino)
        if exp is None:
            return False
        if exp <= time.monotonic():
            self._dying_dirs.pop(ino, None)
            return False
        return True

    async def _op_peer_rmdir_begin(self, args, conn=None
                                   ) -> Tuple[int, Dict[str, Any]]:
        """Rank 0 wants to remove a top-level dir WE own: adjudicate
        emptiness under OUR mutation lock and fence new creates into
        it with a dying mark (TTL-bounded so a crashed remover cannot
        wedge the dir forever)."""
        ino = int(args["ino"])
        entries = await self._load_dir(ino, owned=True)
        if entries:
            return ENOTEMPTY, {}
        self._dying_dirs[ino] = time.monotonic() + 10.0
        return 0, {}

    async def _op_peer_rmdir_done(self, args, conn=None
                                  ) -> Tuple[int, Dict[str, Any]]:
        """Close the protocol: if the dentry removal committed, WE
        remove the (empty) directory object under OUR epoch; either
        way the dying mark clears."""
        ino = int(args["ino"])
        self._dying_dirs.pop(ino, None)
        if args.get("removed"):
            await self._commit([{"op": "rmdirobj", "ino": ino}])
        return 0, {}

    async def _op_setattr(self, args,

                          conn=None) -> Tuple[int, Dict[str, Any]]:
        parent, name, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        # a foreign setattr invalidates cached attrs everywhere else;
        # a foreign writer's dirty size folds in first so an explicit
        # truncate wins over it but a size_max merge sees it
        flush = await self._revoke_caps(inode["ino"], keep=conn)
        changed = False
        if flush.get("size_max") is not None and "size" not in args:
            new = max(inode.get("size", 0), int(flush["size_max"]))
            changed = new != inode.get("size")
            inode["size"] = new
        for key in ("size", "mode", "mtime"):
            if key in args:
                inode[key] = args[key]
                changed = True
        if args.get("size_max") is not None:
            # concurrent writers race size updates: take the max
            # (the size-extending cap flush discipline)
            new = max(inode.get("size", 0), int(args["size_max"]))
            changed = changed or new != inode.get("size")
            inode["size"] = new
        if changed:
            inode["mtime"] = args.get("mtime", self._now())
            await self._commit([self._dentry(parent, name, inode)])
        return 0, {"inode": inode}

    # -- snapshots (SnapServer + SnapRealm + snapdir roles) ----------------
    #
    # Reference parity: src/mds/SnapServer.h (snapid allocation +
    # global snap table), src/mds/snap.cc SnapRealm (which snaps cover
    # an inode), src/mds/Server.cc handle_client_mksnap/rmsnap and the
    # client's ".snap" pseudo-directory (src/client/Client.cc
    # vinodeno_t snapid traversal).  Re-design: COW is delegated
    # entirely to RADOS self-managed snapshots (one snapid per pool
    # per CephFS snapshot) instead of past-parent dentry versioning —
    # dir omap objects clone on the owning rank's next mutation, file
    # data objects clone on the clients' next writes, and ".snap"
    # reads resolve against those snapids.  Point-in-time is the
    # mksnap window (in-flight writes racing mksnap may land on
    # either side), matching the reference's non-linearizable snap
    # semantics.

    @staticmethod
    def _split_snap_path(path: str):
        """'/a/b/.snap/s1/c' -> (['a','b'], ['s1','c']); None when the
        path has no .snap component."""
        parts = [p for p in path.split("/") if p]
        if SNAP_DIR not in parts:
            return None
        i = parts.index(SNAP_DIR)
        return parts[:i], parts[i + 1:]

    async def _snap_records(self) -> Dict[str, dict]:
        """The global snap table: omap key -> record dict (cached
        briefly; version and records come from ONE omap read so they
        are mutually consistent)."""
        now = time.monotonic()
        if self._snap_cache is not None and \
                now - self._snap_cache[0] < self._snap_cache_ttl:
            return self._snap_cache[2]
        try:
            omap = await self.meta.omap_get(SNAPTABLE_OBJ)
        except ObjectNotFound:
            omap = {}
        ver = 0
        recs: Dict[str, dict] = {}
        for k, v in omap.items():
            if k == SNAPVER_KEY:
                ver = int(float(v.decode()))
            elif not k.startswith("\x00"):
                recs[k] = json.loads(v.decode())
        self._snap_cache = (now, ver, recs)
        return recs

    def _snap_invalidate(self) -> None:
        self._snap_cache = None

    async def _bump_snap_ver(self) -> int:
        raw = await self.meta.execute(
            SNAPTABLE_OBJ, "numops", "add",
            json.dumps({"key": SNAPVER_KEY, "value": 1}).encode())
        return int(float(raw.decode()))

    async def _dir_snaps(self, ino: int) -> Dict[str, dict]:
        """Snapshots taken ON directory ino: name -> record.  PENDING
        rows (mksnap in flight or crashed mid-way) are invisible —
        they exist only so their snapids stay accounted for."""
        return {rec["name"]: rec
                for rec in (await self._snap_records()).values()
                if rec["ino"] == ino and not rec.get("pending")}

    async def _refresh_snapc(self) -> None:
        """Recompute both pools' write snap contexts from the snap
        table and arm them on this rank's IoCtxs (the SnapRealm
        get_snap_context role, collapsed to one global realm)."""
        self._snap_invalidate()
        recs = (await self._snap_records()).values()
        self._snapc_ver = self._snap_cache[1] \
            if self._snap_cache is not None else 0
        meta_snaps = sorted((r["meta_snap"] for r in recs),
                            reverse=True)
        data_snaps = sorted((r["data_snap"] for r in recs),
                            reverse=True)
        self.meta.set_snap_context(
            meta_snaps[0] if meta_snaps else 0, meta_snaps)
        self.data_io.set_snap_context(
            data_snaps[0] if data_snaps else 0, data_snaps)
        self._data_snapc = (data_snaps[0] if data_snaps else 0,
                            data_snaps)

    def _snap_io(self, meta_snap: int) -> IoCtx:
        io = self._snap_ios.get(meta_snap)
        if io is None:
            io = self.client.open_ioctx(self.metadata_pool)
            io.snap_set_read(meta_snap)
            self._snap_ios[meta_snap] = io
        return io

    async def _load_dir_snap(self, ino: int,
                             meta_snap: int) -> Dict[str, dict]:
        """Directory entries as of a metadata snapid (reads resolve to
        the head or a clone server-side).  Immutable -> cacheable."""
        key = (ino, meta_snap)
        cached = self._snap_dirs.get(key)
        if cached is not None:
            return cached
        try:
            omap = await self._snap_io(meta_snap).omap_get(
                dir_obj(ino))
        except ObjectNotFound:
            raise MDSError(ENOENT, f"no directory {ino:x}@{meta_snap}")
        entries = {name: json.loads(raw.decode())
                   for name, raw in omap.items()}
        if len(self._snap_dirs) >= 512:
            self._snap_dirs.clear()
        self._snap_dirs[key] = entries
        return entries

    async def _snap_base(self, base_parts) -> dict:
        """Resolve the directory the .snap component hangs off (at
        head)."""
        if not base_parts:
            return {"ino": ROOT_INO, "type": "dir", "mode": 0o755,
                    "size": 0, "mtime": 0}
        _p, _n, inode = await self._resolve("/" + "/".join(base_parts))
        if inode is None:
            raise MDSError(ENOENT, "/".join(base_parts))
        if inode["type"] != "dir":
            raise MDSError(ENOTDIR, "/".join(base_parts))
        return inode

    async def _snap_resolve(self, path: str) -> Optional[dict]:
        """Resolve a path BELOW .snap/<name> to its inode as of that
        snapshot, annotated with the data snapid for file reads.
        Returns None for ENOENT mid-walk."""
        base, rest = self._split_snap_path(path)
        dir_inode = await self._snap_base(base)
        if not rest:  # the .snap pseudo-directory itself
            return {"ino": 0, "type": "dir", "mode": 0o555,
                    "size": 0, "mtime": 0, "readonly": True}
        snaps = await self._dir_snaps(dir_inode["ino"])
        rec = snaps.get(rest[0])
        if rec is None:
            return None
        cur = dict(dir_inode)
        for comp in rest[1:]:
            if cur["type"] != "dir":
                raise MDSError(ENOTDIR, comp)
            entries = await self._load_dir_snap(cur["ino"],
                                                rec["meta_snap"])
            nxt = entries.get(comp)
            if nxt is None:
                return None
            cur = dict(nxt)
        cur["snapid"] = rec["data_snap"]
        cur["readonly"] = True
        return cur

    async def _snap_lookup(self, args) -> Tuple[int, Dict[str, Any]]:
        """lookup/stat on a .snap path: never grants caps (snapshots
        are immutable; nothing to keep coherent)."""
        base, rest = self._split_snap_path(args["path"])
        if not rest:  # the .snap pseudo-directory itself
            await self._snap_base(base)  # existence check
            return 0, {"inode": {"ino": 0, "type": "dir",
                                 "mode": 0o555, "size": 0, "mtime": 0,
                                 "readonly": True}}
        inode = await self._snap_resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        return 0, {"inode": inode}

    async def _snap_readdir(self, args) -> Tuple[int, Dict[str, Any]]:
        base, rest = self._split_snap_path(args["path"])
        dir_inode = await self._snap_base(base)
        snaps = await self._dir_snaps(dir_inode["ino"])
        if not rest:
            # ls /a/.snap -> one pseudo-dir per snapshot
            entries = {
                name: {"ino": dir_inode["ino"], "type": "dir",
                       "mode": 0o555, "size": 0,
                       "mtime": rec.get("ctime", 0),
                       "snapid": rec["data_snap"], "readonly": True}
                for name, rec in snaps.items()}
            return 0, {"entries": dict(sorted(entries.items()))}
        rec = snaps.get(rest[0])
        if rec is None:
            return ENOENT, {}
        cur_ino, cur_type = dir_inode["ino"], "dir"
        for comp in rest[1:]:
            if cur_type != "dir":
                return ENOTDIR, {}
            entries = await self._load_dir_snap(cur_ino,
                                                rec["meta_snap"])
            nxt = entries.get(comp)
            if nxt is None:
                return ENOENT, {}
            cur_ino, cur_type = nxt["ino"], nxt["type"]
        if cur_type != "dir":
            return ENOTDIR, {}
        entries = await self._load_dir_snap(cur_ino, rec["meta_snap"])
        out = {}
        for name, inode in sorted(entries.items()):
            inode = dict(inode)
            inode["snapid"] = rec["data_snap"]
            inode["readonly"] = True
            out[name] = inode
        return 0, {"entries": out}

    async def _op_mksnap(self, args,
                         conn=None) -> Tuple[int, Dict[str, Any]]:
        """Snapshot the directory at args['path'] under args['name']
        (handle_client_mksnap).  Ordering: allocate snapids -> publish
        in the snap table -> refresh every rank's and client's snap
        context (peer fan-out + cap recall) -> ack.  A crash before
        the table write leaks only pool snapids (harmless, trimmed as
        empty); after it, the snapshot exists and takeover republishes
        contexts."""
        name = args.get("name", "")
        if not name or "/" in name or name == SNAP_DIR:
            return EINVAL, {}
        if self._split_snap_path(args["path"]) is not None:
            return EROFS, {}
        _p, _n, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        if inode["type"] != "dir":
            return ENOTDIR, {}
        self._snap_invalidate()
        if name in await self._dir_snaps(inode["ino"]):
            return EEXIST, {}
        # Phase 1 — allocate snapids and record them as a PENDING
        # table row BEFORE any advertisement.  Pending rows are
        # invisible to .snap readers but their snapids ride every
        # write context, so clones created against them stay
        # accounted for: a crash mid-mksnap leaves a row the
        # takeover sweeps (releasing the snapids into removed_snaps,
        # which trims the clones) instead of a permanent leak.
        # OUR metadata write context stays on the pre-snap side: the
        # cap-flush persists below must not clone against the new
        # snapid, or the snapshot would record capped writers' stale
        # (possibly zero) sizes forever.
        meta_ctx = (self.meta.snapc_seq, list(self.meta.snapc_snaps))
        data_snap = await self.data_io.create_selfmanaged_snap()
        meta_snap = await self.meta.create_selfmanaged_snap()
        self.meta.set_snap_context(*meta_ctx)  # defer metadata arming
        rec = {"name": name, "ino": inode["ino"],
               "meta_snap": meta_snap, "data_snap": data_snap,
               "ctime": self._now(), "pending": True,
               "rank": self.rank}
        row_key = f"{data_snap:016x}"
        await self.meta.omap_set(
            SNAPTABLE_OBJ, {row_key: json.dumps(rec).encode()})
        # Phase 2 — bump the DURABLE table version, then arm the
        # client-facing data context at that version and recall every
        # cap: each recall carries the new context (a capped writer
        # COWs its very next write), and the acks return dirty sizes,
        # persisted on the pre-snapshot side of the metadata.  The
        # durable bump precedes any advertisement, so a crash here
        # leaves table-ver >= every advertised ver and a takeover's
        # refresh can still correct the clients (regression guard
        # compares >=).
        self._snapc_ver = await self._bump_snap_ver()
        self._data_snapc = (data_snap,
                            [data_snap] + list(self._data_snapc[1]))
        flushed = await self._revoke_all_caps()
        for fl in flushed:
            await self._apply_flush_locked(fl, fl.get("path", ""))
        # Phase 3 — finalize the row: the snapshot becomes visible.
        rec.pop("pending")
        await self.meta.omap_set(
            SNAPTABLE_OBJ, {row_key: json.dumps(rec).encode()})
        await self._bump_snap_ver()
        await self._refresh_snapc()
        await self._snap_fanout()
        return 0, {"snapid": data_snap}

    async def _op_rmsnap(self, args,
                         conn=None) -> Tuple[int, Dict[str, Any]]:
        """Remove a snapshot: drop the table row, then release both
        pool snapids — the OSDs' snap-trim machinery reclaims the
        clones (handle_client_rmsnap + snap trim)."""
        name = args.get("name", "")
        _p, _n, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        self._snap_invalidate()  # adjudicate on fresh table state
        snaps = await self._dir_snaps(inode["ino"])
        rec = snaps.get(name)
        if rec is None:
            return ENOENT, {}
        # release the pool snapids FIRST (tolerating already-gone), so
        # a transient failure leaves the table row in place and a
        # retried rmsnap reaches the remove calls again — dropping the
        # row first would strand the snapids outside removed_snaps and
        # their clones would never trim
        for io, snapid in ((self.data_io, rec["data_snap"]),
                           (self.meta, rec["meta_snap"])):
            try:
                await io.remove_selfmanaged_snap(snapid)
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
        await self.meta.omap_rm_keys(
            SNAPTABLE_OBJ, [f"{rec['data_snap']:016x}"])
        await self._bump_snap_ver()
        self._snap_ios.pop(rec["meta_snap"], None)
        self._snap_dirs = {k: v for k, v in self._snap_dirs.items()
                           if k[1] != rec["meta_snap"]}
        await self._refresh_snapc()
        await self._snap_fanout()
        return 0, {}

    async def _op_lssnap(self, args,
                         conn=None) -> Tuple[int, Dict[str, Any]]:
        _p, _n, inode = await self._resolve(args["path"])
        if inode is None:
            return ENOENT, {}
        snaps = await self._dir_snaps(inode["ino"])
        return 0, {"snaps": [
            {"name": n, "snapid": r["data_snap"],
             "ctime": r.get("ctime", 0)}
            for n, r in sorted(snaps.items())]}

    async def _sweep_pending_snaps(self) -> None:
        """Takeover: a PENDING row for our rank is a crashed mksnap —
        release its pool snapids (removed_snaps -> the OSDs trim any
        clones clients already created against them) and drop the
        row.  Other ranks' pending rows are their own in-flight or
        crashed mksnaps; their successors sweep them."""
        self._snap_invalidate()
        for key, rec in (await self._snap_records()).items():
            if not rec.get("pending") or \
                    rec.get("rank", 0) != self.rank:
                continue
            log.warning("mds.%s: sweeping crashed mksnap %r "
                        "(snapid %s)", self.name, rec.get("name"),
                        rec.get("data_snap"))
            for io, snapid in ((self.data_io, rec["data_snap"]),
                               (self.meta, rec["meta_snap"])):
                try:
                    await io.remove_selfmanaged_snap(snapid)
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
            await self.meta.omap_rm_keys(SNAPTABLE_OBJ, [key])
            await self._bump_snap_ver()
        self._snap_invalidate()
        await self._refresh_snapc()

    async def _op_peer_snap_refresh(self, args, conn=None
                                    ) -> Tuple[int, Dict[str, Any]]:
        """Another rank changed the snap table: re-arm our snap
        contexts (lock-free — pure IoCtx state, no dir mutation)."""
        await self._refresh_snapc()
        return 0, {}

    async def _snap_fanout(self) -> None:
        """Tell every other rank to refresh its snap context.
        Best-effort: a rank that misses it refreshes on takeover, and
        its stale window only shifts the snapshot's point-in-time for
        dirs it owns (same non-linearizable semantics as the
        reference)."""
        for rank in range(self.num_ranks):
            if rank == self.rank:
                continue
            try:
                await self._peer_request(rank, "peer_snap_refresh",
                                         {}, timeout=3.0)
            except Exception:
                log.warning("mds.%s: snap refresh to rank %d failed",
                            self.name, rank)


class MDSError(Exception):
    def __init__(self, rc: int, what: str = ""):
        super().__init__(f"rc={rc} {what}")
        self.rc = rc

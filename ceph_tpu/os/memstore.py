"""MemStore: the in-RAM ObjectStore used by tests and diskless daemons.

Reference parity: /root/reference/src/os/memstore/MemStore.h:30 — same
role: full ObjectStore semantics with no durability, letting OSD logic
run without a device.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List

from ceph_tpu.common.buffer import StridedBuf
from ceph_tpu.os import ObjectId, ObjectStore, Transaction


class _Object:
    """data is a bytearray OR an adopted immutable buffer
    (bytes/memoryview) — the reference MemStore holds refcounted
    bufferlists, sharing the writer's pages zero-copy (MemStore.h
    BufferlistObject); a full-object write here adopts the submitted
    buffer by reference and any later mutating op promotes it to a
    private bytearray first."""

    __slots__ = ("data", "xattrs", "omap", "omap_header")

    def __init__(self) -> None:
        self.data = bytearray()
        self.xattrs: Dict[str, bytes] = {}
        self.omap: Dict[str, bytes] = {}
        self.omap_header = b""

    def mutable(self) -> bytearray:
        if not isinstance(self.data, bytearray):
            self.data = bytearray(
                self.data.tobytes() if isinstance(self.data, StridedBuf)
                else self.data)
        return self.data

    def clone(self) -> "_Object":
        out = _Object()
        if isinstance(self.data, bytearray):
            out.data = bytearray(self.data)
        else:
            # adopted buffers are immutable (MemStore._immutable):
            # share them — the refcounted-bufferlist COW discipline;
            # a later mutating op promotes through mutable()
            out.data = self.data
        out.xattrs = dict(self.xattrs)
        out.omap = dict(self.omap)
        out.omap_header = self.omap_header
        return out


# full-object writes at least this large are adopted by reference
_ADOPT_MIN = 64 * 1024


class MemStore(ObjectStore):
    def __init__(self) -> None:
        self._colls: Dict[str, Dict[ObjectId, _Object]] = {}
        self._lock = threading.RLock()
        self._mounted = False
        # in-RAM stores still carry an identity: the cluster harness
        # asserts a revived OSD remounted the SAME store (fsid match),
        # and MemStore must answer that question too
        self.fsid = ""

    def mkfs(self) -> None:
        import uuid

        self._colls.clear()
        self.fsid = uuid.uuid4().hex

    def mount(self) -> None:
        self._mounted = True

    def umount(self) -> None:
        self._mounted = False

    # -- transaction apply -------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            for op in txn.ops:
                self._apply(op)
        for cb in txn.on_commit:
            cb()

    @staticmethod
    def _immutable(data) -> bool:
        """Only provably-immutable buffers are adopted by reference: a
        WRITABLE view (or a readonly view over a caller-mutable base)
        could change under the recorded crcs after the op returns.
        The base-chain walk lives in common.buffer.is_immutable (the
        reference's bufferlists are refcounted immutable pages — same
        guarantee)."""
        from ceph_tpu.common.buffer import is_immutable

        return is_immutable(data)

    def _obj(self, cid: str, oid: ObjectId, create: bool = False) -> _Object:
        coll = self._colls[cid]
        if oid not in coll:
            if not create:
                raise KeyError(f"{cid}/{oid}")
            coll[oid] = _Object()
        return coll[oid]

    def _apply(self, op) -> None:
        kind = op[0]
        if kind == "mkcoll":
            self._colls.setdefault(op[1], {})
        elif kind == "rmcoll":
            self._colls.pop(op[1], None)
        elif kind == "touch":
            self._obj(op[1], op[2], create=True)
        elif kind == "write":
            _k, cid, oid, offset, data = op
            obj = self._obj(cid, oid, create=True)
            size = len(obj.data)
            if offset == 0 and size == 0:
                if len(data) >= _ADOPT_MIN and self._immutable(data):
                    # adopt by reference (class docstring): zero-copy
                    obj.data = data
                elif len(data) >= _ADOPT_MIN:
                    # writable buffer: the caller may legally reuse it
                    # after the op returns — snapshot
                    obj.data = bytes(data)
                else:
                    obj.data = bytearray(
                        data.tobytes() if isinstance(data, StridedBuf)
                        else data)
                return
            if isinstance(data, StridedBuf):
                data = data.tobytes()
            buf = obj.mutable()
            if offset == size:
                # append fast path: one memcpy, no zero-fill pass
                buf += data
                return
            end = offset + len(data)
            if size < offset:
                buf.extend(b"\0" * (offset - size))
                buf += data
                return
            buf[offset:end] = data
        elif kind == "zero":
            _k, cid, oid, offset, length = op
            obj = self._obj(cid, oid, create=True)
            buf = obj.mutable()
            end = offset + length
            if len(buf) < end:
                buf.extend(b"\0" * (end - len(buf)))
            buf[offset:end] = b"\0" * length
        elif kind == "truncate":
            _k, cid, oid, size = op
            obj = self._obj(cid, oid, create=True)
            if len(obj.data) > size:
                if isinstance(obj.data, bytearray):
                    del obj.data[size:]
                else:
                    obj.data = obj.data[:size]  # zero-copy narrow
            else:
                obj.mutable().extend(b"\0" * (size - len(obj.data)))
        elif kind == "remove":
            self._colls[op[1]].pop(op[2], None)
        elif kind == "clone":
            _k, cid, src, dst = op
            self._colls[cid][dst] = self._obj(cid, src).clone()
        elif kind == "move":
            _k, src_cid, src, dst_cid, dst = op
            obj = self._colls[src_cid].pop(src)
            self._colls.setdefault(dst_cid, {})[dst] = obj
        elif kind == "alloc_hint":
            self._obj(op[1], op[2], create=True)
        elif kind == "setattr":
            self._obj(op[1], op[2], create=True).xattrs[op[3]] = op[4]
        elif kind == "rmattr":
            self._obj(op[1], op[2]).xattrs.pop(op[3], None)
        elif kind == "omap_setkeys":
            self._obj(op[1], op[2], create=True).omap.update(op[3])
        elif kind == "omap_rmkeys":
            obj = self._obj(op[1], op[2])
            for key in op[3]:
                obj.omap.pop(key, None)
        elif kind == "omap_clear":
            self._obj(op[1], op[2]).omap.clear()
        elif kind == "omap_setheader":
            self._obj(op[1], op[2], create=True).omap_header = op[3]
        else:
            raise ValueError(f"unknown transaction op {kind!r}")

    # -- reads -------------------------------------------------------------

    def read(self, cid: str, oid: ObjectId, offset: int = 0,
             length: int = 0) -> bytes:
        with self._lock:
            obj = self._obj(cid, oid)
            if length == 0:
                length = max(len(obj.data) - offset, 0)
            return bytes(obj.data[offset:offset + length])

    def stat(self, cid: str, oid: ObjectId) -> Dict[str, Any]:
        with self._lock:
            obj = self._obj(cid, oid)
            return {"size": len(obj.data)}

    def getattr(self, cid: str, oid: ObjectId, name: str) -> bytes:
        with self._lock:
            return self._obj(cid, oid).xattrs[name]

    def getattrs(self, cid: str, oid: ObjectId) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._obj(cid, oid).xattrs)

    def omap_get(self, cid: str, oid: ObjectId) -> Dict[str, bytes]:
        with self._lock:
            return dict(self._obj(cid, oid).omap)

    def omap_get_header(self, cid: str, oid: ObjectId) -> bytes:
        with self._lock:
            return self._obj(cid, oid).omap_header

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(self._colls)

    def list_objects(self, cid: str) -> List[ObjectId]:
        with self._lock:
            return sorted(self._colls.get(cid, {}), key=str)

    def statfs(self) -> Dict[str, int]:
        with self._lock:
            used = sum(len(o.data) for c in self._colls.values()
                       for o in c.values())
        return {"total": 1 << 40, "available": (1 << 40) - used,
                "allocated": used, "stored": used}

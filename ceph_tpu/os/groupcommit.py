"""Async group-commit front end for ObjectStore transactions.

The BlueStore kv_sync_thread amortization, asyncio-shaped: every
durable transaction on the OSD write path used to pay its own
`_block_sync()` + `submit_transaction_sync` barrier inside
`TPUStore.queue_transaction` — N concurrent writers bought N fsyncs
where one would do (and paid them ON the event loop, stalling every
other task for the fsync's duration).  This layer is the journal-side
twin of `osd/encode_service.py`: concurrent transactions accumulate
in a short window (or until a txn/byte budget fills — whichever
first), then ONE flush ships the whole FIFO batch through
`store.submit_batch` on a dedicated single-worker commit thread (the
literal kv_sync_thread), which merges the KV batches into a single
sync commit and the direct writes into a single block fsync.  Each
caller's `await` resolves only after the shared barrier — the
ack=>durable contract is unchanged per txn, and the merged batch is
a legal CrashLog trace (the PR-8 sweep proves it: the batch rides
the same _pwrite/_block_sync/submit choke points FaultStore
records).  While batch N commits on the worker, batch N+1
accumulates on the loop — the encode service's double-buffer shape.

Ordering: ONE commit lane.  The single worker drains its queue FIFO
(batch N commits before batch N+1 starts), so a later txn staging a
newer PG-log snapshot can never be overwritten by an earlier txn's
older snapshot landing after it.  For the same reason there is NO
shed-to-inline under pressure (the encode service can shed because
encodes are pure; commits are not): a full window flushes
immediately instead.  Sync call sites that must not reorder around
the window (split redistribution, which both reads pgmeta from the
store and stages it) call `flush_sync()` — it pushes the open window
to the worker and JOINS it, putting the whole store at program
order before they read or write.

Knobs (read at construction):

  CEPH_TPU_GROUP_COMMIT_WINDOW_MS  accumulation window, default 0.5
  CEPH_TPU_GROUP_COMMIT_TXNS       flush early at this many pending
                                   txns (default 64)
  CEPH_TPU_GROUP_COMMIT_BYTES     flush early once this many payload
                                   bytes are pending (default 4 MiB)
  CEPH_TPU_GROUP_COMMIT=0          kill switch — every txn takes the
                                   inline (pre-batching) path:
                                   synchronous queue_transaction in
                                   call order, behavior-parity with
                                   the un-batched daemon

Degradation policy: batching only engages when the store actually
amortizes barriers — i.e. it overrides `ObjectStore.submit_batch`
(TPUStore and subclasses).  MemStore-backed daemons take the inline
path unconditionally: their queue_transaction is a dict update, and
a window would add latency for nothing.

Barrier points: `drain()` flushes the window and awaits the worker —
daemon stop()/kill() call it (like the encode service drains) so
shutdown and power-cut harnesses never see a stranded unacked txn
holding an object lock.  `commit_now()` is the async bypass for
scrub/recovery barriers: drain, then commit inline.
"""

from __future__ import annotations

import asyncio
import os

from ceph_tpu.common import flags
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from ceph_tpu.common import tracing
from ceph_tpu.os import ObjectStore, Transaction

__all__ = ["GroupCommitter"]


def _env_float(name: str, default: float) -> float:
    try:
        return flags.flag_float(name, default)
    except ValueError:
        return default


def _pow2_bucket(n: int) -> int:
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def _txn_bytes(txn: Transaction) -> int:
    """Cheap payload estimate for the byte budget: write-op data
    only (metadata ops are noise next to a data shard)."""
    return sum(len(op[4]) for op in txn.ops if op[0] == "write")


class GroupCommitter:
    """FIFO accumulating committer over one ObjectStore."""

    def __init__(self, store: ObjectStore, who: str = "osd",
                 config=None,
                 window_ms: Optional[float] = None,
                 max_batch_txns: Optional[int] = None,
                 max_batch_bytes: Optional[int] = None):
        self.store = store
        self.who = who
        config = config or {}
        self.enabled = (
            flags.enabled("CEPH_TPU_GROUP_COMMIT")
            and bool(config.get("osd_group_commit_enable", True)))
        # engage only where barriers exist to amortize: a store that
        # kept the base (loop-per-txn) submit_batch gains nothing
        # from batching and would only pay the window
        self.engaged = (self.enabled and
                        type(store).submit_batch
                        is not ObjectStore.submit_batch)
        if window_ms is None:
            window_ms = _env_float("CEPH_TPU_GROUP_COMMIT_WINDOW_MS",
                                   0.5)
        self.window_s = max(float(window_ms), 0.0) / 1e3
        self.max_batch_txns = int(
            max_batch_txns if max_batch_txns is not None
            else _env_float("CEPH_TPU_GROUP_COMMIT_TXNS", 64))
        self.max_batch_bytes = int(
            max_batch_bytes if max_batch_bytes is not None
            else _env_float("CEPH_TPU_GROUP_COMMIT_BYTES",
                            float(4 << 20)))
        self._pending: List[Tuple[Transaction, asyncio.Future]] = []
        self._pending_bytes = 0
        self._timer: Optional[asyncio.TimerHandle] = None
        # the commit lane: ONE worker thread, so executor queue order
        # IS commit order, and sync contexts can join it (.result())
        self._worker: Optional[ThreadPoolExecutor] = None
        self._inflight: list = []  # concurrent.futures.Future, FIFO
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._closed = False
        self.counters: Dict[str, int] = {
            "txns": 0, "inline": 0, "batched": 0, "batches": 0,
            "window_flushes": 0, "budget_flushes": 0,
            "drain_flushes": 0, "commit_errors": 0,
        }
        self.txns_per_batch_hist: Dict[str, int] = {}

    # -- public API -------------------------------------------------------

    async def queue_transaction(self, txn: Transaction) -> None:
        """Awaitable twin of store.queue_transaction — identical
        durability contract, but concurrent callers share one commit
        barrier.  Resolves after THIS txn is durable (its on_commit
        callbacks have fired); raises what the apply raised."""
        self.counters["txns"] += 1
        if not self.engaged or self._closed:
            self.counters["inline"] += 1
            self.store.queue_transaction(txn)
            return
        loop = asyncio.get_running_loop()
        self._loop = loop
        fut: asyncio.Future = loop.create_future()
        self._pending.append((txn, fut))
        self._pending_bytes += _txn_bytes(txn)
        self.counters["batched"] += 1
        if (len(self._pending) >= self.max_batch_txns
                or self._pending_bytes >= self.max_batch_bytes):
            self.counters["budget_flushes"] += 1
            self._flush()
        elif self.window_s == 0.0:
            self._flush()
        elif self._timer is None:
            self._timer = loop.call_later(self.window_s,
                                          self._window_fired)
        # accumulation wait + shared barrier, as the op saw it: the
        # store's own kv_commit/fsync spans run on the commit-lane
        # thread (no task context), so THIS span is where the op's
        # journal cost attributes in the stage histograms
        wait_span = tracing.start_child("kv_commit_wait")
        try:
            await fut
        except asyncio.CancelledError:
            wait_span.set_attr("cancelled", True)
            raise
        finally:
            wait_span.finish()

    def flush_sync(self) -> None:
        """Synchronous total-order barrier for sync call sites (split
        redistribution): push the open window to the commit lane and
        JOIN the lane.  On return every txn queued before this call
        is durable and the store reads at program order.  Blocks the
        calling thread for at most the in-flight commits' barriers —
        exactly what the pre-batching code paid inline per txn."""
        if self._pending:
            self.counters["drain_flushes"] += 1
            self._flush()
        for cf in list(self._inflight):
            try:
                cf.result()
            except Exception:
                pass  # the owning future carries the error
        self._inflight = [f for f in self._inflight if not f.done()]

    async def drain(self) -> None:
        """Flush the open window and await the commit lane: after
        this, nothing queued before the call is un-committed.  The
        stop()/kill() barrier (and the scrub/recovery bypass)."""
        if self._pending:
            self.counters["drain_flushes"] += 1
            self._flush()
        for cf in list(self._inflight):
            try:
                await asyncio.wrap_future(cf)
            except Exception:
                pass  # per-txn futures carry their own errors
        self._inflight = [f for f in self._inflight if not f.done()]

    async def commit_now(self, txn: Transaction) -> None:
        """Barrier-point bypass: drain the lane (nothing may reorder
        around this txn), then commit inline."""
        if self.engaged and not self._closed:
            await self.drain()
        self.counters["txns"] += 1
        self.counters["inline"] += 1
        self.store.queue_transaction(txn)

    async def stop(self) -> None:
        """Drain and latch closed; txns arriving after stop() run
        inline (teardown must not strand a caller on a future no
        flush will resolve).  The commit-lane thread is joined and
        released — a restarting daemon builds a fresh committer, so
        a stopped one must not leak its worker."""
        self._closed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        await self.drain()
        if self._worker is not None:
            await asyncio.to_thread(self._worker.shutdown, True)
            self._worker = None

    def stats(self) -> dict:
        avg = (self.counters["batched"]
               / max(self.counters["batches"], 1))
        return {
            "enabled": self.enabled,
            "engaged": self.engaged,
            **self.counters,
            "txns_per_batch_hist": dict(self.txns_per_batch_hist),
            "txns_per_batch_avg": round(avg, 2),
            "pending": len(self._pending),
            "window_ms": self.window_s * 1e3,
            "max_batch_txns": self.max_batch_txns,
            "max_batch_bytes": self.max_batch_bytes,
        }

    # -- internals --------------------------------------------------------

    def _window_fired(self) -> None:
        self._timer = None
        if self._pending:
            self.counters["window_flushes"] += 1
            self._flush()

    def _flush(self) -> None:
        """Hand the accumulated batch to the commit lane (loop
        thread only)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        self._pending_bytes = 0
        if self._worker is None:
            self._worker = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f"{self.who}-kv-sync")
        # prune settled lanes loop-side only (the worker never touches
        # this list, so no cross-thread mutation race)
        self._inflight = [f for f in self._inflight if not f.done()]
        cf = self._worker.submit(self._commit_batch, batch, self._loop)
        self._inflight.append(cf)

    def _commit_batch(self, batch, loop) -> None:
        """Worker-thread batch body: one commit unit for the whole
        batch; per-txn outcomes fan back out to the loop."""
        txns = [t for t, _f in batch]
        try:
            results = self.store.submit_batch(txns)
        except BaseException as e:  # store seam itself died
            results = [e] * len(batch)
        self.counters["batches"] += 1
        key = str(_pow2_bucket(len(batch)))
        self.txns_per_batch_hist[key] = \
            self.txns_per_batch_hist.get(key, 0) + 1
        try:
            if loop is not None:
                loop.call_soon_threadsafe(self._resolve, batch,
                                          results)
        except RuntimeError:
            pass  # loop gone (teardown): callers are gone too

    def _resolve(self, batch, results) -> None:
        for (_t, fut), res in zip(batch, results):
            if fut.done():
                continue  # caller cancelled; the txn still committed
            if isinstance(res, BaseException):
                self.counters["commit_errors"] += 1
                fut.set_exception(res)
            else:
                fut.set_result(None)

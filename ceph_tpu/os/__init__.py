"""ObjectStore: the local storage engine abstraction.

Reference parity: ObjectStore + Transaction
(/root/reference/src/os/ObjectStore.h, src/os/Transaction.h): compound
transactions of object mutations (touch/write/zero/truncate/remove/clone,
xattrs, omap, alloc hints) applied atomically to collections of objects.
Backends: MemStore (RAM, tests — src/os/memstore/) and TPUStore (the
BlueStore-role engine: raw block file + allocator + KV metadata + inline
compression/checksums — src/os/bluestore/).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# alloc hints (ObjectStore.h CEPH_OSD_ALLOC_HINT_FLAG_*)
ALLOC_HINT_SEQUENTIAL_WRITE = 1
ALLOC_HINT_RANDOM_WRITE = 2
ALLOC_HINT_COMPRESSIBLE = 32
ALLOC_HINT_INCOMPRESSIBLE = 64


@dataclass(frozen=True)
class ObjectId:
    """ghobject-lite: (name, snap); collections scope the pool/pg."""

    name: str
    snap: int = -2  # CEPH_NOSNAP

    def __str__(self) -> str:
        return self.name if self.snap == -2 else f"{self.name}@{self.snap}"


class Transaction:
    """Ordered op list; applied atomically by queue_transaction."""

    def __init__(self) -> None:
        self.ops: List[Tuple] = []
        self.on_commit: List[Callable[[], None]] = []

    # -- collection ops ---------------------------------------------------

    def create_collection(self, cid: str) -> None:
        self.ops.append(("mkcoll", cid))

    def remove_collection(self, cid: str) -> None:
        self.ops.append(("rmcoll", cid))

    # -- object data ops --------------------------------------------------

    def touch(self, cid: str, oid: ObjectId) -> None:
        self.ops.append(("touch", cid, oid))

    def write(self, cid: str, oid: ObjectId, offset: int,
              length: int, data: bytes) -> None:
        """Buffers are CLAIMED, not copied (the reference Transaction
        holds bufferlist refs, src/os/Transaction.h — writers never
        mutate a buffer after queueing it); anything not PROVABLY
        immutable (common.buffer.is_immutable walks the base chain —
        a readonly view over a caller-mutable bytearray is still
        caller-mutable) is snapshotted."""
        assert length == len(data)
        from ceph_tpu.common.buffer import is_immutable

        if not is_immutable(data):
            data = bytes(data)
        self.ops.append(("write", cid, oid, offset, data))

    def zero(self, cid: str, oid: ObjectId, offset: int,
             length: int) -> None:
        self.ops.append(("zero", cid, oid, offset, length))

    def truncate(self, cid: str, oid: ObjectId, size: int) -> None:
        self.ops.append(("truncate", cid, oid, size))

    def remove(self, cid: str, oid: ObjectId) -> None:
        self.ops.append(("remove", cid, oid))

    def clone(self, cid: str, src: ObjectId, dst: ObjectId) -> None:
        self.ops.append(("clone", cid, src, dst))

    def collection_move_rename(self, src_cid: str, src: ObjectId,
                               dst_cid: str, dst: ObjectId) -> None:
        self.ops.append(("move", src_cid, src, dst_cid, dst))

    def set_alloc_hint(self, cid: str, oid: ObjectId,
                       expected_object_size: int,
                       expected_write_size: int, flags: int) -> None:
        self.ops.append(("alloc_hint", cid, oid, expected_object_size,
                         expected_write_size, flags))

    # -- xattrs -----------------------------------------------------------

    def setattr(self, cid: str, oid: ObjectId, name: str,
                value: bytes) -> None:
        self.ops.append(("setattr", cid, oid, name, bytes(value)))

    def setattrs(self, cid: str, oid: ObjectId,
                 attrs: Dict[str, bytes]) -> None:
        for name, value in attrs.items():
            self.setattr(cid, oid, name, value)

    def rmattr(self, cid: str, oid: ObjectId, name: str) -> None:
        self.ops.append(("rmattr", cid, oid, name))

    # -- omap -------------------------------------------------------------

    def omap_setkeys(self, cid: str, oid: ObjectId,
                     keys: Dict[str, bytes]) -> None:
        self.ops.append(("omap_setkeys", cid, oid,
                         {k: bytes(v) for k, v in keys.items()}))

    def omap_rmkeys(self, cid: str, oid: ObjectId,
                    keys: List[str]) -> None:
        self.ops.append(("omap_rmkeys", cid, oid, list(keys)))

    def omap_clear(self, cid: str, oid: ObjectId) -> None:
        self.ops.append(("omap_clear", cid, oid))

    def omap_setheader(self, cid: str, oid: ObjectId,
                       header: bytes) -> None:
        self.ops.append(("omap_setheader", cid, oid, bytes(header)))

    def register_on_commit(self, cb: Callable[[], None]) -> None:
        self.on_commit.append(cb)

    def append(self, other: "Transaction") -> None:
        self.ops.extend(other.ops)
        self.on_commit.extend(other.on_commit)

    def empty(self) -> bool:
        return not self.ops


class ObjectStore:
    """The transactional store interface (ObjectStore.h)."""

    def mount(self) -> None:
        raise NotImplementedError

    def umount(self) -> None:
        raise NotImplementedError

    def mkfs(self) -> None:
        raise NotImplementedError

    def queue_transaction(self, txn: Transaction) -> None:
        """Apply atomically; run on_commit callbacks after durability."""
        raise NotImplementedError

    def submit_batch(self, txns: List[Transaction]
                     ) -> List[Optional[Exception]]:
        """Group commit: apply a FIFO batch of transactions, sharing
        durability barriers where the engine can (TPUStore merges the
        KV batches into ONE sync commit and the direct writes into ONE
        block fsync).  Per-txn outcome list: None = committed (its
        on_commit callbacks have fired), an Exception = that txn
        failed and nothing of it was applied.  The base implementation
        is the semantic reference: each txn commits individually, in
        order — engines may amortize barriers but must not change
        which states are durable-visible at each ack."""
        results: List[Optional[Exception]] = []
        for txn in txns:
            try:
                self.queue_transaction(txn)
                results.append(None)
            except Exception as e:
                results.append(e)
        return results

    # -- reads ------------------------------------------------------------

    def read(self, cid: str, oid: ObjectId, offset: int = 0,
             length: int = 0) -> bytes:
        """length 0 = to end of object.  Raises KeyError if absent."""
        raise NotImplementedError

    def stat(self, cid: str, oid: ObjectId) -> Dict[str, Any]:
        raise NotImplementedError

    def exists(self, cid: str, oid: ObjectId) -> bool:
        try:
            self.stat(cid, oid)
            return True
        except KeyError:
            return False

    def getattr(self, cid: str, oid: ObjectId, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid: str, oid: ObjectId) -> Dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: ObjectId) -> Dict[str, bytes]:
        raise NotImplementedError

    def omap_get_header(self, cid: str, oid: ObjectId) -> bytes:
        raise NotImplementedError

    def list_collections(self) -> List[str]:
        raise NotImplementedError

    def collection_exists(self, cid: str) -> bool:
        return cid in self.list_collections()

    def list_objects(self, cid: str) -> List[ObjectId]:
        raise NotImplementedError

    def statfs(self) -> Dict[str, int]:
        raise NotImplementedError

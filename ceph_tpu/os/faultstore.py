"""Crash-consistency fault injection for TPUStore.

The CrashMonkey/ALICE shape (systematic crash-point exploration,
persistence-ordering checking) on this substrate: a recording shim
under TPUStore's block file and KV logs every write, fsync barrier and
KV batch; from that trace every LEGAL post-crash disk image is
synthesized mechanically — prefix cuts at each event, un-synced block
writes dropped in subsets (the reorder approximation), the last
pending write torn mid-sector — and each image is remounted and
checked against the workload's model:

- mount always succeeds (no schedule may brick the store);
- the observable state equals the model at EXACTLY the last durable
  KV commit — in particular every transaction whose `on_commit` fired
  before the cut is fully visible (acked implies durable);
- journal replay is idempotent, including a second power cut DURING
  replay (the double-crash schedule re-cuts the replay's own writes);
- every read verifies clean (per-blob crc32c — lost un-synced bytes
  under a committed onode surface as csum failures, never as silent
  garbage);
- the freelist and the blob map agree: no extent is both free and
  referenced, no two blobs overlap.

Durability model (what "legal" means here):
- block pwrites are volatile until the next fsync barrier; writes
  after the last barrier may individually persist, vanish or tear;
- KV batches are atomic (the SQLite guarantee) and PREFIX-durable:
  a sync batch (`submit_transaction_sync`) is a barrier; non-sync
  batches after the last barrier may be lost, but only from the tail.

`BrokenBlockStore` / `BrokenCommitStore` are deliberately-broken
subclasses (pre-commit fsync removed / commit point demoted to a
non-sync batch) used as harness self-tests: the same sweep MUST catch
them.

Kill switch: CEPH_TPU_CRASH_INJECT=0 disables power-cut synthesis in
cluster harnesses (kill_osd degrades to a plain process-crash close,
which loses nothing the process handed to the OS).
"""

from __future__ import annotations

import hashlib
import os as _os

from ceph_tpu.common import flags
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple

from ceph_tpu.kv import KeyValueDB, SQLiteDB, Transaction as KVTransaction
from ceph_tpu.os import ObjectId, ObjectStore, Transaction
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.os.tpustore import TPUStore

SECTOR = 512  # torn-write granularity (partial-sector tears cut inside)
KV_PREFIXES = ("S", "O", "M", "F", "D")

# event kinds in the recorded trace
EV_WRITE = "write"    # (offset, bytes)
EV_SYNC = "sync"      # block fsync barrier
EV_KV = "kv"          # (ops, sync_flag)
EV_MARK = "mark"      # (label,) — ack/txn markers ride the trace


def crash_inject_enabled() -> bool:
    return flags.enabled("CEPH_TPU_CRASH_INJECT")


class CrashLog:
    """The recorded persistence trace: every block write, fsync
    barrier and KV batch, in program order."""

    def __init__(self) -> None:
        self.events: List[Tuple] = []

    def block_write(self, offset: int, data: bytes) -> None:
        self.events.append((EV_WRITE, offset, bytes(data)))

    def block_sync(self) -> None:
        self.events.append((EV_SYNC,))

    def kv_commit(self, ops: List[Tuple], sync: bool) -> None:
        self.events.append((EV_KV, list(ops), sync))

    def mark(self, label) -> None:
        self.events.append((EV_MARK, label))

    def __len__(self) -> int:
        return len(self.events)


class RecordingKV(KeyValueDB):
    """Pass-through KV wrapper that records each batch into the
    CrashLog before handing it to the real backend.  `on_commit_event`
    lets the owning store compact its trace on KV-only workloads
    (omap/pg-log traffic produces no block writes, so the block-side
    hooks alone would never fire)."""

    def __init__(self, inner: KeyValueDB, log: CrashLog,
                 on_commit_event=None) -> None:
        self._inner = inner
        self._log = log
        self._on_commit_event = on_commit_event

    def create_and_open(self) -> None:
        self._inner.create_and_open()

    def close(self) -> None:
        self._inner.close()

    def get_transaction(self) -> KVTransaction:
        return self._inner.get_transaction()

    def submit_transaction(self, t: KVTransaction) -> None:
        self._log.kv_commit(t.ops, sync=False)
        self._inner.submit_transaction(t)
        if self._on_commit_event is not None:
            self._on_commit_event()

    def submit_transaction_sync(self, t: KVTransaction) -> None:
        self._log.kv_commit(t.ops, sync=True)
        self._inner.submit_transaction_sync(t)
        if self._on_commit_event is not None:
            self._on_commit_event()

    def get(self, prefix: str, key: bytes):
        return self._inner.get(prefix, key)

    def get_iterator(self, prefix: str, start: bytes = b"",
                     end: Optional[bytes] = None):
        return self._inner.get_iterator(prefix, start, end)


def _dump_kv(kv: KeyValueDB) -> List[Tuple[str, bytes, bytes]]:
    out: List[Tuple[str, bytes, bytes]] = []
    for prefix in KV_PREFIXES:
        for key, value in kv.get_iterator(prefix):
            out.append((prefix, bytes(key), bytes(value or b"")))
    return out


class FaultStore(TPUStore):
    """TPUStore with the recording shim armed: identical behavior, but
    every persistence primitive lands in `self.crashlog` so post-crash
    images can be synthesized from the trace.  The trace covers THIS
    session only; `mount` captures the pre-existing on-disk state as
    the base image synthesis overlays."""

    def __init__(self, path: str, config=None,
                 crashlog: Optional[CrashLog] = None):
        super().__init__(path, config)
        self.crashlog = crashlog if crashlog is not None else CrashLog()
        self._kv = RecordingKV(self._kv, self.crashlog,
                               on_commit_event=self._maybe_compact)
        self.base_block: bytes = b""
        self.base_kv: List[Tuple[str, bytes, bytes]] = []
        # long-lived stores (persistent clusters) fold the durable
        # trace prefix into the base image so RAM stays bounded in
        # events-since-last-barrier, not bytes-ever-written.  The
        # sweep disables this: it needs the whole trace.
        self.trace_compact_threshold: Optional[int] = 4096

    def mount(self) -> None:
        self.capture_base()
        super().mount()

    def capture_base(self) -> None:
        """Snapshot the current on-disk state as the synthesis base
        and restart the trace — everything already down here is, by
        definition, durable."""
        self.base_block = b""
        if _os.path.exists(self._block_path):
            with open(self._block_path, "rb") as f:
                self.base_block = f.read()
        self.base_kv = []
        meta = _os.path.join(self.path, "meta.db")
        if _os.path.exists(meta):
            kv = SQLiteDB(meta)
            kv.create_and_open()
            self.base_kv = _dump_kv(kv)
            kv.close()
        self.crashlog.events.clear()

    def _pwrite(self, offset: int, data: bytes) -> None:
        self.crashlog.block_write(offset, data)
        super()._pwrite(offset, data)
        self._maybe_compact()

    def _block_sync(self) -> None:
        self.crashlog.block_sync()
        super()._block_sync()
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        if self.trace_compact_threshold is not None and \
                len(self.crashlog.events) >= \
                self.trace_compact_threshold:
            self.compact_trace()

    def compact_trace(self) -> None:
        """Fold the durable prefix of the trace into the base image.
        The fold extends to the last sync KV batch but may not cross
        an un-synced block write (one after the last fsync barrier) —
        everything folded survives every legal crash, so synthesis
        from (new base, remaining tail) is byte-identical.  A KV-only
        prefix (omap/pg-log traffic, no block writes) folds on its
        sync batches alone.  Ack marks inside the fold are dropped
        (they refer to txns that are now unconditionally durable)."""
        events = self.crashlog.events
        last_sync = -1
        last_kv_sync = -1
        for i, ev in enumerate(events):
            if ev[0] == EV_SYNC:
                last_sync = i
            elif ev[0] == EV_KV and ev[2]:
                last_kv_sync = i
        fold = last_kv_sync + 1
        for i, ev in enumerate(events[:fold]):
            if ev[0] == EV_WRITE and i > last_sync:
                fold = i  # un-synced write: everything after stays
                break
        if fold <= 0:
            return
        prefix = events[:fold]
        self.base_block = _apply_writes(
            self.base_block,
            [(ev[1], ev[2]) for ev in prefix if ev[0] == EV_WRITE])
        kv: Dict[Tuple[str, bytes], bytes] = {
            (p, k): v for p, k, v in self.base_kv}
        for ev in prefix:
            if ev[0] != EV_KV:
                continue
            for op, p, k, v in ev[1]:
                if op == "set":
                    kv[(p, k)] = v
                elif op == "rm":
                    kv.pop((p, k), None)
                elif op == "rm_prefix":
                    for pk in [pk for pk in kv if pk[0] == p]:
                        del kv[pk]
                elif op == "rm_range":
                    for pk in [pk for pk in kv
                               if pk[0] == p and k <= pk[1] < v]:
                        del kv[pk]
        self.base_kv = sorted(
            (p, k, v) for (p, k), v in kv.items())
        del events[:fold]

    # -- scripted bit-rot --------------------------------------------------

    def inject_bitrot(self, cid: str, oid: ObjectId, span: int = 0,
                      byte: int = 0, mask: int = 0x40) -> int:
        """Flip one byte inside a stored blob (silent media corruption
        — the csum layer, not the journal, must catch this on read).
        Returns the corrupted device offset."""
        onode = self._get_onode(cid, oid)
        blob = onode.blobs[span]
        cur = self._pread(blob.offset + byte, 1)
        # bypass the recorder: bit-rot is not a legal write and must
        # not look like one in the trace
        TPUStore._pwrite(self, blob.offset + byte,
                         bytes([cur[0] ^ mask]))
        self._block.flush()
        return blob.offset + byte

    # -- power-cut crash ---------------------------------------------------

    def crash_powercut(self) -> None:
        """Simulate a POWER CUT (not just a process crash): close the
        handles without flushing, then rewrite the directory to the
        minimal legal post-crash image — un-synced block writes
        dropped, KV cut at the last sync batch.  A subsequent
        TPUStore(path).mount() sees exactly what a machine that lost
        power would."""
        events = list(self.crashlog.events)
        base_block, base_kv = self.base_block, list(self.base_kv)
        self.crash()
        block, ops = build_image(events, len(events), drop_pending=True,
                                 kv_keep="min", base_block=base_block)
        write_image(self.path, block, ops, base_kv=base_kv)


class BrokenBlockStore(FaultStore):
    """Harness SELF-TEST seam: the pre-commit block fsync is removed
    (the barrier neither happens nor is recorded), so direct writes
    stay forever un-synced — the exact bug class the sweep exists to
    catch.  Never mount this outside the self-test."""

    def _block_sync(self) -> None:  # no barrier, no record
        pass


class BrokenCommitStore(FaultStore):
    """Self-test twin: the commit point is demoted to a non-sync KV
    batch, so an acked transaction can vanish in a power cut — the
    sweep must flag the lost ack."""

    def __init__(self, path: str, config=None,
                 crashlog: Optional[CrashLog] = None):
        super().__init__(path, config, crashlog)

        class _Demote(RecordingKV):
            def submit_transaction_sync(self, t):
                self.submit_transaction(t)

        self._kv = _Demote(self._kv._inner, self.crashlog,
                           on_commit_event=self._maybe_compact)


# -- post-crash image synthesis --------------------------------------------


def durable_kv_prefix(events: List[Tuple], cut: int,
                      kv_keep: str = "min") -> List[List[Tuple]]:
    """KV batches surviving a crash after events[:cut].  `min` keeps
    batches up to the last SYNC batch (power cut loses the un-synced
    tail); `max` keeps every batch before the cut (they MAY survive —
    but always as a prefix, the WAL append order)."""
    batches: List[Tuple[List[Tuple], bool]] = [
        (ev[1], ev[2]) for ev in events[:cut] if ev[0] == EV_KV]
    if kv_keep == "max":
        return [ops for ops, _s in batches]
    last_sync = -1
    for n, (_ops, sync) in enumerate(batches):
        if sync:
            last_sync = n
    return [ops for ops, _s in batches[:last_sync + 1]]


def _apply_writes(base: bytes,
                  writes: List[Tuple[int, bytes]]) -> bytes:
    """Overlay (offset, data) writes onto a base block image, growing
    it as needed — the ONE write-apply semantics shared by crash
    synthesis and trace compaction (whose contract is that folding
    must be byte-identical to synthesizing from the full trace)."""
    size = len(base)
    for off, data in writes:
        size = max(size, off + len(data))
    buf = bytearray(size)
    buf[:len(base)] = base
    for off, data in writes:
        buf[off:off + len(data)] = data
    return bytes(buf)


def synthesize_block(events: List[Tuple], cut: int,
                     drop: frozenset = frozenset(),
                     drop_pending: bool = False,
                     torn: Optional[Tuple[int, int]] = None,
                     base_block: bytes = b"") -> bytes:
    """The block file a crash after events[:cut] could leave.  Writes
    before the last fsync barrier are durable in order; writes after
    it are pending — `drop` removes chosen ones (indices into events),
    `drop_pending` removes them all, `torn=(idx, keep)` applies only
    the first `keep` bytes of one pending write."""
    last_sync = -1
    for i, ev in enumerate(events[:cut]):
        if ev[0] == EV_SYNC:
            last_sync = i
    writes: List[Tuple[int, bytes]] = []
    for i, ev in enumerate(events[:cut]):
        if ev[0] != EV_WRITE:
            continue
        _k, off, data = ev
        if i > last_sync:
            if drop_pending or i in drop:
                continue
            if torn is not None and torn[0] == i:
                data = data[:torn[1]]
        writes.append((off, data))
    return _apply_writes(base_block, writes)


def build_image(events: List[Tuple], cut: int, *,
                drop: frozenset = frozenset(),
                drop_pending: bool = False,
                torn: Optional[Tuple[int, int]] = None,
                kv_keep: str = "min",
                base_block: bytes = b"",
                ) -> Tuple[bytes, List[List[Tuple]]]:
    """(block bytes, durable KV batches) for one crash schedule."""
    block = synthesize_block(events, cut, drop=drop,
                             drop_pending=drop_pending, torn=torn,
                             base_block=base_block)
    return block, durable_kv_prefix(events, cut, kv_keep)


def write_image(path: str, block: bytes,
                kv_batches: List[List[Tuple]],
                base_kv: Optional[List[Tuple[str, bytes, bytes]]] = None,
                ) -> None:
    """Write a synthesized post-crash image into `path` (replacing
    whatever is there): block file + a fresh KV seeded from `base_kv`
    with the durable batch prefix applied on top."""
    if _os.path.exists(path):
        shutil.rmtree(path)
    _os.makedirs(path)
    with open(_os.path.join(path, "block"), "wb") as f:
        f.write(block)
    kv = SQLiteDB(_os.path.join(path, "meta.db"))
    kv.create_and_open()
    # batches apply in order; concatenating into one sqlite commit is
    # equivalent (ops are order-preserving) and far cheaper per image
    merged = kv.get_transaction()
    for prefix, key, value in (base_kv or []):
        merged.set(prefix, key, value)
    for ops in kv_batches:
        merged.ops.extend(ops)
    kv.submit_transaction(merged)
    kv.close()


def image_digest(block: bytes, kv_batches: List[List[Tuple]],
                 ) -> bytes:
    """Cheap identity of a synthesized image (dedupe remount checks
    for schedules that collapse to the same disk state)."""
    h = hashlib.sha256()
    h.update(block)
    for ops in kv_batches:
        for op in ops:
            h.update(repr(op).encode())
    return h.digest()


# -- model + invariants ----------------------------------------------------


def snapshot_store(store: ObjectStore) -> Dict[str, Dict[str, Tuple]]:
    """Canonical observable state of a mounted store: every object's
    bytes, xattrs, omap and header across every collection.  IOError
    (csum failure) propagates — a checksum violation IS a sweep
    violation."""
    out: Dict[str, Dict[str, Tuple]] = {}
    for cid in store.list_collections():
        objs: Dict[str, Tuple] = {}
        for oid in store.list_objects(cid):
            objs[str(oid)] = (
                store.read(cid, oid),
                dict(store.getattrs(cid, oid)),
                dict(store.omap_get(cid, oid)),
                store.omap_get_header(cid, oid),
            )
        out[cid] = objs
    return out


def check_alloc_consistency(store: TPUStore) -> None:
    """Freelist/blob-map agreement: no device extent may be both free
    and referenced by a committed onode, and no two blobs overlap."""
    from ceph_tpu.os.tpustore import P_ONODE, _Onode

    free = sorted(store._alloc.free)
    blobs: List[Tuple[int, int, str]] = []
    for key, raw in store._kv.get_iterator(P_ONODE):
        onode = _Onode.from_bytes(raw)
        for span, blob in onode.blobs.items():
            if blob.stored_len:
                blobs.append((blob.offset, blob.stored_len,
                              f"{key!r}:{span}"))
    blobs.sort()
    for (o1, l1, w1), (o2, l2, w2) in zip(blobs, blobs[1:]):
        if o2 < o1 + l1:
            raise AssertionError(
                f"blob overlap: {w1}@{o1}+{l1} vs {w2}@{o2}+{l2}")
    for off, length, who in blobs:
        for f_off, f_len in free:
            if off < f_off + f_len and f_off < off + length:
                raise AssertionError(
                    f"extent both free and referenced: {who}@{off}"
                    f"+{length} overlaps free ({f_off},{f_len})")


class Violation(Exception):
    """One crash schedule broke an invariant."""


class CrashSweep:
    """Run a workload on a recording store, then explore every crash
    point: synthesize each legal post-crash image, remount, check the
    invariants.  `store_cls` swaps in a deliberately broken store for
    the harness self-test."""

    def __init__(self, workdir: str,
                 store_cls: Callable[..., FaultStore] = FaultStore,
                 config=None):
        self.workdir = str(workdir)
        self.store_cls = store_cls
        self.config = config
        self.events: List[Tuple] = []
        # model snapshots: snapshots[i] = observable state after txn i
        # (snapshots[0] = post-setup state)
        self.snapshots: List[Dict] = []
        # cumulative txn count at each sync commit boundary: with
        # group commit (record(batch=K)) one sync covers K txns, so
        # the durable ceiling at sync j is _sync_txns[j-1], not j
        self._sync_txns: List[int] = []
        self.base_block: bytes = b""
        self.base_kv: List[Tuple[str, bytes, bytes]] = []

    # -- recording run -----------------------------------------------------

    def record(self, workload: Optional[Callable] = None,
               txns: int = 24, seed: int = 0,
               batch: int = 1) -> None:
        """Run the workload once on a recording store and a MemStore
        model in lockstep, keeping the trace and per-txn model
        snapshots.  Recording starts after setup (mkfs + collection),
        whose durable state becomes the synthesis base.

        batch > 1 records through the GROUP-COMMIT path: every K txns
        ride ONE store.submit_batch (one sync commit, shared fsync,
        per-txn acks after the shared barrier) — the merged batch must
        still be a legal trace, txns cut mid-window must vanish
        WHOLESALE (none acked), and acked txns must never vanish.
        The model still applies per txn, so snapshots stay per-txn
        and _sync_txns maps each sync commit to the txn count it made
        durable."""
        live_dir = _os.path.join(self.workdir, "live")
        if _os.path.exists(live_dir):
            shutil.rmtree(live_dir)
        store = self.store_cls(live_dir, config=self.config)
        store.trace_compact_threshold = None  # the sweep IS the trace
        store.mkfs()
        store.mount()
        model = MemStore()
        model.mkfs()
        model.mount()
        for target in (store, model):
            t = Transaction()
            t.create_collection("cc")
            target.queue_transaction(t)
        # base image: what is durably down before the workload starts
        # (the setup commits are sync; the block file is still empty)
        with open(store._block_path, "rb") as f:
            self.base_block = f.read()
        self.base_kv = _dump_kv(store._kv)
        store.crashlog.events.clear()
        self.snapshots = [snapshot_store(model)]
        self._sync_txns = []
        batch = max(int(batch), 1)
        window: List[Transaction] = []
        work = list((workload or default_workload)(txns, seed))
        for i, txn in enumerate(work):
            txn.register_on_commit(
                lambda i=i: store.crashlog.mark(("ack", i + 1)))
            mtxn = Transaction()
            mtxn.ops = list(txn.ops)
            model.queue_transaction(mtxn)
            window.append(txn)
            if len(window) >= batch or i == len(work) - 1:
                if len(window) == 1:
                    store.queue_transaction(window[0])
                else:
                    errs = [e for e in store.submit_batch(window) if e]
                    if errs:
                        raise errs[0]
                self._sync_txns.append(i + 1)
                window = []
            self.snapshots.append(snapshot_store(model))
        self.events = list(store.crashlog.events)
        store.umount()
        model.umount()

    # -- exploration -------------------------------------------------------

    def _schedules(self, cut: int, torn: bool = True):
        """Legal crash schedules at one cut: all-pending-lost,
        all-pending-applied, each single pending write dropped
        (reorder approximation, capped), and the last pending write
        torn mid-sector."""
        pending: List[int] = []
        last_sync = -1
        for i, ev in enumerate(self.events[:cut]):
            if ev[0] == EV_SYNC:
                last_sync = i
        for i, ev in enumerate(self.events[:cut]):
            if ev[0] == EV_WRITE and i > last_sync:
                pending.append(i)
        yield {"drop_pending": True}
        if pending:
            yield {}
            for i in pending[:3]:
                yield {"drop": frozenset([i])}
            if torn:
                last = pending[-1]
                data = self.events[last][2]
                if len(data) > 1:
                    keep = (len(data) // SECTOR) * SECTOR
                    if keep in (0, len(data)):
                        keep = max(1, len(data) // 2)  # mid-sector tear
                    yield {"torn": (last, keep)}

    def _legal_window(self, cut: int) -> Tuple[int, int]:
        """(ack floor, durable commit ceiling) in txn numbers for a
        power cut after events[:cut]."""
        floor = ceiling = 0
        syncs = 0
        for ev in self.events[:cut]:
            if ev[0] == EV_KV and ev[2]:
                syncs += 1
                # one sync commit may cover a whole group-commit
                # batch: the ceiling is the txn count that sync made
                # durable (identity when recorded un-batched)
                ceiling = self._sync_txns[syncs - 1] \
                    if syncs <= len(self._sync_txns) else syncs
            elif ev[0] == EV_MARK and isinstance(ev[1], tuple) \
                    and ev[1][0] == "ack":
                floor = max(floor, ev[1][1])
        return floor, ceiling

    def check_image(self, img: str, cut: int) -> None:
        """Mount the synthesized image and check every invariant."""
        floor, ceiling = self._legal_window(cut)
        if floor > ceiling:
            raise Violation(
                f"acked txn {floor} not durable at cut {cut} "
                f"(durable ceiling {ceiling})")
        store = TPUStore(img, config=self.config)
        try:
            store.mount()  # invariant: mount always succeeds
        except Exception as e:
            raise Violation(f"mount failed at cut {cut}: {e!r}")
        try:
            try:
                state = snapshot_store(store)
            except IOError as e:
                raise Violation(
                    f"csum failure at cut {cut} (floor {floor}): {e}")
            # the durable KV prefix pins the state exactly: the
            # observable store is a function of (KV prefix, journal),
            # and every referenced byte is either synced or journaled
            if ceiling >= len(self.snapshots) or \
                    state != self.snapshots[ceiling]:
                raise Violation(
                    f"state at cut {cut} is not the model at txn "
                    f"{ceiling} (acked floor {floor})")
            try:
                check_alloc_consistency(store)
            except AssertionError as e:
                raise Violation(f"alloc at cut {cut}: {e}")
        finally:
            store.umount()

    def _double_crash(self, img: str, cut: int) -> int:
        """Re-crash DURING the first remount's journal replay: record
        the replay's own writes, cut them again at every point, and
        require the SECOND remount to still satisfy the invariants.
        Returns the number of inner crash points checked."""
        store = FaultStore(img, config=self.config)
        try:
            store.mount()  # replay runs here, recorded
        except Exception as e:
            raise Violation(f"replay mount failed at cut {cut}: {e!r}")
        replay_events = list(store.crashlog.events)
        replay_base_block = store.base_block
        replay_base_kv = store.base_kv
        store.crash()
        if not replay_events:
            return 0
        points = 0
        img2 = _os.path.join(self.workdir, "img2")
        for inner in range(1, len(replay_events) + 1):
            block, ops = build_image(
                replay_events, inner, drop_pending=True, kv_keep="min",
                base_block=replay_base_block)
            write_image(img2, block, ops, base_kv=replay_base_kv)
            self.check_image(img2, cut)
            points += 1
        return points

    def run(self, workload: Optional[Callable] = None,
            txns: int = 24, seed: int = 0,
            max_points: Optional[int] = None,
            stride: int = 1, torn: bool = True,
            double_crash: bool = True,
            batch: int = 1) -> Dict[str, Any]:
        """The sweep: record, then explore.  `stride`/`max_points`
        bound smoke runs (tier-1 sizes via CEPH_TPU_CRASH_SWEEP_*);
        batch > 1 records through submit_batch (group commit armed);
        returns {points, violations, double_crash_points, ...}."""
        self.record(workload=workload, txns=txns, seed=seed,
                    batch=batch)
        img = _os.path.join(self.workdir, "img")
        points = 0
        dc_points = 0
        violations: List[str] = []
        seen: set = set()
        cuts = list(range(1, len(self.events) + 1, max(1, stride)))
        if cuts and cuts[-1] != len(self.events):
            cuts.append(len(self.events))
        dc_budget = 3  # double-crash legs are the expensive tail
        for cut in cuts:
            if max_points is not None and points >= max_points:
                break
            # ack⇒durable is checked PER CUT, before any image-digest
            # dedup: the ack mark changes no disk byte, so the cut
            # right after an ack dedups to the pre-ack image — hiding
            # exactly the inversion (floor > ceiling) a broken commit
            # point produces
            floor, ceiling = self._legal_window(cut)
            if floor > ceiling:
                points += 1
                violations.append(
                    f"acked txn {floor} not durable at cut {cut} "
                    f"(durable ceiling {ceiling})")
                continue
            # un-synced KV batches may also SURVIVE (as a prefix):
            # explore the max variant whenever it differs from min
            kv_keeps = ["min"]
            if len(durable_kv_prefix(self.events, cut, "max")) != \
                    len(durable_kv_prefix(self.events, cut, "min")):
                kv_keeps.append("max")
            for sched in self._schedules(cut, torn=torn):
                for kv_keep in kv_keeps:
                    if max_points is not None and \
                            points >= max_points:
                        break
                    points += 1
                    try:
                        block, ops = build_image(
                            self.events, cut, kv_keep=kv_keep,
                            base_block=self.base_block, **sched)
                        # identical images need only one remount
                        # check, but each schedule still counts as a
                        # crash point
                        digest = image_digest(block, ops)
                        fresh = digest not in seen
                        if fresh:
                            seen.add(digest)
                            write_image(img, block, ops,
                                        base_kv=self.base_kv)
                            self.check_image(img, cut)
                        if double_crash and kv_keep == "min" \
                                and sched.get("drop_pending") \
                                and dc_budget > 0 and _has_defer(
                                    self.events, cut):
                            dc_budget -= 1
                            # ALWAYS rewrite: check_image's mount has
                            # already replayed + trimmed the journal
                            # inside `img`, so reusing it would hand
                            # _double_crash an empty replay trace
                            write_image(img, block, ops,
                                        base_kv=self.base_kv)
                            dc_points += self._double_crash(img, cut)
                    except Violation as e:
                        violations.append(str(e))
        return {"points": points,
                "distinct_images": len(seen),
                "double_crash_points": dc_points,
                "events": len(self.events),
                "txns": len(self.snapshots) - 1,
                "violations": violations}


def _has_defer(events: List[Tuple], cut: int) -> bool:
    """True when the durable KV prefix at this cut still carries
    deferred-journal entries (a double-crash-during-replay leg is only
    interesting when replay has work to do)."""
    live: set = set()
    for ops in durable_kv_prefix(events, cut, "min"):
        for op, prefix, key, _value in ops:
            if prefix != "D":
                continue
            if op == "set":
                live.add(key)
            elif op == "rm":
                live.discard(key)
            elif op in ("rm_prefix", "rm_range"):
                live.clear()
    return bool(live)


# -- default workload ------------------------------------------------------


def default_workload(txns: int = 24, seed: int = 0):
    """Mixed write/overwrite/deferred/omap workload: small in-place
    overwrites (the deferred WAL path), COW rewrites, multi-span
    objects, zero/truncate, xattr/omap churn, clone and remove — every
    TPUStore persistence path, deterministic per seed."""
    import random

    rng = random.Random(seed)
    oids = [ObjectId(f"o{i}") for i in range(6)]
    sizes: Dict[str, int] = {}  # current sizes, drives legal overwrites

    def payload(n: int) -> bytes:
        return bytes(rng.getrandbits(8) for _ in range(n))

    for i in range(txns):
        t = Transaction()
        kind = i % 8
        oid = oids[rng.randrange(len(oids))]
        if kind == 0 or str(oid) not in sizes:
            # fresh/base write: big enough that overwrites can defer,
            # occasionally multi-span (COW across blob boundaries)
            n = 70_000 if i % 5 == 0 else rng.randrange(4096, 9000)
            t.write("cc", oid, 0, n, payload(n))
            sizes[str(oid)] = n
        elif kind in (1, 2, 3):
            # small in-place overwrite: the deferred-WAL path
            n = rng.randrange(16, 600)
            off = rng.randrange(0, max(1, sizes[str(oid)] - n))
            t.write("cc", oid, off, n, payload(n))
        elif kind == 4:
            n = rng.randrange(100, 2000)
            off = rng.randrange(0, sizes[str(oid)])
            t.zero("cc", oid, off, n)
            t.omap_setkeys("cc", oid, {f"k{i}": payload(12)})
            sizes[str(oid)] = max(sizes[str(oid)], off + n)
        elif kind == 5:
            new = max(1, sizes[str(oid)] // 2)
            t.truncate("cc", oid, new)
            t.setattr("cc", oid, f"a{i % 3}", payload(8))
            sizes[str(oid)] = new
        elif kind == 6:
            dst = ObjectId(f"{oid.name}_c{i}")
            t.clone("cc", oid, dst)
            sizes[str(dst)] = sizes[str(oid)]
        else:
            t.remove("cc", oid)
            t.omap_setheader("cc", oids[0], payload(6))
            sizes.pop(str(oid), None)
        yield t

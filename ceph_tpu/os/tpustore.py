"""TPUStore: the BlueStore-role persistent ObjectStore.

Reference parity: BlueStore (/root/reference/src/os/bluestore/) at
architecture level — a raw block file managed by an extent Allocator,
object metadata (onodes: size, blob map, xattrs) in a KeyValueDB, omap in
the same KV, per-blob checksums verified on every read (_verify_csum,
BlueStore.cc:9636-9663), inline compression behind the required-ratio
gate (_do_alloc_write, BlueStore.cc:13459-13606).

Write model: objects are covered by fixed logical spans of
`max_blob_size`; a write copies-on-writes every touched span — new data
always lands in freshly allocated extents, and the KV batch that commits
the new blob map also returns the old extents to the freelist, so a crash
between the two leaves the old object intact (BlueStore's no-overwrite
discipline) — EXCEPT small overwrites of existing uncompressed blobs,
which take BlueStore's deferred-write path: the new bytes ride the KV
commit batch itself (the WAL), the transaction skips the block-file
fsync entirely, and the in-place overwrite is applied after the commit
point and journal-trimmed in batches; mount replays any pending
entries (BlueStore.cc _deferred_queue/_deferred_replay).

TPU hook: per-blob crc32c runs through the batched Checksummer path, and
compression candidates are pre-scored on device
(ceph_tpu.compressor.scoring) before any host codec runs.
"""

from __future__ import annotations

import json
import os as _os
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.common import checksummer as csum_mod
from ceph_tpu.common import tracing
from ceph_tpu.common.checksummer import CSUM_NONE, Checksummer
from ceph_tpu.compressor import Compressor, gate, scoring
from ceph_tpu.kv import SQLiteDB
from ceph_tpu.os import ObjectId, ObjectStore, Transaction

# KV prefixes (BlueStore's column families)
P_SUPER = "S"
P_ONODE = "O"
P_OMAP = "M"
P_FREELIST = "F"
P_DEFER = "D"   # deferred-write WAL (BlueStore deferred_transaction_t)


class Allocator:
    """First-fit extent allocator over the block file (Allocator role)."""

    def __init__(self) -> None:
        self.free: List[Tuple[int, int]] = []  # sorted (offset, length)
        self.device_size = 0

    def init_add_free(self, offset: int, length: int) -> None:
        self.free.append((offset, length))
        self._merge()

    def _merge(self) -> None:
        self.free.sort()
        merged: List[Tuple[int, int]] = []
        for off, ln in self.free:
            if merged and merged[-1][0] + merged[-1][1] == off:
                merged[-1] = (merged[-1][0], merged[-1][1] + ln)
            else:
                merged.append((off, ln))
        self.free = merged

    def allocate(self, length: int) -> int:
        """Returns the offset; grows the logical device when fragmented."""
        for i, (off, ln) in enumerate(self.free):
            if ln >= length:
                if ln == length:
                    self.free.pop(i)
                else:
                    self.free[i] = (off + length, ln - length)
                return off
        off = self.device_size
        self.device_size += length
        return off

    def release(self, offset: int, length: int) -> None:
        if length:
            self.free.append((offset, length))
            self._merge()

    def to_json(self) -> dict:
        return {"free": self.free, "device_size": self.device_size}

    @classmethod
    def from_json(cls, d: dict) -> "Allocator":
        a = cls()
        a.free = [tuple(e) for e in d["free"]]
        a.device_size = int(d["device_size"])
        return a


class _Blob:
    """One stored span: extent + csum + compression metadata."""

    __slots__ = ("offset", "stored_len", "raw_len", "csum_data",
                 "comp_alg", "comp_msg", "csum_type", "csum_block")

    def __init__(self, offset: int, stored_len: int, raw_len: int,
                 csum_data: bytes, comp_alg: Optional[int],
                 comp_msg: Optional[int], csum_type: int = 1,  # CSUM_NONE
                 csum_block: int = 4096):
        self.offset = offset
        self.stored_len = stored_len
        self.raw_len = raw_len
        self.csum_data = csum_data
        self.comp_alg = comp_alg
        self.comp_msg = comp_msg
        # blobs carry their own csum params (bluestore_blob_t does the
        # same) so a config change never invalidates existing data
        self.csum_type = csum_type
        self.csum_block = csum_block

    def to_json(self) -> list:
        return [self.offset, self.stored_len, self.raw_len,
                self.csum_data.hex(), self.comp_alg, self.comp_msg,
                self.csum_type, self.csum_block]

    @classmethod
    def from_json(cls, d: list) -> "_Blob":
        return cls(d[0], d[1], d[2], bytes.fromhex(d[3]), d[4], d[5],
                   d[6] if len(d) > 6 else 1,
                   d[7] if len(d) > 7 else 4096)


class _Onode:
    def __init__(self) -> None:
        self.size = 0
        self.blobs: Dict[int, _Blob] = {}  # span index -> blob
        self.xattrs: Dict[str, str] = {}   # hex-encoded values
        self.omap_header = ""
        self.alloc_hint_flags = 0

    def to_bytes(self) -> bytes:
        return json.dumps({
            "size": self.size,
            "blobs": {str(k): b.to_json() for k, b in self.blobs.items()},
            "xattrs": self.xattrs,
            "omap_header": self.omap_header,
            "alloc_hint_flags": self.alloc_hint_flags,
        }).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "_Onode":
        d = json.loads(raw)
        o = cls()
        o.size = d["size"]
        o.blobs = {int(k): _Blob.from_json(v)
                   for k, v in d["blobs"].items()}
        o.xattrs = d["xattrs"]
        o.omap_header = d.get("omap_header", "")
        o.alloc_hint_flags = d.get("alloc_hint_flags", 0)
        return o


class TPUStore(ObjectStore):
    def __init__(self, path: str, config=None):
        self.path = path
        self._config = config
        self._kv = SQLiteDB(_os.path.join(path, "meta.db"))
        self._block_path = _os.path.join(path, "block")
        self._block = None
        self._alloc = Allocator()
        self._lock = threading.RLock()
        self._txc: Optional[Dict[bytes, Optional[_Onode]]] = None
        self._txc_colls: set = set()
        # extents freed by the in-flight transaction; returned to the
        # allocator only after the KV commit succeeds (BlueStore defers
        # release until after kv commit) so no op in the same transaction
        # — or a crash before the commit point — can overwrite data still
        # referenced by committed onodes
        self._txc_release: List[Tuple[int, int]] = []
        # deferred-write WAL state: entries journaled by the in-flight
        # txn, and applied-but-not-yet-trimmed journal keys
        self._txc_defer: List[Tuple[int, bytes, bytes]] = []
        self._txc_direct = False
        # (journal key, extent offset, length) applied but untrimmed
        self._pending_defer: List[Tuple[bytes, int, int]] = []
        self._defer_seq = 0
        # journaled-but-not-yet-applied bytes, keyed by blob offset:
        # reads (including later ops in the SAME txn) must see the
        # deferred data even though the block file still holds the old
        # bytes until the post-commit apply
        self._defer_overlay: Dict[int, bytes] = {}
        self._compressor: Optional[Compressor] = None
        self._mounted = False
        # config (bluestore_* options)
        self.max_blob_size = 64 * 1024
        self.prefer_deferred_size = 32 * 1024
        self.deferred_batch = 32
        self.csum_type = csum_mod.CSUM_CRC32C
        self.csum_block_size = 4096
        self.comp_mode = 0  # COMP_NONE unless configured
        self.required_ratio = gate.DEFAULT_REQUIRED_RATIO
        # store identity: written once at mkfs, read back at mount —
        # a remount of the same directory must present the same fsid
        # (the BlueStore fsid file role; cluster harnesses assert a
        # revived OSD got ITS disk back, not a fresh one)
        self.fsid: str = ""
        # durability/observability counters (l_bluestore_* perf role);
        # surfaced by the daemon's `store_status` and perf dump
        self.perf: Dict[str, int] = {
            "kv_commits": 0,
            "block_fsyncs": 0,
            "deferred_writes": 0,
            "deferred_bytes": 0,
            "journal_replays": 0,
            "journal_replayed_entries": 0,
            "journal_replayed_bytes": 0,
            "csum_read_failures": 0,
            # group commit (submit_batch): merged-batch accounting —
            # barriers the batching amortized away vs one-txn commits
            "gc_batches": 0,
            "gc_txns": 0,
            "gc_fsyncs_saved": 0,
            "gc_kv_commits_saved": 0,
        }
        self._load_config()

    def _load_config(self) -> None:
        from ceph_tpu.compressor import get_comp_mode_type

        if self._config is None:
            self.comp_mode = 0  # none
            return
        self.csum_type = csum_mod.get_csum_string_type(
            self._config.get("bluestore_csum_type"))
        self.csum_block_size = int(
            self._config.get("bluestore_csum_block_size"))
        self.max_blob_size = int(
            self._config.get("bluestore_compression_max_blob_size"))
        self.comp_mode = get_comp_mode_type(
            self._config.get("bluestore_compression_mode")) or 0
        self.required_ratio = float(
            self._config.get("bluestore_compression_required_ratio"))
        alg = self._config.get("bluestore_compression_algorithm")
        self._compressor = Compressor.create(alg) if alg else None

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        _os.makedirs(self.path, exist_ok=True)
        # the block file (and its directory entry) must be durable
        # BEFORE the superblock commit below: a store whose KV says it
        # is valid but whose block file's dirent died with the power
        # would fail mount
        with open(self._block_path, "ab"):
            pass
        dirfd = _os.open(self.path, _os.O_RDONLY)
        try:
            _os.fsync(dirfd)
        finally:
            _os.close(dirfd)
        self._kv.create_and_open()
        t = self._kv.get_transaction()
        t.set(P_SUPER, b"format", b"tpustore-1")
        t.set(P_SUPER, b"fsid", uuid.uuid4().hex.encode())
        t.set(P_FREELIST, b"state",
              json.dumps(self._alloc.to_json()).encode())
        # mkfs is a durability point: a power cut right after must
        # still find a mountable store
        self._kv.submit_transaction_sync(t)
        self._kv.close()

    def mount(self) -> None:
        self._kv.create_and_open()
        fmt = self._kv.get(P_SUPER, b"format")
        if fmt != b"tpustore-1":
            raise RuntimeError(f"{self.path}: not a tpustore ({fmt!r})")
        self.fsid = (self._kv.get(P_SUPER, b"fsid") or b"").decode()
        state = self._kv.get(P_FREELIST, b"state")
        self._alloc = Allocator.from_json(json.loads(state))
        self._block = open(self._block_path, "r+b")
        self._replay_deferred()
        self._mounted = True

    def _block_sync(self) -> None:
        """The block-file durability barrier: everything written
        before this survives a power cut (the ONE choke point, so a
        fault-injecting subclass can record — or deliberately omit —
        the barrier)."""
        self._block.flush()
        _os.fsync(self._block.fileno())
        self.perf["block_fsyncs"] += 1

    def _replay_deferred(self) -> None:
        """Apply journaled in-place writes that may not have reached
        the block file before a crash (idempotent — a crash DURING
        replay just replays again on the next mount), then trim."""
        keys = []
        for key, value in self._kv.get_iterator(P_DEFER):
            off = int.from_bytes(value[:8], "little")
            self._pwrite(off, value[8:])
            keys.append(key)
            self._defer_seq = max(self._defer_seq, int(key))
            self.perf["journal_replayed_entries"] += 1
            self.perf["journal_replayed_bytes"] += len(value) - 8
        if keys:
            self.perf["journal_replays"] += 1
            self._block_sync()
            t = self._kv.get_transaction()
            for key in keys:
                t.rmkey(P_DEFER, key)
            # trim loss is benign (replay is idempotent and KV batches
            # are prefix-durable), so the trim rides a NORMAL commit
            self._kv.submit_transaction(t)

    def _flush_deferred(self) -> None:
        """Make applied deferred writes durable on the block file,
        then trim their journal entries (one fsync per batch — the
        amortization that makes small overwrites cheap)."""
        if not self._pending_defer:
            return
        self._block_sync()
        t = self._kv.get_transaction()
        for key, _off, _ln in self._pending_defer:
            t.rmkey(P_DEFER, key)
        self._kv.submit_transaction(t)
        self._pending_defer = []

    def umount(self) -> None:
        if self._block is not None:
            self._flush_deferred()
        if self._block is not None:
            self._block_sync()
            self._block.close()
            self._block = None
        self._kv.close()
        self._mounted = False

    def crash(self) -> None:
        """Process-crash seam for tests/harnesses: abandon the store
        WITHOUT the clean umount's deferred flush + fsync.  Bytes
        already handed to the OS survive (process-crash semantics — a
        remount replays the deferred WAL); a power cut additionally
        loses un-synced state, which FaultStore.crash_powercut
        synthesizes on top of this."""
        if self._block is not None:
            try:
                # hand userspace-buffered bytes to the OS page cache
                # (a crashed process loses nothing it already wrote);
                # deliberately NO fsync and NO journal trim
                self._block.flush()
            except ValueError:
                pass
            self._block.close()
            self._block = None
        self._kv.close()
        self._mounted = False
        self._pending_defer = []
        self._defer_overlay.clear()

    def perf_counters(self) -> Dict[str, int]:
        """Durability counters + live gauges (the perf-dump `store`
        section / `store_status` payload)."""
        out = dict(self.perf)
        out["deferred_queue_depth"] = len(self._pending_defer)
        return out

    # -- onode cache-free helpers ------------------------------------------

    @staticmethod
    def _okey(cid: str, oid: ObjectId) -> bytes:
        return f"{cid}\0{oid}".encode()

    def _get_onode(self, cid: str, oid: ObjectId,
                   create: bool = False) -> _Onode:
        # read-your-writes within the transaction being applied
        key = self._okey(cid, oid)
        if self._txc is not None and key in self._txc:
            cached = self._txc[key]
            if cached is None:
                if not create:
                    raise KeyError(f"{cid}/{oid}")
            else:
                return cached
        raw = self._kv.get(P_ONODE, key)
        if raw is None or (self._txc is not None
                           and self._txc.get(key, raw) is None):
            if not create:
                raise KeyError(f"{cid}/{oid}")
            if cid not in self._txc_colls and \
                    self._kv.get(P_SUPER, b"coll." + cid.encode()) is None:
                raise KeyError(f"no collection {cid}")
            onode = _Onode()
        else:
            onode = _Onode.from_bytes(raw)
        if self._txc is not None:
            self._txc[key] = onode
        return onode

    def _put_onode(self, kvt, cid: str, oid: ObjectId,
                   onode: _Onode) -> None:
        key = self._okey(cid, oid)
        if self._txc is not None:
            self._txc[key] = onode
        kvt.set(P_ONODE, key, onode.to_bytes())

    def _drop_onode(self, kvt, cid: str, oid: ObjectId) -> None:
        key = self._okey(cid, oid)
        if self._txc is not None:
            self._txc[key] = None
        kvt.rmkey(P_ONODE, key)

    # -- block io ----------------------------------------------------------

    def _pwrite(self, offset: int, data: bytes) -> None:
        self._block.seek(offset)
        self._block.write(data)

    def _pwrite_direct(self, offset: int, data: bytes) -> None:
        """A write that must be durable at THIS transaction's commit
        (marks the txn as needing the pre-commit block fsync)."""
        self._txc_direct = True
        self._pwrite(offset, data)

    def _pread(self, offset: int, length: int) -> bytes:
        self._block.seek(offset)
        out = self._block.read(length)
        if len(out) < length:
            out += bytes(length - len(out))
        return out

    # -- write path (_do_alloc_write) --------------------------------------

    def _span_write(self, kvt, onode: _Onode, span: int,
                    raw: bytes, write_len: Optional[int] = None,
                    write_off: int = 0) -> None:
        """Store one logical span COW-style: compress-candidate scoring,
        gate, csum, allocate, write; old extent freed in the same batch.

        Small overwrites (write_len <= prefer_deferred_size) of an
        existing uncompressed blob take the DEFERRED path instead: the
        bytes are journaled into this txn's KV batch and applied
        in-place after the commit point — no COW, no per-write block
        fsync."""
        old = onode.blobs.get(span)
        if (write_len is not None and old is not None
                and old.comp_alg is None
                and old.stored_len >= len(raw) > 0
                and write_len <= self.prefer_deferred_size
                and not (self.comp_mode and self._compressor)):
            csum_data = bytearray()
            if self.csum_type != CSUM_NONE:
                padded_len = -(-len(raw) // self.csum_block_size) * \
                    self.csum_block_size
                padded = raw + bytes(padded_len - len(raw))
                Checksummer.calculate(
                    self.csum_type, self.csum_block_size, 0,
                    padded_len, padded, csum_data)
            self._defer_seq += 1
            key = f"{self._defer_seq:020d}".encode()
            # journal ONLY the touched byte range (BlueStore journals
            # the modified chunks, not the whole blob — a 50-byte
            # overwrite must not WAL 64 KiB); crash replay applies the
            # delta over the intact pre-image, matching the committed
            # csum computed over the merged span
            delta = raw[write_off:write_off + write_len]
            kvt.set(P_DEFER, key,
                    (old.offset + write_off).to_bytes(8, "little")
                    + delta)
            self._txc_defer.append(
                (old.offset + write_off, delta, key))
            self.perf["deferred_writes"] += 1
            self.perf["deferred_bytes"] += len(delta)
            self._defer_overlay[old.offset] = bytes(raw)
            if old.stored_len > len(raw):
                # the shrunken tail is unreferenced: free it
                self._txc_release.append(
                    (old.offset + len(raw), old.stored_len - len(raw)))
            onode.blobs[span] = _Blob(
                old.offset, len(raw), len(raw), bytes(csum_data),
                None, None, csum_type=self.csum_type,
                csum_block=self.csum_block_size)
            return
        payload, header = raw, None
        if self.comp_mode and self._compressor is not None and raw:
            # TPU pre-score: skip the host codec for incompressible spans
            # (COMP_FORCE bypasses the prescreen — forced means forced)
            arr = np.frombuffer(raw, dtype=np.uint8)[None, :]
            if self.comp_mode == gate.COMP_FORCE or bool(
                    np.asarray(scoring.compress_decision(
                        arr, self.required_ratio))[0]):
                payload, header = gate.maybe_compress(
                    raw, self._compressor, self.comp_mode,
                    onode.alloc_hint_flags, self.required_ratio)
        csum_data = bytearray()
        if self.csum_type != CSUM_NONE:
            padded_len = -(-len(payload) // self.csum_block_size) * \
                self.csum_block_size
            padded = payload + bytes(padded_len - len(payload))
            Checksummer.calculate(self.csum_type, self.csum_block_size, 0,
                                  padded_len, padded, csum_data)
        offset = self._alloc.allocate(len(payload)) if payload else 0
        if payload:
            self._pwrite_direct(offset, payload)
        onode.blobs[span] = _Blob(
            offset, len(payload), len(raw), bytes(csum_data),
            header.alg if header else None,
            header.compressor_message if header else None,
            csum_type=self.csum_type, csum_block=self.csum_block_size)
        if old is not None and old.stored_len:
            self._txc_release.append((old.offset, old.stored_len))

    def _span_read(self, blob: _Blob) -> bytes:
        overlay = self._defer_overlay.get(blob.offset)
        if overlay is not None and len(overlay) >= blob.stored_len:
            payload = overlay[:blob.stored_len]
        else:
            payload = self._pread(blob.offset, blob.stored_len)
        if blob.csum_type != CSUM_NONE and blob.csum_data:
            padded_len = -(-len(payload) // blob.csum_block) * \
                blob.csum_block
            padded = payload + bytes(padded_len - len(payload))
            bad = Checksummer.verify(
                blob.csum_type, blob.csum_block, 0, padded_len,
                padded, blob.csum_data)
            if bad >= 0:
                self.perf["csum_read_failures"] += 1
                raise IOError(
                    f"csum mismatch at blob offset {bad}"
                    f" (device offset {blob.offset + bad})")
        if blob.comp_alg is not None:
            header = gate.CompressionHeader(
                blob.comp_alg, blob.raw_len, blob.comp_msg)
            payload = gate.decompress(payload, header)
        return payload

    def _object_write(self, kvt, cid: str, oid: ObjectId, offset: int,
                      data: bytes) -> None:
        onode = self._get_onode(cid, oid, create=True)
        end = offset + len(data)
        span0 = offset // self.max_blob_size
        span1 = (end - 1) // self.max_blob_size if data else span0
        pos = 0
        for span in range(span0, span1 + 1):
            s_start = span * self.max_blob_size
            s_end = s_start + self.max_blob_size
            w_start = max(offset, s_start)
            w_end = min(end, s_end)
            old_blob = onode.blobs.get(span)
            span_len = min(self.max_blob_size,
                           max(onode.size, w_end) - s_start)
            if old_blob is not None:
                raw = bytearray(self._span_read(old_blob))
                if len(raw) < span_len:
                    raw.extend(bytes(span_len - len(raw)))
            else:
                raw = bytearray(span_len)
            raw[w_start - s_start:w_end - s_start] = \
                data[pos:pos + (w_end - w_start)]
            pos += w_end - w_start
            self._span_write(kvt, onode, span, bytes(raw),
                             write_len=w_end - w_start,
                             write_off=w_start - s_start)
        onode.size = max(onode.size, end)
        self._put_onode(kvt, cid, oid, onode)

    def _object_remove(self, kvt, cid: str, oid: ObjectId) -> None:
        try:
            onode = self._get_onode(cid, oid)
        except KeyError:
            return
        for blob in onode.blobs.values():
            if blob.stored_len:
                self._txc_release.append((blob.offset, blob.stored_len))
        self._drop_onode(kvt, cid, oid)
        okey = self._okey(cid, oid)
        kvt.rm_range_keys(P_OMAP, okey + b"\0", okey + b"\1")

    # -- transaction apply --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        err = self._submit_merged([txn])
        if err is not None:
            raise err

    def submit_batch(self, txns) -> list:
        """Group commit: N transactions, ONE commit point.  The KV
        batches merge into a single submit_transaction_sync and the
        direct block writes share a single pre-commit fsync — N
        concurrent writers buy one barrier instead of N (the BlueStore
        kv_sync_thread amortization).  Read-your-writes spans the
        batch (txn i sees txn j<i's onodes/collections), so the
        merged batch applies byte-identically to committing each txn
        in order.  If ANY apply fails, the merged attempt is rolled
        back untouched (nothing was submitted) and the batch replays
        through the one-txn path so exactly the failing txn reports
        its error and the rest still commit — per-txn isolation at
        per-txn cost, paid only on the error path."""
        if not txns:
            return []
        if len(txns) == 1:
            try:
                self.queue_transaction(txns[0])
                return [None]
            except Exception as e:
                return [e]
        if self._submit_merged(txns) is None:
            return [None] * len(txns)
        results = []
        for txn in txns:
            try:
                self.queue_transaction(txn)
                results.append(None)
            except Exception as e:
                results.append(e)
        return results

    def _submit_merged(self, txns) -> Optional[Exception]:
        """Apply+commit a FIFO list of transactions as one commit unit
        (the one-txn path is the degenerate batch).  Returns None on
        success — all on_commit callbacks fired — or the first apply
        exception, with the store rolled back as if nothing ran."""
        with self._lock:
            kvt = self._kv.get_transaction()
            self._txc = {}
            self._txc_colls = set()
            self._txc_release = []
            self._txc_defer = []
            self._txc_direct = False
            direct_txns = 0
            # a failed apply must not leak half a batch: restore the
            # allocator (extents allocated by earlier ops) and the
            # deferred overlay, and submit nothing; pending releases
            # are simply discarded, so nothing was freed and nothing
            # freed was reusable mid-batch
            alloc_snapshot = (list(self._alloc.free),
                              self._alloc.device_size)
            overlay_snapshot = dict(self._defer_overlay)
            try:
                for txn in txns:
                    txn_direct_before = self._txc_direct
                    self._txc_direct = False
                    for op in txn.ops:
                        self._apply(kvt, op)
                    if self._txc_direct:
                        direct_txns += 1
                    self._txc_direct = \
                        self._txc_direct or txn_direct_before
            except Exception as e:
                self._alloc.free, self._alloc.device_size = alloc_snapshot
                self._txc_release = []
                self._defer_overlay = overlay_snapshot
                self._txc_defer = []
                self._txc = None
                self._txc_colls = set()
                return e
            finally:
                self._txc = None
                self._txc_colls = set()
            # the persisted freelist is the post-commit truth: allocator
            # state with this transaction's releases applied — but the
            # in-memory allocator only sees them after the commit point
            if self._txc_release:
                final_alloc = Allocator()
                final_alloc.free = list(self._alloc.free)
                final_alloc.device_size = self._alloc.device_size
                for off, ln in self._txc_release:
                    final_alloc.release(off, ln)
                state_json = final_alloc.to_json()
            else:
                state_json = self._alloc.to_json()
            kvt.set(P_FREELIST, b"state",
                    json.dumps(state_json).encode())
            # data first, then the metadata commit point — but a
            # purely-deferred txn carries its data IN the KV batch and
            # skips the block fsync entirely (the deferred-write win)
            if self._txc_direct:
                with tracing.child_span_sync("fsync"):
                    self._block_sync()
            # the commit point IS the durability point: once this
            # returns, on_commit fires and the ack must survive a
            # power cut — so the batch goes down SYNC (BlueStore syncs
            # its RocksDB WAL the same way; the WAL-mode NORMAL
            # default only survives process death, and an acked write
            # that vanishes on power loss is the one failure nothing
            # upstack can repair)
            with tracing.child_span_sync("kv_commit"):
                self._kv.submit_transaction_sync(kvt)
            self.perf["kv_commits"] += 1
            # apply deferred in-place writes AFTER the commit point:
            # their durability is the journal entry; the block file
            # catches up here and fsyncs lazily in batches
            for off, delta, key in self._txc_defer:
                self._pwrite(off, delta)
                self._pending_defer.append((key, off, len(delta)))
            if self._txc_defer:
                # the block file has caught up: overlays are stale
                # (a newer same-txn overlay was already overwritten by
                # its own later _span_write call)
                self._defer_overlay.clear()
            self._txc_defer = []
            # releases overlapping a pending journal entry must wait
            # for the journal trim: a crash would otherwise REPLAY the
            # stale bytes over whatever reallocated the extent
            # (BlueStore holds deferred extents out of the freelist
            # for the same reason)
            if self._txc_release and self._pending_defer and any(
                    r_off < d_off + d_ln and d_off < r_off + r_ln
                    for r_off, r_ln in self._txc_release
                    for _k, d_off, d_ln in self._pending_defer):
                self._flush_deferred()
            elif len(self._pending_defer) >= self.deferred_batch:
                self._flush_deferred()
            for off, ln in self._txc_release:
                self._alloc.release(off, ln)
            self._txc_release = []
            if len(txns) > 1:
                # group-commit accounting: what the batch saved vs N
                # one-txn commits (fsyncs only count when more than
                # one member would have paid one)
                self.perf["gc_batches"] += 1
                self.perf["gc_txns"] += len(txns)
                self.perf["gc_kv_commits_saved"] += len(txns) - 1
                self.perf["gc_fsyncs_saved"] += max(direct_txns - 1, 0)
        # per-txn acks fire only after the SHARED barrier, in batch
        # order — the ack=>durable contract is per txn, the barrier is
        # per batch
        for txn in txns:
            for cb in txn.on_commit:
                cb()
        return None

    def _apply(self, kvt, op) -> None:
        kind = op[0]
        if kind == "mkcoll":
            kvt.set(P_SUPER, b"coll." + op[1].encode(), b"1")
            self._txc_colls.add(op[1])  # visible within this txn
        elif kind == "rmcoll":
            kvt.rmkey(P_SUPER, b"coll." + op[1].encode())
        elif kind == "touch" or kind == "alloc_hint":
            cid, oid = op[1], op[2]
            onode = self._get_onode(cid, oid, create=True)
            if kind == "alloc_hint":
                onode.alloc_hint_flags = op[5]
            self._put_onode(kvt, cid, oid, onode)
        elif kind == "write":
            _k, cid, oid, offset, data = op
            if not isinstance(data, (bytes, bytearray, memoryview)):
                data = bytes(data)  # StridedBuf: durable store is a copy anyway
            self._object_write(kvt, cid, oid, offset, data)
        elif kind == "zero":
            _k, cid, oid, offset, length = op
            self._object_write(kvt, cid, oid, offset, bytes(length))
        elif kind == "truncate":
            _k, cid, oid, size = op
            onode = self._get_onode(cid, oid, create=True)
            if size < onode.size:
                keep_spans = -(-size // self.max_blob_size) if size else 0
                for span in [s for s in onode.blobs if s >= keep_spans]:
                    blob = onode.blobs.pop(span)
                    if blob.stored_len:
                        self._txc_release.append(
                            (blob.offset, blob.stored_len))
                onode.size = size
                # partial tail span: rewrite truncated
                if size % self.max_blob_size and (size // self.max_blob_size) in onode.blobs:
                    tail_span = size // self.max_blob_size
                    raw = self._span_read(onode.blobs[tail_span])
                    self._span_write(kvt, onode, tail_span,
                                     raw[:size % self.max_blob_size])
            else:
                onode.size = size
            self._put_onode(kvt, cid, oid, onode)
        elif kind == "remove":
            self._object_remove(kvt, op[1], op[2])
        elif kind == "clone":
            _k, cid, src, dst = op
            data = self.read(cid, src)
            src_onode = self._get_onode(cid, src)
            self._object_remove(kvt, cid, dst)
            dst_onode = _Onode()
            dst_onode.xattrs = dict(src_onode.xattrs)
            dst_onode.omap_header = src_onode.omap_header
            dst_onode.alloc_hint_flags = src_onode.alloc_hint_flags
            self._put_onode(kvt, cid, dst, dst_onode)
            self._object_write(kvt, cid, dst, 0, data)
            # omap copy
            okey_src = self._okey(cid, src)
            okey_dst = self._okey(cid, dst)
            for key, value in list(self._kv.get_iterator(
                    P_OMAP, okey_src + b"\0", okey_src + b"\1")):
                kvt.set(P_OMAP, okey_dst + b"\0" + key[len(okey_src) + 1:],
                        value)
        elif kind == "move":
            _k, src_cid, src, dst_cid, dst = op
            onode = self._get_onode(src_cid, src)
            self._drop_onode(kvt, src_cid, src)
            self._put_onode(kvt, dst_cid, dst, onode)
            okey_src = self._okey(src_cid, src)
            okey_dst = self._okey(dst_cid, dst)
            for key, value in list(self._kv.get_iterator(
                    P_OMAP, okey_src + b"\0", okey_src + b"\1")):
                kvt.set(P_OMAP, okey_dst + b"\0" + key[len(okey_src) + 1:],
                        value)
                kvt.rmkey(P_OMAP, key)
        elif kind == "setattr":
            _k, cid, oid, name, value = op
            onode = self._get_onode(cid, oid, create=True)
            onode.xattrs[name] = value.hex()
            self._put_onode(kvt, cid, oid, onode)
        elif kind == "rmattr":
            _k, cid, oid, name = op
            onode = self._get_onode(cid, oid)
            onode.xattrs.pop(name, None)
            self._put_onode(kvt, cid, oid, onode)
        elif kind == "omap_setkeys":
            _k, cid, oid, keys = op
            okey = self._okey(cid, oid)
            for key, value in keys.items():
                kvt.set(P_OMAP, okey + b"\0" + key.encode(), value)
        elif kind == "omap_rmkeys":
            _k, cid, oid, keys = op
            okey = self._okey(cid, oid)
            for key in keys:
                kvt.rmkey(P_OMAP, okey + b"\0" + key.encode())
        elif kind == "omap_clear":
            okey = self._okey(op[1], op[2])
            kvt.rm_range_keys(P_OMAP, okey + b"\0", okey + b"\1")
        elif kind == "omap_setheader":
            _k, cid, oid, header = op
            onode = self._get_onode(cid, oid, create=True)
            onode.omap_header = header.hex()
            self._put_onode(kvt, cid, oid, onode)
        else:
            raise ValueError(f"unknown transaction op {kind!r}")

    # -- reads --------------------------------------------------------------

    def read(self, cid: str, oid: ObjectId, offset: int = 0,
             length: int = 0) -> bytes:
        with self._lock:
            onode = self._get_onode(cid, oid)
            if length == 0:
                length = max(onode.size - offset, 0)
            end = min(offset + length, onode.size)
            if end <= offset:
                return b""
            out = bytearray()
            span0 = offset // self.max_blob_size
            span1 = (end - 1) // self.max_blob_size
            for span in range(span0, span1 + 1):
                s_start = span * self.max_blob_size
                blob = onode.blobs.get(span)
                covered = min(self.max_blob_size, onode.size - s_start)
                if blob is None:
                    raw = bytes(covered)
                else:
                    raw = self._span_read(blob)
                    if len(raw) < covered:  # hole inside the span
                        raw += bytes(covered - len(raw))
                r_start = max(offset, s_start) - s_start
                r_end = min(end, s_start + self.max_blob_size) - s_start
                out += raw[r_start:r_end]
            return bytes(out)

    def stat(self, cid: str, oid: ObjectId) -> Dict[str, Any]:
        with self._lock:
            onode = self._get_onode(cid, oid)
            return {"size": onode.size}

    def getattr(self, cid: str, oid: ObjectId, name: str) -> bytes:
        with self._lock:
            return bytes.fromhex(self._get_onode(cid, oid).xattrs[name])

    def getattrs(self, cid: str, oid: ObjectId) -> Dict[str, bytes]:
        with self._lock:
            return {k: bytes.fromhex(v)
                    for k, v in self._get_onode(cid, oid).xattrs.items()}

    def omap_get(self, cid: str, oid: ObjectId) -> Dict[str, bytes]:
        with self._lock:
            okey = self._okey(cid, oid)
            return {key[len(okey) + 1:].decode(): value
                    for key, value in self._kv.get_iterator(
                        P_OMAP, okey + b"\0", okey + b"\1")}

    def omap_get_header(self, cid: str, oid: ObjectId) -> bytes:
        with self._lock:
            return bytes.fromhex(self._get_onode(cid, oid).omap_header)

    def list_collections(self) -> List[str]:
        with self._lock:
            return sorted(
                key[len(b"coll."):].decode()
                for key, _v in self._kv.get_iterator(P_SUPER, b"coll.")
                if key.startswith(b"coll."))

    def list_objects(self, cid: str) -> List[ObjectId]:
        with self._lock:
            prefix = f"{cid}\0".encode()
            out = []
            for key, _v in self._kv.get_iterator(
                    P_ONODE, prefix, prefix + b"\xff"):
                name = key[len(prefix):].decode()
                if "@" in name:
                    base, snap_s = name.rsplit("@", 1)
                    out.append(ObjectId(base, int(snap_s)))
                else:
                    out.append(ObjectId(name))
            return sorted(out, key=str)

    def statfs(self) -> Dict[str, int]:
        with self._lock:
            free = sum(ln for _off, ln in self._alloc.free)
            return {"total": max(self._alloc.device_size, 1),
                    "available": free,
                    "allocated": self._alloc.device_size - free,
                    "stored": self._alloc.device_size - free}

"""CephFS subvolumes (the mgr/volumes module role).

Reference parity: /root/reference/src/pybind/mgr/volumes/ — the `fs
subvolume`/`fs subvolumegroup` surface: named, independently managed
directory trees under a conventional /volumes layout, with per-
subvolume metadata, snapshots, and quota bookkeeping; the module is
what CSI drivers and OpenStack Manila drive.

Re-design notes: the module logic runs client-side over the ordinary
CephFS mount (the reference's module also just manipulates paths over
libcephfs from inside the mgr).  Quota is recorded as intent and
enforced at resize/info time by walking the subtree — this build's
MDS has no per-dir byte accounting (rstats gap, documented).
Subvolume snapshots are real CephFS snapshots on the subvolume
directory (.snap machinery)."""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ceph_tpu.cephfs import CephFS, CephFSError

NOGROUP = "_nogroup"
ROOT = "/volumes"
META = ".meta"

ENOENT = -2
EEXIST = -17
ENOTEMPTY = -39


class VolumeClient:
    """`fs subvolume` / `fs subvolumegroup` operations over a
    mount."""

    def __init__(self, fs: CephFS):
        self.fs = fs

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _group_path(group: Optional[str]) -> str:
        return f"{ROOT}/{group or NOGROUP}"

    def _subvol_path(self, name: str,
                     group: Optional[str] = None) -> str:
        return f"{self._group_path(group)}/{name}"

    async def _mkdirs(self, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        for i in range(len(parts)):
            try:
                await self.fs.mkdir("/" + "/".join(parts[:i + 1]))
            except CephFSError as e:
                if e.rc != EEXIST:
                    raise

    async def _meta(self, path: str) -> Dict[str, Any]:
        try:
            return json.loads(await self.fs.read_file(
                f"{path}/{META}"))
        except CephFSError as e:
            if e.rc != ENOENT:
                raise
            raise CephFSError(ENOENT, f"no subvolume at {path}")

    async def _save_meta(self, path: str, doc: Dict[str, Any]) -> None:
        await self.fs.write_file(f"{path}/{META}",
                                 json.dumps(doc).encode())

    # -- subvolume groups --------------------------------------------------

    async def group_create(self, group: str) -> None:
        await self._mkdirs(self._group_path(group))

    async def group_ls(self) -> List[str]:
        try:
            names = await self.fs.listdir(ROOT)
        except CephFSError as e:
            if e.rc != ENOENT:
                raise
            return []
        return sorted(n for n in names if n != NOGROUP)

    async def group_rm(self, group: str) -> None:
        path = self._group_path(group)
        if await self.fs.listdir(path):
            raise CephFSError(ENOTEMPTY, f"group {group} has"
                                         " subvolumes")
        await self.fs.rmdir(path)

    # -- subvolumes --------------------------------------------------------

    async def create(self, name: str, group: Optional[str] = None,
                     size: Optional[int] = None,
                     mode: int = 0o755) -> str:
        """`fs subvolume create`; returns the data path."""
        path = self._subvol_path(name, group)
        await self._mkdirs(path)
        try:
            await self._meta(path)
            raise CephFSError(EEXIST, f"subvolume {name} exists")
        except CephFSError as e:
            if e.rc != ENOENT:
                raise
        await self._save_meta(path, {
            "name": name, "group": group or NOGROUP,
            "size": size, "mode": mode,
            "created": time.time(), "state": "complete"})
        return path

    async def getpath(self, name: str,
                      group: Optional[str] = None) -> str:
        """`fs subvolume getpath` — the mount path CSI hands out."""
        path = self._subvol_path(name, group)
        await self._meta(path)  # existence check
        return path

    async def ls(self, group: Optional[str] = None) -> List[str]:
        try:
            names = await self.fs.listdir(self._group_path(group))
        except CephFSError as e:
            if e.rc != ENOENT:
                raise
            return []
        return sorted(names)

    async def info(self, name: str,
                   group: Optional[str] = None) -> Dict[str, Any]:
        """`fs subvolume info`: metadata + usage (subtree walk — the
        rstats role done the slow, honest way)."""
        path = self._subvol_path(name, group)
        doc = await self._meta(path)
        used = await self._du(path)
        return dict(doc, path=path, bytes_used=used,
                    bytes_quota=doc.get("size"))

    async def _du(self, path: str) -> int:
        total = 0
        for fname, inode in (await self.fs.readdir(path)).items():
            if inode["type"] == "dir":
                total += await self._du(f"{path}/{fname}")
            elif fname != META:
                total += int(inode.get("size", 0))
        return total

    async def resize(self, name: str, new_size: int,
                     group: Optional[str] = None,
                     no_shrink: bool = False) -> Dict[str, Any]:
        path = self._subvol_path(name, group)
        doc = await self._meta(path)
        used = await self._du(path)
        if no_shrink and doc.get("size") and \
                new_size < int(doc["size"]):
            raise CephFSError(-22, "would shrink (no_shrink set)")
        doc["size"] = int(new_size)
        await self._save_meta(path, doc)
        return {"size": doc["size"], "bytes_used": used}

    async def rm(self, name: str, group: Optional[str] = None,
                 force: bool = False) -> None:
        path = self._subvol_path(name, group)
        try:
            await self._meta(path)
        except CephFSError:
            if not force:
                raise
            # force: a half-created subvolume (dir without .meta) must
            # still be removable — fall through to the tree delete if
            # the directory exists at all
            if not await self.fs.exists(path):
                return
        snaps = await self.fs.lssnap(path)
        if snaps:
            raise CephFSError(ENOTEMPTY,
                              f"subvolume {name} has snapshots")
        await self._rm_tree(path)

    async def _rm_tree(self, path: str) -> None:
        for fname, inode in (await self.fs.readdir(path)).items():
            if inode["type"] == "dir":
                await self._rm_tree(f"{path}/{fname}")
            else:
                await self.fs.unlink(f"{path}/{fname}")
        await self.fs.rmdir(path)

    # -- subvolume snapshots (`fs subvolume snapshot *`) -------------------

    async def snapshot_create(self, name: str, snap: str,
                              group: Optional[str] = None) -> None:
        path = self._subvol_path(name, group)
        await self._meta(path)
        await self.fs.mksnap(path, snap)

    async def snapshot_ls(self, name: str,
                          group: Optional[str] = None
                          ) -> List[Dict[str, Any]]:
        path = self._subvol_path(name, group)
        await self._meta(path)
        return await self.fs.lssnap(path)

    async def snapshot_rm(self, name: str, snap: str,
                          group: Optional[str] = None) -> None:
        path = self._subvol_path(name, group)
        await self.fs.rmsnap(path, snap)

"""cephfs-mirror role: snapshot-based one-way directory replication.

Reference parity: /root/reference/src/tools/cephfs_mirror/ — the
mirror daemon watches a source directory's snapshots and incrementally
replicates each new snapshot to a remote filesystem, creating the
same-named snapshot there once the content matches; snapshots deleted
at the source are pruned from the remote (PeerReplayer
do_synchronize/propagate_snap_deletes).

Re-design notes: source and destination are CephFS mounts — a second
cluster is just a second RadosClient's mount, same code path (the
rbd-mirror stance).  Sync is SNAPSHOT-DIFF: the first snapshot is a
full tree copy; every later one walks the source snapshot against the
PREVIOUS source snapshot and only touches entries whose (ino, type,
size, mtime) changed — the remote head is then frozen with mksnap.
The remote directory is mirror-managed: out-of-band writes to it
between syncs may be clobbered or shadow-deleted, as with the
reference's requirement that the peer path be dedicated to the
mirror.  Overwrites that change neither size nor mtime are invisible
to the diff (the client's buffered-attr discipline never surfaces
them); the reference's ctime heuristic shares this blind spot.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.common.periodic import PeriodicDaemon

log = logging.getLogger("cephfs.mirror")

ENOENT = -2


class DirMirror(PeriodicDaemon):
    """Replicates ONE directory's snapshots src -> dst (the
    PeerReplayer role)."""

    def __init__(self, src: CephFS, dst: CephFS, path: str):
        self.src = src
        self.dst = dst
        self.path = "/" + "/".join(p for p in path.split("/") if p)
        self._tick_what = f"cephfs-mirror {self.path}"
        # observability
        self.snaps_synced = 0
        self.files_copied = 0
        self.entries_deleted = 0

    async def _tick(self) -> None:
        await self.sync_once()

    # -- one sync pass -----------------------------------------------------

    async def sync_once(self) -> int:
        """Replicate every source snapshot the destination lacks (in
        snapid order) and prune destination snapshots the source
        dropped.  Returns the number of snapshots created.

        Snapshot identity is (name, SOURCE snapid), not name alone:
        the synced source snapid is recorded remotely (a state file
        beside — never inside — the mirrored tree, the reference's
        peer snap metadata role), so a snapshot deleted and re-created
        under the same name between passes is detected and re-synced."""
        src_snaps = await self.src.lssnap(self.path)
        src_snaps.sort(key=lambda s: s["snapid"])
        try:
            dst_have = {s["name"]
                        for s in await self.dst.lssnap(self.path)}
        except CephFSError as e:
            if e.rc != ENOENT:
                raise
            await self._ensure_dir(self.dst, self.path)
            dst_have = set()
        synced_ids = await self._load_state()
        src_ids = {s["name"]: s["snapid"] for s in src_snaps}
        # prune: dropped at the source, or re-created under an old name
        pruned = False
        for name in sorted(dst_have):
            if name in src_ids and \
                    synced_ids.get(name, src_ids[name]) == \
                    src_ids[name]:
                continue
            await self.dst.rmsnap(self.path, name)
            dst_have.discard(name)
            synced_ids.pop(name, None)
            pruned = True
        created = 0
        prev: Optional[str] = None
        for snap in src_snaps:
            name = snap["name"]
            if name in dst_have:
                prev = name  # diff base for the next new snapshot
                continue
            await self._sync_tree(
                self._snap_root(name),
                self.path,
                self._snap_root(prev) if prev else None)
            await self.dst.mksnap(self.path, name)
            synced_ids[name] = snap["snapid"]
            await self._save_state(synced_ids)
            self.snaps_synced += 1
            created += 1
            prev = name
        if created == 0 and pruned:
            # state changed only by pruning; an idle pass writes
            # nothing to the destination
            await self._save_state(synced_ids)
        return created

    # remote bookkeeping: which SOURCE snapid each remote snapshot was
    # synced from — kept OUTSIDE the mirrored tree so the sync's
    # delete-extraneous pass never eats it

    def _state_path(self) -> str:
        tag = self.path.strip("/").replace("/", "_") or "root"
        return f"/.cephfs-mirror/{tag}.json"

    async def _load_state(self) -> Dict[str, int]:
        import json
        try:
            raw = await self.dst.read_file(self._state_path())
            return {k: int(v) for k, v in json.loads(raw).items()}
        except (CephFSError, ValueError):
            return {}

    async def _save_state(self, ids: Dict[str, int]) -> None:
        import json
        await self._ensure_dir(self.dst, "/.cephfs-mirror")
        await self.dst.write_file(self._state_path(),
                                  json.dumps(ids).encode())

    def _snap_root(self, snap_name: str) -> str:
        return f"{self.path}/.snap/{snap_name}" if self.path != "/" \
            else f"/.snap/{snap_name}"

    @staticmethod
    async def _ensure_dir(fs: CephFS, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        for i in range(len(parts)):
            sub = "/" + "/".join(parts[:i + 1])
            try:
                await fs.mkdir(sub)
            except CephFSError as e:
                if e.rc != -17:  # EEXIST
                    raise

    async def _sync_tree(self, src_dir: str, dst_dir: str,
                         prev_dir: Optional[str]) -> None:
        """Make dst_dir (head) match src_dir (a snapshot view),
        diffing against prev_dir (the previously synced snapshot view)
        to skip unchanged entries."""
        src_entries = await self.src.readdir(src_dir)
        at_dst_root = dst_dir == "/"
        if at_dst_root:
            # only when mirroring INTO the root does the state dir
            # live inside the synced tree; deeper a ".cephfs-mirror"
            # entry is ordinary user data and must replicate
            src_entries.pop(".cephfs-mirror", None)
        prev_entries: Dict[str, dict] = {}
        if prev_dir is not None:
            try:
                prev_entries = await self.src.readdir(prev_dir)
            except CephFSError:
                prev_entries = {}
        try:
            dst_entries = await self.dst.readdir(dst_dir)
        except CephFSError as e:
            if e.rc != ENOENT:
                raise
            await self._ensure_dir(self.dst, dst_dir)
            dst_entries = {}
        if at_dst_root:
            dst_entries.pop(".cephfs-mirror", None)
        # remove entries the source snapshot does not have
        for name in sorted(set(dst_entries) - set(src_entries)):
            await self._rm_tree(f"{dst_dir}/{name}")
        for name, inode in sorted(src_entries.items()):
            src_p = f"{src_dir}/{name}"
            dst_p = f"{dst_dir}/{name}"
            prev_i = prev_entries.get(name)
            kind = inode["type"]
            existed = name in dst_entries
            if existed and dst_entries[name].get("type") != kind:
                # type flip (file <-> dir <-> symlink) — judged against
                # the DESTINATION's actual type, so it triggers even
                # with no diff base: start clean
                await self._rm_tree(dst_p)
                existed = False
                prev_i = None
            if kind == "dir":
                if not existed:
                    try:
                        await self.dst.mkdir(dst_p)
                    except CephFSError as e:
                        if e.rc != -17:
                            raise
                await self._sync_tree(
                    src_p, dst_p,
                    f"{prev_dir}/{name}"
                    if prev_dir is not None and prev_i is not None
                    else None)
            elif kind == "symlink":
                target = await self.src.readlink(src_p)
                if existed:
                    try:
                        if await self.dst.readlink(dst_p) == target:
                            continue
                    except CephFSError:
                        pass
                    await self._rm_tree(dst_p)
                await self.dst.symlink(target, dst_p)
            else:  # file
                if existed and prev_i is not None and \
                        self._unchanged(prev_i, inode):
                    continue
                data = await self.src.read_file(src_p)
                await self.dst.write_file(dst_p, data)
                if len(data) < int(inode.get("size", 0)):
                    # sparse tail: size recorded past written blocks
                    await self.dst.truncate(dst_p,
                                            int(inode["size"]))
                self.files_copied += 1

    @staticmethod
    def _unchanged(prev_i: dict, cur_i: dict) -> bool:
        return (prev_i.get("ino") == cur_i.get("ino")
                and prev_i.get("size") == cur_i.get("size")
                and prev_i.get("mtime") == cur_i.get("mtime"))

    async def _rm_tree(self, path: str) -> None:
        try:
            st = await self.dst.stat(path)
        except CephFSError as e:
            if e.rc == ENOENT:
                return
            raise
        if st["type"] == "dir":
            for name in await self.dst.listdir(path):
                await self._rm_tree(f"{path}/{name}")
            await self.dst.rmdir(path)
        else:
            await self.dst.unlink(path)
        self.entries_deleted += 1


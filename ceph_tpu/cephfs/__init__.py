"""CephFS client role: POSIX-shaped filesystem over the cluster.

Reference parity: libcephfs / the kernel client
(/root/reference/src/libcephfs.cc, src/client/Client.cc): metadata ops
go to the MDS (MClientRequest), file DATA reads/writes go straight to
the OSDs as striped objects (Client::_read/_write via the Objecter,
filer/striper layout).  The MDS address is discovered from the
mds_lock object in the metadata pool (the MDSMap role).

CLIENT CAPS (Client.cc caps + mds/Locker.cc): metadata replies can
GRANT a capability on the inode ("r": cache attrs and serve stat/read
locally; "rw": additionally buffer dirty size/mtime and flush on
close/recall) — so a hot stat/read loop costs ZERO MDS round trips.
Coherence is recall-based: when another client's access conflicts, the
MDS sends MClientCaps revoke; this client folds its dirty attrs into
the ack and drops the cached entries.  Caps die with the MDS
connection (failover = start capless) and carry a TTL as a belt
against partitions where the recall cannot reach us.

File layout: fixed-block striping `fsdata.<ino:x>.<blockno:016x>` in
the data pool (file_layout_t object_size, default 4 MiB), sparse like
the reference (absent blocks read as zeros).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Set

from ceph_tpu.mds import (
    ADDR_ATTR,
    MDSMAP_OBJ,
    data_obj,
    owner_rank,
    rank_lock_obj,
)
from ceph_tpu.msg.messages import MClientCaps, MClientRequest
from ceph_tpu.rados.client import (
    IoCtx,
    ObjectNotFound,
    RadosClient,
    RadosError,
)

log = logging.getLogger("cephfs")

ENOENT = -2
ESTALE = -116
EROFS = -30


class CephFSError(Exception):
    def __init__(self, rc: int, what: str = ""):
        super().__init__(f"rc={rc} {what}")
        self.rc = rc


class CephFS:
    """Mounted filesystem handle (libcephfs ceph_mount role)."""

    def __init__(self, client: RadosClient, metadata_pool: str,
                 data_pool: str, caps_ttl: float = 60.0):
        self.client = client
        self.meta = client.open_ioctx(metadata_pool)
        self.data = client.open_ioctx(data_pool)
        self._tid = 0
        # one address per MDS RANK (multi-active subtree partitioning;
        # rank layout discovered from the mds_map object)
        self._mds_addrs: Dict[int, str] = {}
        self._num_ranks: Optional[int] = None
        # -- caps state (Client.cc cap cache) ------------------------------
        self.caps_ttl = caps_ttl
        self._caps: Dict[int, str] = {}            # ino -> "r"|"rw"
        self._cap_expiry: Dict[int, float] = {}    # ino -> monotonic
        # the Connection each cap was granted on: a silent reconnect
        # makes a NEW conn at the same addr, and the MDS evicted our
        # caps when the old one died — identity, not liveness, is the
        # validity test
        self._cap_conn: Dict[int, Any] = {}
        self._attr_cache: Dict[str, dict] = {}     # path -> inode
        self._ino_paths: Dict[int, Set[str]] = {}  # reverse index
        # ino -> buffered dirty attrs awaiting flush (rw caps only)
        self._dirty: Dict[int, Dict[str, Any]] = {}
        # snapid -> data-pool IoCtx reading at that snapshot
        self._snap_ios: Dict[int, IoCtx] = {}
        # snap-context version (regression guard): a reply from an MDS
        # rank that missed the snap fan-out must not downgrade a
        # fresher context another rank already gave us
        self._snapc_ver = 0
        # observability (tests assert the zero-round-trip property)
        self.mds_requests = 0
        self.cap_hits = 0
        # route cap recalls arriving on the shared rados messenger
        client.fs_caps_handler = self._handle_caps

    # -- caps cache (Client.cc insert_trace / handle_caps roles) -----------

    # bound on cached caps (the mds_max_caps_per_client role): a tree
    # walk over millions of files must not grow the mount's memory
    # forever — past the bound the soonest-expiring quarter is shed
    max_caps = 4096

    def _record_cap(self, path: str, inode: dict, cap: str,
                    conn: Any = None) -> None:
        """conn: the connection the reply that granted this cap rode in
        on (stamped into the reply by _request).  It must NOT be read
        from shared mutable state: a concurrent request can reconnect
        and rebind such state while this reply is in flight, and the
        cap would then pass the conn-identity check against a session
        the MDS never granted it on."""
        if not cap or not isinstance(inode, dict) or conn is None:
            return
        ino = inode["ino"]
        if ino not in self._caps and len(self._caps) >= self.max_caps:
            self._trim_caps()
        self._caps[ino] = cap
        self._cap_expiry[ino] = time.monotonic() + self.caps_ttl
        self._cap_conn[ino] = conn
        self._attr_cache[path] = inode
        self._ino_paths.setdefault(ino, set()).add(path)

    def _trim_caps(self) -> None:
        victims = sorted(self._cap_expiry,
                         key=self._cap_expiry.get)[:self.max_caps // 4]
        for ino in victims:
            if ino in self._dirty:
                continue  # never shed unflushed state
            # voluntary release goes to the conn the cap was granted
            # on (its rank's session)
            conn = self._cap_conn.get(ino)
            self._drop_ino(ino)
            if conn is not None and not conn.closed:
                # best-effort voluntary return so the MDS table shrinks
                # too and later writers skip a recall round trip
                try:
                    self.client.msgr._spawn(conn.send(
                        MClientCaps("release", ino)))
                except Exception:
                    pass

    def _drop_ino(self, ino: int) -> None:
        self._caps.pop(ino, None)
        self._cap_expiry.pop(ino, None)
        self._cap_conn.pop(ino, None)
        for path in self._ino_paths.pop(ino, set()):
            self._attr_cache.pop(path, None)

    def _drop_all_caps(self) -> None:
        self._caps.clear()
        self._cap_expiry.clear()
        self._cap_conn.clear()
        self._attr_cache.clear()
        self._ino_paths.clear()
        # dirty sizes survive — close()/flush() re-sends them through
        # the ordinary setattr path, which retries across failover

    def _cap_valid(self, ino: int) -> bool:
        """A cap is usable only while its TTL holds AND the connection
        it was granted on is alive — a dead conn means the MDS has
        already evicted us (or a new MDS knows nothing of us)."""
        if ino not in self._caps:
            return False
        if time.monotonic() > self._cap_expiry.get(ino, 0.0):
            self._drop_ino(ino)
            return False
        granted_on = self._cap_conn.get(ino)
        if granted_on is None or granted_on.closed or \
                self.client.msgr._conns.get(
                    granted_on.peer_addr) is not granted_on:
            # the granting connection is gone (or a reconnect minted a
            # new one): that MDS evicted us with it, so every cached
            # answer granted on it is suspect (other ranks' sessions
            # are independent and keep their caps)
            self._drop_conn_caps(granted_on)
            return False
        return True

    def _drop_conn_caps(self, conn) -> None:
        for ino in [i for i, c in self._cap_conn.items()
                    if c is conn or c is None]:
            self._drop_ino(ino)

    def _drop_addr_caps(self, addr: str) -> None:
        """Failover hygiene: a rank's address was re-discovered, so
        anything granted over connections to the OLD address came from
        a possibly-fenced incarnation — even if that conn is still
        open (a hung-but-connected deposed active must not keep
        serving stale cached attrs until TTL)."""
        for ino in [i for i, c in self._cap_conn.items()
                    if c is None or getattr(c, "peer_addr", None)
                    == addr]:
            self._drop_ino(ino)

    def _cached_inode(self, path: str) -> Optional[dict]:
        inode = self._attr_cache.get(path)
        if inode is not None and self._cap_valid(inode["ino"]):
            self.cap_hits += 1
            return inode
        return None

    async def _handle_caps(self, conn, msg: MClientCaps) -> None:
        """MDS-initiated recall: fold dirty attrs into the ack, drop
        the cache.  op=evict (MDS stepping down) drops everything, no
        ack expected."""
        if msg.op == "evict":
            self._drop_all_caps()
            return
        if msg.op != "revoke":
            return
        snapc = msg.attrs.get("snapc")
        if snapc is not None:
            # a recall after mksnap carries the fresh snap context —
            # arm it NOW so our next write clones, even with no
            # further MDS round trip
            self._apply_snapc(snapc)
        # the ack carries our dirty attrs INCLUDING the path: recalls
        # driven by a directory rename persist bystander flushes by
        # path while those paths still resolve
        attrs = self._dirty.pop(msg.ino, {})
        self._drop_ino(msg.ino)
        try:
            await conn.send(MClientCaps("ack", msg.ino, tid=msg.tid,
                                        attrs=attrs))
        except (ConnectionError, OSError):
            # conn died mid-ack: the MDS evicts us on timeout/fault,
            # but the buffered attrs never reached it — restore them
            # so close()/flush() re-sends through the ordinary path.
            # MERGE, never setdefault: a concurrent write during the
            # send may have re-dirtied the ino with a SMALLER size_max,
            # and dropping the older high-water mark would let the
            # eventual flush truncate acknowledged data
            if attrs:
                d = self._dirty.get(msg.ino)
                if d is None:
                    self._dirty[msg.ino] = attrs
                else:
                    d["size_max"] = max(
                        int(d.get("size_max", 0)),
                        int(attrs.get("size_max", 0)))
                    if d.get("mtime") is None and \
                            attrs.get("mtime") is not None:
                        d["mtime"] = attrs["mtime"]

    def _note_dirty(self, ino: int, path: str, size: int,
                    mtime: float) -> None:
        d = self._dirty.setdefault(ino, {"size_max": 0})
        d["size_max"] = max(int(d.get("size_max", 0)), size)
        d["mtime"] = mtime
        d["path"] = path

    async def _flush_dirty_path(self, path: str) -> None:
        """Flush any buffered attrs recorded FOR this path — keyed on
        the dirty table itself, not the attr cache, so a failover
        (which clears the cache but keeps dirty records) cannot skip
        the flush."""
        for ino, d in list(self._dirty.items()):
            if d.get("path") == path:
                await self._flush_dirty(ino)

    async def _flush_dirty(self, ino: int) -> None:
        """Push buffered size/mtime to the MDS (cap flush): done on
        close/fsync; recall-time flushes ride the ack instead."""
        d = self._dirty.pop(ino, None)
        if d is None:
            return
        args = {"path": d["path"], "size_max": d["size_max"]}
        if d.get("mtime") is not None:
            args["mtime"] = d["mtime"]
        try:
            await self._request("setattr", args)
        except CephFSError:
            pass  # path raced away (unlink/rename revoked us already)

    # -- MDS session -------------------------------------------------------

    async def _num_mds_ranks(self) -> int:
        """Rank-layout discovery (MDSMap role): published by the
        active MDS; absent on a still-booting cluster — fall back to
        single-active until it appears."""
        if self._num_ranks is not None:
            return self._num_ranks
        try:
            import json as _json

            raw = await self.meta.read(MDSMAP_OBJ)
            self._num_ranks = int(_json.loads(
                raw.decode()).get("num_ranks", 1))
        except Exception:
            return 1
        return self._num_ranks

    def _rank_of(self, op: str, args: Dict[str, Any],
                 num_ranks: int) -> int:
        """The rank serving this op: same parent-directory rule the
        daemons enforce (owner_rank); rename routes to the SRC owner,
        which coordinates the dst rank itself."""
        path = args.get("path") or args.get("src") or "/"
        return owner_rank(path, num_ranks)

    async def _discover_mds(self, rank: int = 0) -> str:
        for _ in range(100):
            try:
                raw = await self.meta.getxattr(rank_lock_obj(rank),
                                               ADDR_ATTR)
                return raw.decode()
            except (ObjectNotFound, RadosError):
                await asyncio.sleep(0.1)
        raise CephFSError(
            ESTALE, f"no active MDS for rank {rank} published"
                    " an address")

    async def _request(self, op: str, args: Dict[str, Any]
                       ) -> Dict[str, Any]:
        """Send one metadata op to the owning rank; on ESTALE/timeout
        re-discover and resend (Client session reconnect role)."""
        last: Optional[BaseException] = None
        self.mds_requests += 1
        # EAGAIN (subtree mid-migration) has its OWN budget: the
        # freeze can legitimately last up to the MDS's 30s export TTL
        # plus peer timeouts, far beyond the connection-retry budget
        eagain_left = 150  # x0.3s ~ 45s
        attempt = 0
        while attempt < 30:
            attempt += 1
            rank = self._rank_of(op, args, await self._num_mds_ranks())
            if rank not in self._mds_addrs:
                self._mds_addrs[rank] = await self._discover_mds(rank)
                # fresh discovery: whatever this rank granted was from
                # a possibly-dead incarnation — conn-identity checks
                # in _cap_valid retire those caps lazily
            # ride the rados client's messenger + future table:
            # MClientReply resolves through its dispatcher like any
            # other tid-matched reply
            tid = self.client._next_tid()
            fut: asyncio.Future = \
                asyncio.get_running_loop().create_future()
            self.client._futures[tid] = fut
            try:
                conn = await self.client.msgr.connect(
                    self._mds_addrs[rank])
                await conn.send(MClientRequest(tid, op, args))
                reply = await asyncio.wait_for(fut, 10.0)
            except (ConnectionError, OSError,
                    asyncio.TimeoutError) as e:
                last = e
                old = self._mds_addrs.pop(rank, None)  # re-discover
                if old is not None:
                    self._drop_addr_caps(old)
                await asyncio.sleep(0.3)
                continue
            finally:
                self.client._futures.pop(tid, None)
            if reply.rc == ESTALE:
                # standby answered, or the rank layout changed under
                # us (misrouted): re-discover both
                old = self._mds_addrs.pop(rank, None)
                if old is not None:
                    self._drop_addr_caps(old)
                self._num_ranks = None
                await asyncio.sleep(0.3)
                continue
            if reply.rc == -11 and eagain_left > 0:
                # EAGAIN: subtree frozen (migrating) — wait it out
                # without burning the connection-retry budget
                eagain_left -= 1
                attempt -= 1
                last = CephFSError(-11, "subtree migrating")
                await asyncio.sleep(0.3)
                continue
            if reply.rc != 0:
                raise CephFSError(reply.rc,
                                  f"{op} {args.get('path', '')!r}"
                                  f" {reply.out.get('error', '')}")
            dsnapc = reply.out.pop("_dsnapc", None)
            if dsnapc is not None:
                # the MDS publishes the data-pool snap context on
                # every reply: our direct-to-OSD writes must COW
                # against every live CephFS snapshot
                self._apply_snapc(dsnapc)
            self._trace_reply(op, args, reply.out)
            # stamp the conn this reply rode in on: any cap in the
            # reply was granted on THAT session (see _record_cap)
            reply.out["_conn"] = conn
            return reply.out
        raise CephFSError(ESTALE, f"{op}: no MDS reachable ({last!r})")

    def _trace_reply(self, op: str, args: Dict[str, Any],
                     out: Dict[str, Any]) -> None:
        """Fold a mutation's reply back into OUR cap cache (the
        insert_trace role): the MDS only recalls OTHER clients'
        caps, so our own cached attrs would go stale without this."""
        if op == "setattr":
            inode = out.get("inode")
            if inode and args["path"] in self._attr_cache:
                self._attr_cache[args["path"]] = inode
        elif op in ("unlink", "rmdir"):
            self._drop_path(args["path"])
        elif op == "rename":
            self._drop_path(args["src"])
            self._drop_path(args["dst"])

    def _drop_path(self, path: str) -> None:
        inode = self._attr_cache.get(path)
        if inode is not None:
            self._drop_ino(inode["ino"])

    # -- namespace ops -----------------------------------------------------

    @staticmethod
    def _snap_mkdir_target(path: str):
        """'/a/b/.snap/s1' -> ('/a/b', 's1') — mkdir/rmdir inside a
        .snap pseudo-directory IS snapshot create/remove (the
        reference's mkdir-on-snapdir semantics)."""
        parts = [p for p in path.split("/") if p]
        if len(parts) >= 2 and parts[-2] == ".snap":
            return "/" + "/".join(parts[:-2]), parts[-1]
        return None

    async def mkdir(self, path: str, mode: int = 0o755) -> None:
        snap = self._snap_mkdir_target(path)
        if snap is not None:
            await self.mksnap(snap[0], snap[1])
            return
        await self._request("mkdir", {"path": path, "mode": mode})

    async def rmdir(self, path: str) -> None:
        snap = self._snap_mkdir_target(path)
        if snap is not None:
            await self.rmsnap(snap[0], snap[1])
            return
        await self._request("rmdir", {"path": path})

    # -- snapshots (.snap pseudo-directory surface) ------------------------

    async def mksnap(self, path: str, name: str) -> int:
        out = await self._request("mksnap",
                                  {"path": path, "name": name})
        return out.get("snapid", 0)

    async def rmsnap(self, path: str, name: str) -> None:
        await self._request("rmsnap", {"path": path, "name": name})

    async def lssnap(self, path: str) -> List[dict]:
        out = await self._request("lssnap", {"path": path})
        return out["snaps"]

    def _apply_snapc(self, v) -> None:
        """[ver, seq, snaps] from an MDS: apply unless it would
        REGRESS the version — a rank that missed the snap fan-out
        serves a stale context, and downgrading would make our next
        write skip COW for a live snapshot."""
        if v[0] >= self._snapc_ver:
            self._snapc_ver = v[0]
            self.data.set_snap_context(v[1], v[2])

    def _snap_data_io(self, snapid: int) -> IoCtx:
        """Data-pool IoCtx reading at a snapshot (cached; snapshots
        are immutable)."""
        io = self._snap_ios.get(snapid)
        if io is None:
            if len(self._snap_ios) >= 64:
                self._snap_ios.clear()  # bounded: rebuilt on demand
            io = IoCtx(self.client, self.data.pool_id)
            io.snap_set_read(snapid)
            self._snap_ios[snapid] = io
        return io

    async def listdir(self, path: str) -> List[str]:
        out = await self._request("readdir", {"path": path})
        return list(out["entries"])

    async def readdir(self, path: str) -> Dict[str, dict]:
        out = await self._request("readdir", {"path": path})
        return out["entries"]

    async def stat(self, path: str) -> dict:
        cached = self._cached_inode(path)
        if cached is not None:
            return dict(cached)   # zero MDS round trips
        out = await self._request("stat", {"path": path, "want": "r"})
        self._record_cap(path, out["inode"], out.get("cap", ""),
                         out.get("_conn"))
        return out["inode"]

    async def exists(self, path: str) -> bool:
        try:
            await self.stat(path)
            return True
        except CephFSError as e:
            if e.rc == ENOENT:
                return False
            raise

    async def symlink(self, target: str, path: str) -> None:
        await self._request("symlink", {"path": path, "target": target})

    async def readlink(self, path: str) -> str:
        out = await self._request("readlink", {"path": path})
        return out["target"]

    async def rename(self, src: str, dst: str) -> None:
        # our own dirty size must land while the dentry still exists
        # at src (the MDS folds FOREIGN writers via recall; ours is
        # local knowledge it cannot recall mid-request)
        await self._flush_dirty_path(src)
        await self._request("rename", {"src": src, "dst": dst})

    async def unlink(self, path: str) -> None:
        # flush our own buffered size first: the MDS purges by size
        await self._flush_dirty_path(path)
        out = await self._request("unlink", {"path": path})
        inode = out["inode"]
        # purge the file's data objects (the client-driven purge;
        # the reference queues this on the MDS PurgeQueue)
        bs = inode.get("block_size", 1 << 22)
        blocks = (inode.get("size", 0) + bs - 1) // bs
        await asyncio.gather(*(
            _ignore_enoent(self.data.remove(
                data_obj(inode["ino"], b)))
            for b in range(blocks)))

    async def truncate(self, path: str, size: int) -> None:
        if ".snap" in path.split("/"):
            # guard BEFORE touching data objects: the snap-aware stat
            # below would resolve to the live ino and the head purge
            # would destroy the live file before the MDS said EROFS
            raise CephFSError(EROFS, path)
        await self._flush_dirty_path(path)
        inode = await self.stat(path)
        if inode["type"] != "file":
            raise CephFSError(-21, path)  # EISDIR
        bs = inode.get("block_size", 1 << 22)
        if size < inode["size"]:
            first_dead = (size + bs - 1) // bs
            last = (inode["size"] + bs - 1) // bs
            await asyncio.gather(*(
                _ignore_enoent(self.data.remove(
                    data_obj(inode["ino"], b)))
                for b in range(first_dead, last)))
            if size % bs:
                await self.data.write(
                    data_obj(inode["ino"], size // bs),
                    bytes(bs - size % bs), size % bs)
        await self._request("setattr", {"path": path, "size": size})

    # -- file I/O ----------------------------------------------------------

    async def open(self, path: str, flags: str = "r",
                   mode: int = 0o644,
                   block_size: int = 1 << 22) -> "File":
        """block_size is the file_layout_t object_size: fixed at
        create time, ignored on existing files."""
        create = any(f in flags for f in "wax")
        writable = create or "+" in flags
        if ".snap" in path.split("/") and (create or writable):
            raise CephFSError(EROFS, path)
        want = "rw" if writable else "r"
        if create:
            out = await self._request(
                "create", {"path": path, "mode": mode,
                           "exclusive": "x" in flags,
                           "block_size": block_size, "want": want})
            inode = out["inode"]
            self._record_cap(path, inode, out.get("cap", ""),
                             out.get("_conn"))
            if "w" in flags and inode.get("size", 0) > 0:
                await self.truncate(path, 0)
                inode = await self.stat(path)
        else:
            cached = self._cached_inode(path)
            if cached is not None and not writable:
                inode = dict(cached)
            else:
                out = await self._request(
                    "stat", {"path": path, "want": want})
                inode = out["inode"]
                self._record_cap(path, inode, out.get("cap", ""),
                                 out.get("_conn"))
            if inode["type"] == "dir":
                raise CephFSError(-21, path)
        return File(self, path, inode, writable=writable)

    # convenience one-shots (qa-workunit style helpers)

    async def write_file(self, path: str, data: bytes) -> None:
        f = await self.open(path, "w")
        await f.write(0, data)
        await f.close()

    async def read_file(self, path: str) -> bytes:
        f = await self.open(path, "r")
        try:
            # read() revalidates and clamps to the CURRENT size
            return await f.read(0, 1 << 62)
        finally:
            await f.close()


async def _ignore_enoent(coro) -> None:
    try:
        await coro
    except ObjectNotFound:
        pass


class File:
    """An open file handle (Fh role): offset I/O over striped data
    objects, size flushed to the MDS on write/close."""

    def __init__(self, fs: CephFS, path: str, inode: dict,
                 writable: bool):
        self.fs = fs
        self.path = path
        self.inode = inode
        self.writable = writable
        self._max_written = inode.get("size", 0)

    @property
    def block_size(self) -> int:
        return self.inode.get("block_size", 1 << 22)

    def _extents(self, offset: int, length: int):
        out = []
        end = offset + length
        while offset < end:
            blockno = offset // self.block_size
            in_off = offset % self.block_size
            span = min(self.block_size - in_off, end - offset)
            out.append((blockno, in_off, span))
            offset += span
        return out

    async def _revalidate(self) -> None:
        """Refresh the inode before trusting its size: served from the
        cap cache when we still hold the cap (zero round trips), else
        re-stat — a revoke since open means someone changed it."""
        cached = self.fs._cached_inode(self.path)
        if cached is not None:
            self.inode = cached
        else:
            self.inode = await self.fs.stat(self.path)

    async def read(self, offset: int, length: int) -> bytes:
        await self._revalidate()
        size = self.inode.get("size", 0)
        if offset >= size:
            return b""
        length = min(length, size - offset)

        # a snapshot inode reads its data AT the snapshot's snapid
        snapid = self.inode.get("snapid", 0)
        io = self.fs._snap_data_io(snapid) if snapid else self.fs.data

        async def one(blockno: int, in_off: int, span: int) -> bytes:
            try:
                buf = await io.read(
                    data_obj(self.inode["ino"], blockno), in_off, span)
            except ObjectNotFound:
                return bytes(span)
            if len(buf) < span:
                buf += bytes(span - len(buf))
            return buf

        parts = await asyncio.gather(
            *(one(*ext) for ext in self._extents(offset, length)))
        return b"".join(parts)

    async def write(self, offset: int, data: bytes) -> int:
        if not self.writable:
            raise CephFSError(EROFS, self.path)
        pos = 0
        jobs = []
        for blockno, in_off, span in self._extents(offset, len(data)):
            chunk = data[pos:pos + span]
            pos += span
            jobs.append(self.fs.data.write(
                data_obj(self.inode["ino"], blockno), chunk, in_off))
        await asyncio.gather(*jobs)
        end = offset + len(data)
        if end > self._max_written:
            self._max_written = end
            now = time.time()
            ino = self.inode["ino"]
            if self.fs._caps.get(ino) == "rw" and \
                    self.fs._cap_valid(ino):
                # rw cap held: BUFFER the size locally (the Fw dirty-
                # caps discipline) — no MDS round trip per write.  It
                # flushes on close/fsync, or rides the revoke ack if
                # another client conflicts first.
                if end > self.inode.get("size", 0):
                    self.inode = dict(self.inode, size=end, mtime=now)
                    self.fs._attr_cache[self.path] = self.inode
                self.fs._note_dirty(ino, self.path, end, now)
            else:
                # capless: write-through size flush, max-merged on the
                # MDS so concurrent writers never shrink each other
                out = await self.fs._request(
                    "setattr", {"path": self.path, "size_max": end})
                self.inode = out["inode"]
        return len(data)

    async def append(self, data: bytes) -> int:
        await self._revalidate()
        return await self.write(self.inode.get("size", 0), data)

    async def flush(self) -> None:
        """fsync-of-metadata: push any buffered size/mtime now."""
        await self.fs._flush_dirty(self.inode["ino"])

    async def close(self) -> None:
        ino = self.inode["ino"]
        await self.fs._flush_dirty(ino)
        if self.writable and self.fs._caps.get(ino) == "rw":
            # voluntarily return the exclusive cap so other clients'
            # opens don't pay a recall round trip (dirty already
            # flushed above, so the release carries nothing) — to the
            # conn it was granted on (that rank's session)
            conn = self.fs._cap_conn.get(ino)
            self.fs._drop_ino(ino)
            if conn is not None and not conn.closed:
                try:
                    await conn.send(MClientCaps("release", ino))
                except (ConnectionError, OSError):
                    pass

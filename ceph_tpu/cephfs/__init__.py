"""CephFS client role: POSIX-shaped filesystem over the cluster.

Reference parity: libcephfs / the kernel client
(/root/reference/src/libcephfs.cc, src/client/Client.cc): metadata ops
go to the MDS (MClientRequest), file DATA reads/writes go straight to
the OSDs as striped objects (Client::_read/_write via the Objecter,
filer/striper layout).  The MDS address is discovered from the
mds_lock object in the metadata pool (the MDSMap role).

File layout: fixed-block striping `fsdata.<ino:x>.<blockno:016x>` in
the data pool (file_layout_t object_size, default 4 MiB), sparse like
the reference (absent blocks read as zeros).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ceph_tpu.mds import ADDR_ATTR, LOCK_OBJ, data_obj
from ceph_tpu.msg.messages import MClientRequest
from ceph_tpu.rados.client import (
    IoCtx,
    ObjectNotFound,
    RadosClient,
    RadosError,
)

log = logging.getLogger("cephfs")

ENOENT = -2
ESTALE = -116
EROFS = -30


class CephFSError(Exception):
    def __init__(self, rc: int, what: str = ""):
        super().__init__(f"rc={rc} {what}")
        self.rc = rc


class CephFS:
    """Mounted filesystem handle (libcephfs ceph_mount role)."""

    def __init__(self, client: RadosClient, metadata_pool: str,
                 data_pool: str):
        self.client = client
        self.meta = client.open_ioctx(metadata_pool)
        self.data = client.open_ioctx(data_pool)
        self._tid = 0
        self._mds_addr: Optional[str] = None

    # -- MDS session -------------------------------------------------------

    async def _discover_mds(self) -> str:
        for _ in range(100):
            try:
                raw = await self.meta.getxattr(LOCK_OBJ, ADDR_ATTR)
                return raw.decode()
            except (ObjectNotFound, RadosError):
                await asyncio.sleep(0.1)
        raise CephFSError(ESTALE, "no active MDS published an address")

    async def _request(self, op: str, args: Dict[str, Any]
                       ) -> Dict[str, Any]:
        """Send one metadata op; on ESTALE/timeout re-discover the
        active MDS and resend (Client session reconnect role)."""
        last: Optional[BaseException] = None
        for attempt in range(30):
            if self._mds_addr is None:
                self._mds_addr = await self._discover_mds()
            # ride the rados client's messenger + future table:
            # MClientReply resolves through its dispatcher like any
            # other tid-matched reply
            tid = self.client._next_tid()
            fut: asyncio.Future = \
                asyncio.get_running_loop().create_future()
            self.client._futures[tid] = fut
            try:
                await self.client.msgr.send_to(
                    self._mds_addr, MClientRequest(tid, op, args))
                reply = await asyncio.wait_for(fut, 10.0)
            except (ConnectionError, OSError,
                    asyncio.TimeoutError) as e:
                last = e
                self._mds_addr = None   # re-discover (failover)
                await asyncio.sleep(0.3)
                continue
            finally:
                self.client._futures.pop(tid, None)
            if reply.rc == ESTALE:
                self._mds_addr = None   # standby answered: re-discover
                await asyncio.sleep(0.3)
                continue
            if reply.rc != 0:
                raise CephFSError(reply.rc,
                                  f"{op} {args.get('path', '')!r}"
                                  f" {reply.out.get('error', '')}")
            return reply.out
        raise CephFSError(ESTALE, f"{op}: no MDS reachable ({last!r})")

    # -- namespace ops -----------------------------------------------------

    async def mkdir(self, path: str, mode: int = 0o755) -> None:
        await self._request("mkdir", {"path": path, "mode": mode})

    async def rmdir(self, path: str) -> None:
        await self._request("rmdir", {"path": path})

    async def listdir(self, path: str) -> List[str]:
        out = await self._request("readdir", {"path": path})
        return list(out["entries"])

    async def readdir(self, path: str) -> Dict[str, dict]:
        out = await self._request("readdir", {"path": path})
        return out["entries"]

    async def stat(self, path: str) -> dict:
        out = await self._request("stat", {"path": path})
        return out["inode"]

    async def exists(self, path: str) -> bool:
        try:
            await self.stat(path)
            return True
        except CephFSError as e:
            if e.rc == ENOENT:
                return False
            raise

    async def symlink(self, target: str, path: str) -> None:
        await self._request("symlink", {"path": path, "target": target})

    async def readlink(self, path: str) -> str:
        out = await self._request("readlink", {"path": path})
        return out["target"]

    async def rename(self, src: str, dst: str) -> None:
        await self._request("rename", {"src": src, "dst": dst})

    async def unlink(self, path: str) -> None:
        out = await self._request("unlink", {"path": path})
        inode = out["inode"]
        # purge the file's data objects (the client-driven purge;
        # the reference queues this on the MDS PurgeQueue)
        bs = inode.get("block_size", 1 << 22)
        blocks = (inode.get("size", 0) + bs - 1) // bs
        await asyncio.gather(*(
            _ignore_enoent(self.data.remove(
                data_obj(inode["ino"], b)))
            for b in range(blocks)))

    async def truncate(self, path: str, size: int) -> None:
        inode = await self.stat(path)
        if inode["type"] != "file":
            raise CephFSError(-21, path)  # EISDIR
        bs = inode.get("block_size", 1 << 22)
        if size < inode["size"]:
            first_dead = (size + bs - 1) // bs
            last = (inode["size"] + bs - 1) // bs
            await asyncio.gather(*(
                _ignore_enoent(self.data.remove(
                    data_obj(inode["ino"], b)))
                for b in range(first_dead, last)))
            if size % bs:
                await self.data.write(
                    data_obj(inode["ino"], size // bs),
                    bytes(bs - size % bs), size % bs)
        await self._request("setattr", {"path": path, "size": size})

    # -- file I/O ----------------------------------------------------------

    async def open(self, path: str, flags: str = "r",
                   mode: int = 0o644,
                   block_size: int = 1 << 22) -> "File":
        """block_size is the file_layout_t object_size: fixed at
        create time, ignored on existing files."""
        create = any(f in flags for f in "wax")
        if create:
            out = await self._request(
                "create", {"path": path, "mode": mode,
                           "exclusive": "x" in flags,
                           "block_size": block_size})
            inode = out["inode"]
            if "w" in flags and inode.get("size", 0) > 0:
                await self.truncate(path, 0)
                inode = await self.stat(path)
        else:
            inode = await self.stat(path)
            if inode["type"] == "dir":
                raise CephFSError(-21, path)
        return File(self, path, inode,
                    writable=create or "+" in flags)

    # convenience one-shots (qa-workunit style helpers)

    async def write_file(self, path: str, data: bytes) -> None:
        f = await self.open(path, "w")
        await f.write(0, data)
        await f.close()

    async def read_file(self, path: str) -> bytes:
        f = await self.open(path, "r")
        try:
            return await f.read(0, f.inode["size"])
        finally:
            await f.close()


async def _ignore_enoent(coro) -> None:
    try:
        await coro
    except ObjectNotFound:
        pass


class File:
    """An open file handle (Fh role): offset I/O over striped data
    objects, size flushed to the MDS on write/close."""

    def __init__(self, fs: CephFS, path: str, inode: dict,
                 writable: bool):
        self.fs = fs
        self.path = path
        self.inode = inode
        self.writable = writable
        self._max_written = inode.get("size", 0)

    @property
    def block_size(self) -> int:
        return self.inode.get("block_size", 1 << 22)

    def _extents(self, offset: int, length: int):
        out = []
        end = offset + length
        while offset < end:
            blockno = offset // self.block_size
            in_off = offset % self.block_size
            span = min(self.block_size - in_off, end - offset)
            out.append((blockno, in_off, span))
            offset += span
        return out

    async def read(self, offset: int, length: int) -> bytes:
        size = self.inode.get("size", 0)
        if offset >= size:
            return b""
        length = min(length, size - offset)

        async def one(blockno: int, in_off: int, span: int) -> bytes:
            try:
                buf = await self.fs.data.read(
                    data_obj(self.inode["ino"], blockno), in_off, span)
            except ObjectNotFound:
                return bytes(span)
            if len(buf) < span:
                buf += bytes(span - len(buf))
            return buf

        parts = await asyncio.gather(
            *(one(*ext) for ext in self._extents(offset, length)))
        return b"".join(parts)

    async def write(self, offset: int, data: bytes) -> int:
        if not self.writable:
            raise CephFSError(EROFS, self.path)
        pos = 0
        jobs = []
        for blockno, in_off, span in self._extents(offset, len(data)):
            chunk = data[pos:pos + span]
            pos += span
            jobs.append(self.fs.data.write(
                data_obj(self.inode["ino"], blockno), chunk, in_off))
        await asyncio.gather(*jobs)
        end = offset + len(data)
        if end > self._max_written:
            self._max_written = end
            # size flush: max-merge on the MDS so concurrent writers
            # never shrink each other
            out = await self.fs._request(
                "setattr", {"path": self.path, "size_max": end})
            self.inode = out["inode"]
        return len(data)

    async def append(self, data: bytes) -> int:
        return await self.write(self.inode.get("size", 0), data)

    async def close(self) -> None:
        return None  # write-through: nothing buffered

"""`rados` CLI parity: object I/O + pool admin against a live cluster.

Reference: /root/reference/src/tools/rados/rados.cc — the workhorse
admin CLI: put/get/rm/ls/stat/append, xattr and omap surfaces,
mkpool/lspools, bench, plus `ceph`-style mon/osd commands (`status`,
`health`, `tell`).  One process, one command, JSON-friendly output.

Usage examples:
  python -m ceph_tpu.tools.rados -m HOST:PORT lspools
  python -m ceph_tpu.tools.rados -m HOST:PORT mkpool data --size 3
  python -m ceph_tpu.tools.rados -m HOST:PORT -p data put obj ./file
  python -m ceph_tpu.tools.rados -m HOST:PORT -p data get obj -
  python -m ceph_tpu.tools.rados -m HOST:PORT -p data ls
  python -m ceph_tpu.tools.rados -m HOST:PORT status
  python -m ceph_tpu.tools.rados -m HOST:PORT tell 0 perf dump
  python -m ceph_tpu.tools.rados -m HOST:PORT -p data bench 5 write
  python -m ceph_tpu.tools.rados -m HOST:PORT -p data scan gf_fold
  python -m ceph_tpu.tools.rados -m HOST:PORT -p data scan count \\
      --args '{"record":8,"cmp":"lt","value":10}'
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

import numpy as np

from ceph_tpu.rados.client import RadosClient, RadosError
from ceph_tpu.tools import fileio


def _out(obj) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True))


async def _run(args) -> int:
    secret = args.secret
    if not secret and args.keyring:
        secret = (await fileio.read_text(args.keyring)).strip()
    client = RadosClient(args.mon, secret=secret or None)
    await client.connect()
    try:
        return await _dispatch(client, args)
    finally:
        await client.shutdown()


async def _dispatch(client: RadosClient, args) -> int:
    cmd = args.cmd
    if cmd == "lspools":
        for pool in client.osdmap.pools.values():
            print(pool.name)
        return 0
    if cmd == "mkpool":
        if args.ec_profile:
            profile = json.loads(args.ec_profile)
            await client.create_ec_pool(args.name, profile,
                                        pg_num=args.pg_num)
        else:
            await client.create_replicated_pool(
                args.name, size=args.size, pg_num=args.pg_num)
        return 0
    if cmd == "status" or cmd == "health":
        rc, out = await client.mon_command({"prefix": cmd})
        _out(out)
        return 0 if rc == 0 else 1
    if cmd == "crash":
        rc, out = await client.mon_command(
            {"prefix": f"crash {args.verb}", "id": args.id})
        _out(out)
        return 0 if rc == 0 else 1
    if cmd == "df":
        _out(await client.df())
        return 0
    if cmd == "tell":
        rc, out = await client.osd_command(
            args.osd, {"prefix": " ".join(args.tell_cmd)})
        _out(out)
        return 0 if rc == 0 else 1

    # object commands need a pool
    if not args.pool:
        print("error: -p/--pool required", file=sys.stderr)
        return 2
    io = client.open_ioctx(args.pool)
    if cmd == "put":
        data = await fileio.read_stdin() if args.file == "-" else \
            await fileio.read_bytes(args.file)
        await io.write_full(args.obj, data)
        return 0
    if cmd == "get":
        data = await io.read(args.obj)
        if args.file == "-":
            sys.stdout.buffer.write(data)
        else:
            await fileio.write_bytes(args.file, data)
        return 0
    if cmd == "append":
        data = await fileio.read_stdin() if args.file == "-" else \
            await fileio.read_bytes(args.file)
        await io.append(args.obj, data)
        return 0
    if cmd == "rm":
        await io.remove(args.obj)
        return 0
    if cmd == "ls":
        for name in await io.list_objects():
            print(name)
        return 0
    if cmd == "stat":
        _out(await io.stat(args.obj))
        return 0
    if cmd == "setxattr":
        await io.setxattr(args.obj, args.name, args.value.encode())
        return 0
    if cmd == "getxattr":
        sys.stdout.buffer.write(await io.getxattr(args.obj, args.name))
        return 0
    if cmd == "listxattr":
        for k in sorted(await io.getxattrs(args.obj)):
            print(k)
        return 0
    if cmd == "setomapval":
        await io.omap_set(args.obj, {args.name: args.value.encode()})
        return 0
    if cmd == "listomapvals":
        for k, v in sorted((await io.omap_get(args.obj)).items()):
            print(f"{k}: {v.decode('latin-1')}")
        return 0
    if cmd == "scan":
        return await _scan(io, args)
    if cmd == "bench":
        return await _bench(io, args)
    print(f"error: unknown command {cmd!r}", file=sys.stderr)
    return 2


async def _scan(io, args) -> int:
    """`rados scan <kernel> [obj ...]` — the coded-compute front
    door: run a registered kernel over the named objects (default:
    every object in the pool) where they live, print per-object
    results.  Linear kernels (gf_fold, gf_fingerprint) print hex
    digests; JSON-result kernels (count/sum/min/max/filter,
    compress_score, dot_score) print decoded JSON."""
    kargs = json.loads(args.kernel_args) if args.kernel_args else None
    oids = args.objs or await io.list_objects()
    if not oids:
        _out({"results": {}, "errors": {}})
        return 0
    results, errors = await io.compute(args.kernel, oids, kargs)
    rendered = {}
    for oid, res in sorted(results.items()):
        try:
            rendered[oid] = json.loads(res)
        except (ValueError, UnicodeDecodeError):
            rendered[oid] = bytes(res).hex()
    _out({"kernel": args.kernel,
          "results": rendered,
          "errors": {k: v for k, v in sorted(errors.items())}})
    return 0 if not errors else 1


def zipf_indices(theta: float, n: int, count: int,
                 seed: int = 0) -> np.ndarray:
    """Deterministic Zipf(theta) sample of `count` object ranks in
    [0, n): P(rank i) ∝ 1/(i+1)^theta, rank 0 hottest.  theta=0 is
    uniform.  Seeded rng so bench legs (and the tier regression tests
    built on them) are reproducible."""
    ranks = np.arange(1, max(int(n), 1) + 1, dtype=np.float64)
    weights = ranks ** -float(theta)
    cdf = np.cumsum(weights)
    cdf /= cdf[-1]
    u = np.random.default_rng(seed).random(int(count))
    return np.searchsorted(cdf, u).astype(np.int64)


async def _bench(io, args) -> int:
    """`rados bench <seconds> write|seq` (rados.cc bench role).

    `seq --read-skew <theta>` runs the skewed-read leg: prefill
    --objects objects, then hammer them with a deterministic
    Zipf(theta) index stream — the workload shape that demonstrates
    (and regression-tests) read-tier hit rates.

    `--tenants N` switches the bench to the OPEN-LOOP multi-tenant
    harness (ceph_tpu/loadgen): N simulated tenants fire ops on a
    Poisson schedule at --arrival-rate ops/s each with the --blend
    op mix, latency measured from scheduled arrival (queueing delay
    counted), goodput + streaming p50/p95/p99 reported."""
    if getattr(args, "tenants", 0) > 0:
        return await _bench_loadgen(io, args)
    size = args.block_size
    payload = np.random.default_rng(0).integers(
        0, 256, size, dtype=np.uint8).tobytes()
    deadline = time.monotonic() + args.seconds
    done = [0]

    theta = float(getattr(args, "read_skew", 0.0) or 0.0)
    if args.mode == "seq" and theta > 0:
        n_objs = int(args.objects)
        for i in range(n_objs):
            await io.write_full(f"bench_z_{i}", payload)
        # the measurement window opens AFTER the prefill: writing
        # --objects payloads must not eat into the read leg
        deadline = time.monotonic() + args.seconds

        async def skewed_reader(slot: int) -> None:
            idx = zipf_indices(theta, n_objs, 65536,
                               seed=int(args.seed) + slot)
            pos = 0
            while time.monotonic() < deadline:
                i = int(idx[pos % len(idx)])
                pos += 1
                await io.read(f"bench_z_{i}")
                done[0] += 1

        t0 = time.monotonic()
        await asyncio.gather(*(skewed_reader(s)
                               for s in range(args.concurrency)))
        secs = max(time.monotonic() - t0, 1e-9)
        _out({"mode": "seq", "read_skew": theta, "objects": n_objs,
              "ops": done[0], "seconds": round(secs, 3),
              "ops_per_sec": round(done[0] / secs, 2),
              "mib_per_sec": round(done[0] * size / secs / (1 << 20),
                                   2)})
        return 0

    async def writer(slot: int) -> None:
        i = 0
        while time.monotonic() < deadline:
            await io.write_full(f"bench_{slot}_{i}", payload)
            done[0] += 1
            i += 1

    async def reader(slot: int) -> None:
        i = 0
        while time.monotonic() < deadline:
            try:
                await io.read(f"bench_{slot}_{i}")
            except RadosError:
                i = 0
                continue
            done[0] += 1
            i += 1

    t0 = time.monotonic()
    fn = writer if args.mode == "write" else reader
    await asyncio.gather(*(fn(s) for s in range(args.concurrency)))
    secs = time.monotonic() - t0
    _out({"mode": args.mode, "ops": done[0], "seconds": round(secs, 3),
          "ops_per_sec": round(done[0] / secs, 2),
          "mib_per_sec": round(done[0] * size / secs / (1 << 20), 2)})
    return 0


async def _bench_loadgen(io, args) -> int:
    """Open-loop multi-tenant leg: the CLI front door onto the
    loadgen subsystem (ceph_tpu/loadgen)."""
    from ceph_tpu.loadgen import (
        RadosTarget, make_tenants, parse_blend, run_open_loop,
    )

    blend = parse_blend(getattr(args, "blend", "") or "")
    # --read-skew is the tenants' zipf theta here, taken literally:
    # an explicit 0 means uniform popularity (same semantics as the
    # closed-loop skewed-read leg)
    tenants = make_tenants(
        int(args.tenants), rate=float(args.arrival_rate),
        blend=blend, zipf_theta=float(args.read_skew),
        objects=int(args.objects), object_size=int(args.block_size))
    target = RadosTarget(io)
    await target.setup(int(args.objects), int(args.block_size))
    report = await run_open_loop(target, tenants,
                                 duration=float(args.seconds),
                                 seed=int(args.seed))
    _out({"mode": "loadgen", "blend": blend, **report})
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("-m", "--mon", required=True,
                    help="mon address host:port")
    ap.add_argument("-p", "--pool", default="")
    ap.add_argument("--secret", default="",
                    help="cephx-lite hex secret for a keyed cluster")
    ap.add_argument("-k", "--keyring", default="",
                    help="file holding the hex secret")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("lspools")
    mk = sub.add_parser("mkpool")
    mk.add_argument("name")
    mk.add_argument("--size", type=int, default=3)
    mk.add_argument("--pg-num", type=int, default=32)
    mk.add_argument("--ec-profile", default="",
                    help="JSON EC profile (makes an EC pool)")
    sub.add_parser("status")
    sub.add_parser("health")
    sub.add_parser("df")
    cr = sub.add_parser("crash")
    cr.add_argument("verb", choices=["ls", "ls-new", "info",
                                     "archive", "archive-all", "rm"])
    cr.add_argument("id", nargs="?", default="")
    tell = sub.add_parser("tell")
    tell.add_argument("osd", type=int)
    tell.add_argument("tell_cmd", nargs="+")
    for name in ("put", "get", "append"):
        p = sub.add_parser(name)
        p.add_argument("obj")
        p.add_argument("file")
    for name in ("rm", "stat", "listxattr", "listomapvals"):
        p = sub.add_parser(name)
        p.add_argument("obj")
    sub.add_parser("ls")
    for name in ("setxattr", "setomapval"):
        p = sub.add_parser(name)
        p.add_argument("obj")
        p.add_argument("name")
        p.add_argument("value")
    gx = sub.add_parser("getxattr")
    gx.add_argument("obj")
    gx.add_argument("name")
    scan = sub.add_parser("scan")
    scan.add_argument("kernel",
                      help="registered compute kernel (gf_fold,"
                           " gf_fingerprint, count, sum, min, max,"
                           " filter, compress_score, dot_score)")
    scan.add_argument("objs", nargs="*",
                      help="objects to scan (default: whole pool)")
    scan.add_argument("--args", default="", dest="kernel_args",
                      help="kernel args as JSON, e.g."
                           " '{\"record\":8,\"cmp\":\"lt\","
                           "\"value\":10}'")
    bench = sub.add_parser("bench")
    bench.add_argument("seconds", type=int)
    bench.add_argument("mode", choices=["write", "seq"])
    bench.add_argument("-b", "--block-size", type=int,
                       default=4 << 20)
    bench.add_argument("-t", "--concurrency", type=int, default=16)
    bench.add_argument("--read-skew", type=float, default=0.0,
                       dest="read_skew", metavar="THETA",
                       help="seq mode: zipfian read skew exponent"
                            " (0 = uniform scan)")
    bench.add_argument("--objects", type=int, default=64,
                       help="seq --read-skew: prefilled object count")
    bench.add_argument("--seed", type=int, default=0,
                       help="seq --read-skew: deterministic rng seed")
    bench.add_argument("--tenants", type=int, default=0,
                       help="open-loop mode: number of simulated"
                            " tenants (0 = classic closed-loop"
                            " bench)")
    bench.add_argument("--arrival-rate", type=float, default=2.0,
                       dest="arrival_rate", metavar="OPS_PER_SEC",
                       help="open-loop mode: per-tenant Poisson"
                            " arrival rate")
    bench.add_argument("--blend", default="",
                       help="open-loop mode: op mix, e.g."
                            " read=0.7,write=0.2,stat=0.1"
                            " (kinds: read write stat ranged)")
    args = ap.parse_args(argv)
    try:
        return asyncio.run(_run(args))
    except RadosError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""ceph_erasure_code_benchmark parity CLI.

Reference: /root/reference/src/test/erasure-code/ceph_erasure_code_benchmark.cc
— same flags (-p/-w/-s/-i/-e/--erased/-E/-P/-v), same output contract: one
line `<seconds>\t<KiB processed>` so qa/workunits/erasure-code/bench.sh can
drive this tool unchanged.

Extension over the reference: --plan-cache (default) / --no-plan-cache
toggles the ExecPlan dispatch cache (ceph_tpu.ec.plan) so the win is
measurable from the CLI; plan-cache hit/miss/retrace counters print to
stderr after the timing line (stdout keeps the reference contract).
"""

from __future__ import annotations

import argparse
import itertools
import random
import sys
import time
from typing import Dict, List

from ceph_tpu.ec.registry import ErasureCodePluginRegistry


def parse_args(argv: List[str]) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="ceph_erasure_code_benchmark")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-s", "--size", type=int, default=1024 * 1024,
                   help="size of the buffer to be encoded")
    p.add_argument("-i", "--iterations", type=int, default=1,
                   help="number of encode/decode runs")
    p.add_argument("-p", "--plugin", default="jerasure",
                   help="erasure code plugin name")
    p.add_argument("-w", "--workload", default="encode",
                   choices=("encode", "decode"))
    p.add_argument("-e", "--erasures", type=int, default=1,
                   help="number of erasures when decoding")
    p.add_argument("--erased", type=int, action="append", default=[],
                   help="erased chunk (repeat)")
    p.add_argument("-E", "--erasures-generation", default="random",
                   choices=("random", "exhaustive"), dest="erasures_generation")
    p.add_argument("-P", "--parameter", action="append", default=[],
                   help="add a profile parameter key=value")
    p.add_argument("--plan-cache", dest="plan_cache",
                   action="store_true", default=None,
                   help="dispatch through the ExecPlan cache "
                        "(the default unless the CEPH_TPU_PLAN_CACHE=0 "
                        "kill switch is set; see ceph_tpu.ec.plan)")
    p.add_argument("--no-plan-cache", dest="plan_cache",
                   action="store_false",
                   help="bypass the plan cache: every shape "
                        "dispatches/retraces exactly as requested")
    return p.parse_args(argv)


def build_profile(args: argparse.Namespace) -> Dict[str, str]:
    profile: Dict[str, str] = {"plugin": args.plugin}
    for param in args.parameter:
        if "=" not in param:
            raise SystemExit(f"parameter {param!r} is not in key=value form")
        key, val = param.split("=", 1)
        profile[key] = val
    return profile


def display_chunks(chunks, chunk_count: int) -> None:
    out = "chunks "
    for chunk in range(chunk_count):
        out += f"({chunk})  " if chunk not in chunks else f" {chunk}   "
    print(out + "(X) is an erased chunk")


def _decode_and_check(codec, all_chunks, chunks) -> None:
    want = {c for c in range(codec.get_chunk_count()) if c not in chunks}
    decoded = codec.decode(want, chunks)
    for c in want:
        if decoded[c] != all_chunks[c]:
            raise SystemExit(
                f"chunk {c} content and recovered content are different")


def run(argv: List[str]) -> int:
    args = parse_args(argv)
    from ceph_tpu.ec import plan

    # tri-state: an explicit flag overrides for this run only; no flag
    # leaves the process state (incl. the CEPH_TPU_PLAN_CACHE=0 kill
    # switch) untouched
    was_enabled = (plan.set_enabled(args.plan_cache)
                   if args.plan_cache is not None else None)
    plan.reset_stats()
    try:
        return _run_timed(args)
    finally:
        stats = plan.stats()
        print(f"plan-cache: enabled={plan.enabled()}"
              f" hits={stats['hits']} misses={stats['misses']}"
              f" retraces={stats['retraces']}", file=sys.stderr)
        if was_enabled is not None:
            plan.set_enabled(was_enabled)


def _run_timed(args: argparse.Namespace) -> int:
    profile = build_profile(args)
    codec = ErasureCodePluginRegistry.instance().factory(
        args.plugin, profile)
    n = codec.get_chunk_count()
    data = b"X" * args.size
    want_all = set(range(n))

    if args.workload == "encode":
        begin = time.perf_counter()
        for _ in range(args.iterations):
            codec.encode(want_all, data)
        elapsed = time.perf_counter() - begin
    else:
        encoded = codec.encode(want_all, data)
        full = dict(encoded)
        if args.erased:
            for e in args.erased:
                encoded.pop(e, None)
            display_chunks(encoded, n)
        begin = time.perf_counter()
        for _ in range(args.iterations):
            if args.erasures_generation == "exhaustive":
                for erased in itertools.combinations(
                        sorted(encoded), args.erasures):
                    chunks = {c: b for c, b in encoded.items()
                              if c not in erased}
                    if args.verbose:
                        display_chunks(chunks, n)
                    _decode_and_check(codec, full, chunks)
            elif args.erased:
                _decode_and_check(codec, full, encoded)
            else:
                chunks = dict(encoded)
                for _j in range(args.erasures):
                    erasure = random.choice(sorted(chunks))
                    del chunks[erasure]
                _decode_and_check(codec, encoded, chunks)
        elapsed = time.perf_counter() - begin

    print(f"{elapsed:.6f}\t{args.iterations * (args.size // 1024)}")
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()

"""CLI tools mirroring the reference's operator/test surface:

- erasure_code_benchmark  (ceph_erasure_code_benchmark)
- erasure_code_tool       (ceph-erasure-code-tool)
- non_regression          (ceph_erasure_code_non_regression)
- crushtool               (crushtool)

Run as `python -m ceph_tpu.tools.<name> ...` with the reference's flags.
"""

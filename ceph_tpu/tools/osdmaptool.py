"""osdmaptool parity CLI.

Reference: /root/reference/src/tools/osdmaptool.cc — create/inspect/
simulate OSDMaps offline: --createsimple, --print, --test-map-pg,
--test-map-pgs[-dump] (PG->OSD distribution with per-OSD counts),
--mark-up-in, --export-crush/--import-crush, --upmap-cleanup analogs.
Compiled maps use this framework's versioned binary encoding
(ceph_tpu.common.encoding).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

import numpy as np

from ceph_tpu.osd.osdmap import (
    CEPH_OSD_EXISTS,
    CEPH_OSD_IN,
    CEPH_OSD_UP,
    OSDMap,
    OSDMapMapping,
    PgId,
    TYPE_REPLICATED,
)


def _load(path: str) -> OSDMap:
    with open(path, "rb") as f:
        return OSDMap.decode(f.read())


def _save(m: OSDMap, path: str) -> None:
    with open(path, "wb") as f:
        f.write(m.encode())


def _print_map(m: OSDMap) -> None:
    print(f"epoch {m.epoch}")
    print(f"fsid {m.fsid}")
    print(f"flags {m.flags}")
    print()
    for pool in m.pools.values():
        kind = "replicated" if pool.type == TYPE_REPLICATED else "erasure"
        print(f"pool {pool.id} '{pool.name}' {kind} size {pool.size}"
              f" min_size {pool.min_size} crush_rule {pool.crush_rule}"
              f" pg_num {pool.pg_num} pgp_num {pool.pgp_num}"
              + (f" profile {pool.erasure_code_profile}"
                 if pool.erasure_code_profile else ""))
    print()
    print(f"max_osd {m.max_osd}")
    for o in range(m.max_osd):
        if not m.exists(o):
            continue
        state = ("up" if m.is_up(o) else "down") + \
            (" in" if m.is_in(o) else " out")
        print(f"osd.{o} {state} weight {m.get_weight(o) / 0x10000:g}")


def _test_map_pgs(m: OSDMap, pool_filter: int, dump: bool) -> None:
    mapping = OSDMapMapping(m)
    count = np.zeros(m.max_osd, dtype=np.int64)
    primary_count = np.zeros(m.max_osd, dtype=np.int64)
    total = 0
    sizes = {}
    for pool in m.pools.values():
        if pool_filter >= 0 and pool.id != pool_filter:
            continue
        for ps in range(pool.pg_num):
            pg = PgId(pool.id, ps)
            up, up_p, acting, acting_p = mapping.get(pg)
            if dump:
                print(f"{pg}\t{up}\t{up_p}\t{acting}\t{acting_p}")
            for o in up:
                if 0 <= o < m.max_osd:
                    count[o] += 1
            if 0 <= up_p < m.max_osd:
                primary_count[up_p] += 1
            sizes[len(up)] = sizes.get(len(up), 0) + 1
            total += 1
    print(f"pool {pool_filter if pool_filter >= 0 else 'all'}"
          f" pg_num {total}")
    print(f"size {json.dumps(sizes, sort_keys=True)}")
    in_ids = [o for o in range(m.max_osd) if m.is_in(o)]
    if in_ids:
        in_counts = count[in_ids]
        lo, hi = int(in_counts.argmin()), int(in_counts.argmax())
        print(f"min osd.{in_ids[lo]} {int(in_counts[lo])}")
        print(f"max osd.{in_ids[hi]} {int(in_counts[hi])}")
        print(f"avg {float(in_counts.mean()):.2f}"
              f" stddev {float(in_counts.std()):.2f}")


def run(argv: List[str]) -> int:
    p = argparse.ArgumentParser(prog="osdmaptool")
    p.add_argument("mapfilename")
    p.add_argument("--createsimple", type=int, metavar="NUM_OSD")
    p.add_argument("--pg-bits", type=int, default=6, dest="pg_bits",
                   help="pg bits per osd for --createsimple")
    p.add_argument("--with-default-pool", action="store_true",
                   dest="with_default_pool")
    p.add_argument("--print", action="store_true", dest="print_map")
    p.add_argument("--mark-up-in", action="store_true", dest="mark_up_in")
    p.add_argument("--test-map-pg", metavar="PGID", dest="test_map_pg")
    p.add_argument("--test-map-pgs", action="store_true",
                   dest="test_map_pgs")
    p.add_argument("--test-map-pgs-dump", action="store_true",
                   dest="test_map_pgs_dump")
    p.add_argument("--pool", type=int, default=-1)
    p.add_argument("--export-crush", metavar="FILE", dest="export_crush")
    p.add_argument("--import-crush", metavar="FILE", dest="import_crush")
    args = p.parse_args(argv)

    if args.createsimple:
        m = OSDMap.build_simple(args.createsimple)
        if args.with_default_pool:
            pg_num = 1 << max(
                (args.createsimple * args.pg_bits - 1).bit_length() - 1, 3)
            m.create_pool("rbd", pg_num=min(pg_num, 1 << 15))
        _save(m, args.mapfilename)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfilename}")
        return 0

    try:
        m = _load(args.mapfilename)
    except OSError as e:
        print(f"osdmaptool: error reading {args.mapfilename}: {e}",
              file=sys.stderr)
        return 1

    changed = False
    if args.mark_up_in:
        for o in range(m.max_osd):
            m.osd_state[o] |= CEPH_OSD_EXISTS | CEPH_OSD_UP
            m.osd_weight[o] = CEPH_OSD_IN
        changed = True
    if args.import_crush:
        from ceph_tpu.tools.crushtool import load_map

        m.crush = load_map(args.import_crush)
        changed = True
    if args.export_crush:
        from ceph_tpu.crush.serialize import to_json

        with open(args.export_crush, "w") as f:
            json.dump(to_json(m.crush), f, indent=1)
        print(f"osdmaptool: exported crush map to {args.export_crush}")
    if args.test_map_pg:
        pg = PgId.parse(args.test_map_pg)
        up, up_p, acting, acting_p = m.pg_to_up_acting_osds(pg)
        print(f" parsed '{args.test_map_pg}' -> {pg}")
        print(f"{pg} raw ({up}, p{up_p}) up ({up}, p{up_p}) acting"
              f" ({acting}, p{acting_p})")
    if args.test_map_pgs or args.test_map_pgs_dump:
        _test_map_pgs(m, args.pool, args.test_map_pgs_dump)
    if args.print_map:
        _print_map(m)
    if changed:
        _save(m, args.mapfilename)
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()

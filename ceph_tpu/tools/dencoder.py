"""ceph-dencoder parity: encode/decode/inspect versioned wire types.

Reference: /root/reference/src/tools/ceph-dencoder/ — `ceph-dencoder
type <T> import <file> decode dump_json` for debugging encodings and
pinning cross-version compatibility corpora.  Here the type registry
covers the framework's versioned structs (OSDMap, Incremental) and
every tagged wire message.

Usage:
  python -m ceph_tpu.tools.dencoder list_types
  python -m ceph_tpu.tools.dencoder type OSDMap import m.bin decode \
      dump_json
  python -m ceph_tpu.tools.dencoder message import frame.bin decode
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.msg import messages as msgmod
from ceph_tpu.osd.osdmap import Incremental, OSDMap


def _jsonable(obj, depth: int = 0):
    if depth > 6:
        return repr(obj)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        obj = bytes(obj)
        return {"__bytes__": len(obj),
                "hex_head": obj[:32].hex()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v, depth + 1) for v in obj]
    if isinstance(obj, (int, float, str, bool)) or obj is None:
        return obj
    if hasattr(obj, "__dict__"):
        return {k: _jsonable(v, depth + 1)
                for k, v in vars(obj).items()
                if not k.startswith("_")}
    return repr(obj)


TYPES = {
    "OSDMap": (OSDMap.decode, lambda m: m.encode()),
    "OSDMap::Incremental": (Incremental.decode,
                            lambda i: i.encode()),
}


def _message_types() -> dict:
    return {cls.__name__: cls
            for cls in msgmod._REGISTRY.values()}


def _samples():
    """One representative, field-populated instance of EVERY versioned
    wire type — the corpus generator (ceph-object-corpus role).  Keep
    values deterministic: the corpus pins bytes, and the dump compare
    pins semantics."""
    from ceph_tpu.osd.osdmap import PgId

    m = msgmod
    pg = PgId(3, 5)
    entry = {"version": [7, 42], "prior": [7, 41], "oid": "obj-1",
             "op": "modify", "size": 4096}
    info = {"last_update": [7, 42], "log_tail": [1, 2],
            "missing": {"obj-2": [7, 40]},
            "objects": ["obj-1", "obj-2"]}
    osdmap = OSDMap.build_simple(6, osds_per_host=2)
    scratch = OSDMap.decode(osdmap.encode())
    pool = scratch.create_pool("corpus", type_=1, size=3, pg_num=8)
    inc = Incremental(epoch=osdmap.epoch + 1)
    inc.new_pools[pool.id] = pool
    inc.new_up_osds[2] = "127.0.0.1:6801"
    inc.new_weight[3] = 0x10000
    inc.new_pg_upmap_items[pg] = [(1, 4)]
    ops = [m.OSDOp("write_full", data=b"corpus-bytes" * 10),
           m.OSDOp("read", offset=512, length=1024)]
    shard_ops = [m.ShardOp("write", 128, b"shard-data"),
                 m.ShardOp("setattr", name="_", value=b"{}"),
                 m.ShardOp("remove")]
    yield "OSDMap", osdmap
    yield "OSDMap::Incremental", inc
    yield "MHello", m.MHello("osd.1", "127.0.0.1:6800",
                             nonce=b"n" * 16, kid=2, ticket=b"tkt")
    yield "MPing", m.MPing(1, 12.5, epoch=9, from_osd=4)
    yield "MOSDBoot", m.MOSDBoot(2, "127.0.0.1:6802", boot_epoch=5)
    yield "MOSDFailure", m.MOSDFailure(3, 1, 7.25, 11)
    yield "MGetMap", m.MGetMap(since_epoch=8, subscribe=True)
    yield "MOSDMapMsg", m.MOSDMapMsg(
        12, full_map=osdmap.encode(), incrementals=[inc.encode()],
        gap_unfillable=True)
    yield "MMonCommand", m.MMonCommand(77, {"prefix": "status"})
    yield "MMonCommandReply", m.MMonCommandReply(77, 0, {"ok": True})
    yield "MOSDOp", m.MOSDOp(88, "client.abc", pg, "obj-1", ops, 12,
                             snapc_seq=4, snapc_snaps=[4, 2],
                             snap_id=3)
    yield "MOSDOpReply", m.MOSDOpReply(88, 0, b"reply-data",
                                       {"size": 10}, replay_epoch=13)
    yield "MOSDSubWrite", m.MOSDSubWrite(99, pg, 2, "obj-1",
                                         shard_ops, 12, entry, 1,
                                         guard=(7, 41))
    yield "MOSDSubWriteReply", m.MOSDSubWriteReply(99, 0, 2)
    yield "MOSDSubRead", m.MOSDSubRead(100, pg, 1, "obj-1", 0, 4096,
                                       True, True)
    yield "MOSDSubReadReply", m.MOSDSubReadReply(
        100, 0, b"sub-data", {"_": b"{}"}, 1, {"k": b"v"})
    yield "MPGQuery", m.MPGQuery(101, pg, 12, 0, shard=2)
    yield "MPGLogMsg", m.MPGLogMsg(102, pg, 1, info, [entry],
                                   epoch=12, from_osd=0,
                                   is_reply=True)
    yield "MWatchNotify", m.MWatchNotify(5, 3, "obj-1",
                                         b"notify-payload", 9)
    yield "MWatchNotifyAck", m.MWatchNotifyAck(5, 9)
    yield "MOSDCommand", m.MOSDCommand(103, {"prefix": "perf dump"})
    yield "MOSDCommandReply", m.MOSDCommandReply(103, 0,
                                                 {"counters": {}})
    yield "MClientRequest", m.MClientRequest(104, "mkdir",
                                             {"path": "/a"})
    yield "MClientReply", m.MClientReply(104, 0, {"inode": {"ino": 7}})
    yield "MMonElection", m.MMonElection(3, 15, 1, quorum=[0, 1, 2])
    yield "MMonPaxos", m.MMonPaxos(
        5, pn=201, version=9, value=b"paxos-value",
        last_committed=8, first_committed=1, values={9: b"paxos-value"},
        lease=2.0, uncommitted_pn=101, from_rank=1)
    yield "MMonForward", m.MMonForward(6, 7, b"inner-payload")
    yield "MMonForwardReply", m.MMonForwardReply(6, 0, {"done": 1})
    yield "MAuth", m.MAuth(105, "client.x", 2, kid=1,
                           client_challenge=b"c" * 16,
                           proof=b"p" * 8)
    yield "MAuthReply", m.MAuthReply(105, 0, b"s" * 16, b"ticket")
    yield "MOSDCompute", m.MOSDCompute(
        106, "client.abc", 3, ["obj-1", "obj-2"], "gf_fold",
        '{"record":8}', epoch=12, tenant="t1")
    yield "MOSDComputeReply", m.MOSDComputeReply(
        106, 0, {"obj-1": (0, b"\x01" * 32), "obj-2": (-2, b"")},
        {"pushdown": 1, "fallback": 0}, replay_epoch=0)
    yield "MOSDSubCompute", m.MOSDSubCompute(
        107, "gf_fold", "", [(3, 5, 1, "obj-1"), (3, 5, 1, "obj-2")],
        epoch=12)
    yield "MOSDSubComputeReply", m.MOSDSubComputeReply(
        107, 0, [(0, "12'7", b"\x02" * 32), (-2, "", b"")])


def _dump(obj) -> dict:
    return _jsonable(obj)


def _decode_named(name: str, blob: bytes):
    if name in TYPES:
        return TYPES[name][0](blob)
    cls = _message_types()[name]
    return cls.decode(blob)


def corpus_create(directory: str) -> int:
    """Write <dir>/<Type>.bin + .json for every versioned type
    (ceph-object-corpus generation, readable.sh's archive step)."""
    import os

    os.makedirs(directory, exist_ok=True)
    n = 0
    for name, obj in _samples():
        blob = obj.encode()
        safe = name.replace(":", "_")
        with open(os.path.join(directory, safe + ".bin"), "wb") as f:
            f.write(blob)
        with open(os.path.join(directory, safe + ".json"), "w") as f:
            json.dump({"type": name, "dump": _dump(obj)}, f, indent=1,
                      sort_keys=True)
        n += 1
    print(f"archived {n} types into {directory}")
    return 0


def corpus_check(directory: str) -> int:
    """Decode every archived blob with TODAY's code and compare its
    dump against the archived one (readable.sh's check step): a wire
    change that breaks decoding of an older release's bytes — or
    silently changes their meaning — fails here."""
    import glob
    import os

    failures = 0
    count = 0
    for jpath in sorted(glob.glob(os.path.join(directory, "*.json"))):
        with open(jpath) as f:
            doc = json.load(f)
        name = doc["type"]
        with open(jpath[:-5] + ".bin", "rb") as f:
            blob = f.read()
        count += 1
        try:
            got = _dump(_decode_named(name, blob))
        except Exception as e:
            print(f"FAIL {name}: decode raised {e!r}")
            failures += 1
            continue
        # every archived field must decode to its archived value; a
        # field TODAY's code grew (absent from the archive, defaulted
        # at decode) is the DECODE_FINISH growth contract, not drift
        drifted = {k for k in doc["dump"]
                   if got.get(k) != doc["dump"][k]}
        if drifted:
            print(f"FAIL {name}: dump drifted")
            for k in sorted(drifted):
                print(f"  field {k}: archived="
                      f"{doc['dump'].get(k)!r} now={got.get(k)!r}")
            failures += 1
    print(f"checked {count} archived types, {failures} failures")
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dencoder")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list_types")
    tp = sub.add_parser("type")
    tp.add_argument("name")
    tp.add_argument("verbs", nargs="+",
                    help="import <file> | decode | dump_json")
    msg = sub.add_parser("message")
    msg.add_argument("verbs", nargs="+",
                     help="import <file> | decode  (tagged frame:"
                          " 2-byte LE tag + payload)")
    cc = sub.add_parser("corpus_create")
    cc.add_argument("directory")
    ck = sub.add_parser("corpus_check")
    ck.add_argument("directory")
    args = ap.parse_args(argv)

    if args.cmd == "corpus_create":
        return corpus_create(args.directory)
    if args.cmd == "corpus_check":
        return corpus_check(args.directory)
    if args.cmd == "list_types":
        for name in sorted(TYPES):
            print(name)
        for name in sorted(_message_types()):
            print(name)
        return 0

    verbs = args.verbs
    data = b""
    i = 0
    while i < len(verbs):
        verb = verbs[i]
        if verb == "import":
            i += 1
            path = verbs[i]
            data = sys.stdin.buffer.read() if path == "-" else \
                open(path, "rb").read()
        elif verb == "decode":
            pass  # decoding happens at dump time (stateless CLI)
        elif verb == "dump_json":
            pass
        else:
            print(f"error: unknown verb {verb!r}", file=sys.stderr)
            return 2
        i += 1

    if args.cmd == "type":
        entry = TYPES.get(args.name)
        if entry is None:
            cls = _message_types().get(args.name)
            if cls is None:
                print(f"error: unknown type {args.name!r}",
                      file=sys.stderr)
                return 2
            obj = cls.decode(data)
        else:
            obj = entry[0](data)
        print(json.dumps(_jsonable(obj), indent=2, sort_keys=True))
        return 0

    # tagged message frame: 2-byte LE tag + versioned payload
    if len(data) < 2:
        print("error: short frame", file=sys.stderr)
        return 2
    tag = int.from_bytes(data[:2], "little")
    obj = msgmod.decode_message(tag, data[2:])
    print(json.dumps({"tag": tag, "type": type(obj).__name__,
                      "fields": _jsonable(obj)}, indent=2,
                     sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""ceph-dencoder parity: encode/decode/inspect versioned wire types.

Reference: /root/reference/src/tools/ceph-dencoder/ — `ceph-dencoder
type <T> import <file> decode dump_json` for debugging encodings and
pinning cross-version compatibility corpora.  Here the type registry
covers the framework's versioned structs (OSDMap, Incremental) and
every tagged wire message.

Usage:
  python -m ceph_tpu.tools.dencoder list_types
  python -m ceph_tpu.tools.dencoder type OSDMap import m.bin decode \
      dump_json
  python -m ceph_tpu.tools.dencoder message import frame.bin decode
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.msg import messages as msgmod
from ceph_tpu.osd.osdmap import Incremental, OSDMap


def _jsonable(obj, depth: int = 0):
    if depth > 6:
        return repr(obj)
    if isinstance(obj, bytes):
        return {"__bytes__": len(obj),
                "hex_head": obj[:32].hex()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v, depth + 1) for v in obj]
    if isinstance(obj, (int, float, str, bool)) or obj is None:
        return obj
    if hasattr(obj, "__dict__"):
        return {k: _jsonable(v, depth + 1)
                for k, v in vars(obj).items()
                if not k.startswith("_")}
    return repr(obj)


TYPES = {
    "OSDMap": (OSDMap.decode, lambda m: m.encode()),
    "OSDMap::Incremental": (Incremental.decode,
                            lambda i: i.encode()),
}


def _message_types() -> dict:
    return {cls.__name__: cls
            for cls in msgmod._REGISTRY.values()}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dencoder")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list_types")
    tp = sub.add_parser("type")
    tp.add_argument("name")
    tp.add_argument("verbs", nargs="+",
                    help="import <file> | decode | dump_json")
    msg = sub.add_parser("message")
    msg.add_argument("verbs", nargs="+",
                     help="import <file> | decode  (tagged frame:"
                          " 2-byte LE tag + payload)")
    args = ap.parse_args(argv)

    if args.cmd == "list_types":
        for name in sorted(TYPES):
            print(name)
        for name in sorted(_message_types()):
            print(name)
        return 0

    verbs = args.verbs
    data = b""
    i = 0
    while i < len(verbs):
        verb = verbs[i]
        if verb == "import":
            i += 1
            path = verbs[i]
            data = sys.stdin.buffer.read() if path == "-" else \
                open(path, "rb").read()
        elif verb == "decode":
            pass  # decoding happens at dump time (stateless CLI)
        elif verb == "dump_json":
            pass
        else:
            print(f"error: unknown verb {verb!r}", file=sys.stderr)
            return 2
        i += 1

    if args.cmd == "type":
        entry = TYPES.get(args.name)
        if entry is None:
            cls = _message_types().get(args.name)
            if cls is None:
                print(f"error: unknown type {args.name!r}",
                      file=sys.stderr)
                return 2
            obj = cls.decode(data)
        else:
            obj = entry[0](data)
        print(json.dumps(_jsonable(obj), indent=2, sort_keys=True))
        return 0

    # tagged message frame: 2-byte LE tag + versioned payload
    if len(data) < 2:
        print("error: short frame", file=sys.stderr)
        return 2
    tag = int.from_bytes(data[:2], "little")
    obj = msgmod.decode_message(tag, data[2:])
    print(json.dumps({"tag": tag, "type": type(obj).__name__,
                      "fields": _jsonable(obj)}, indent=2,
                     sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())

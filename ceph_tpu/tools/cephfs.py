"""cephfs CLI: drive a CephFS filesystem from the shell.

Reference parity: the cephfs-shell tool + the `ceph fs subvolume`
command family (/root/reference/src/tools/cephfs/shell,
src/pybind/mgr/volumes) collapsed onto one non-interactive CLI:
namespace ops, file transfer, snapshots (.snap surface), and
subvolume management.

    python -m ceph_tpu.tools.cephfs -m MON ls /
    ... put local.bin /dir/file     get /dir/file out.bin
    ... snap create /dir name       snap ls /dir
    ... subvolume create name --group g
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.cephfs import CephFS, CephFSError
from ceph_tpu.rados.client import RadosClient
from ceph_tpu.tools import fileio


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cephfs")
    ap.add_argument("-m", "--mon", required=True)
    ap.add_argument("--meta", default="cephfs.meta")
    ap.add_argument("--data", default="cephfs.data")
    ap.add_argument("--secret", default="")
    sub = ap.add_subparsers(dest="cmd", required=True)

    for name in ("ls", "stat", "rmdir", "rm", "cat"):
        p = sub.add_parser(name)
        p.add_argument("path")
    mk = sub.add_parser("mkdir")
    mk.add_argument("path")
    mk.add_argument("-p", "--parents", action="store_true")
    mv = sub.add_parser("mv")
    mv.add_argument("src")
    mv.add_argument("dst")
    pu = sub.add_parser("put")
    pu.add_argument("local", help="local file, or - for stdin")
    pu.add_argument("path")
    ge = sub.add_parser("get")
    ge.add_argument("path")
    ge.add_argument("local", help="local file, or - for stdout")
    sn = sub.add_parser("snap")
    sn.add_argument("verb", choices=["create", "ls", "rm"])
    sn.add_argument("path")
    sn.add_argument("name", nargs="?", default="")
    sv = sub.add_parser("subvolume")
    sv.add_argument("verb", choices=["create", "ls", "rm", "getpath",
                                     "info", "resize"])
    sv.add_argument("name", nargs="?", default="")
    sv.add_argument("--group", default=None)
    sv.add_argument("--size", type=int, default=None)

    args = ap.parse_args(argv)
    try:
        return asyncio.run(_run(args))
    except CephFSError as e:
        print(f"cephfs: {e}", file=sys.stderr)
        return 1


async def _run(args) -> int:
    client = RadosClient(args.mon, secret=args.secret or None)
    await client.connect()
    try:
        fs = CephFS(client, args.meta, args.data)
        return await _dispatch(fs, args)
    finally:
        await client.shutdown()


async def _mkdirs(fs: CephFS, path: str) -> None:
    parts = [p for p in path.split("/") if p]
    for i in range(len(parts)):
        try:
            await fs.mkdir("/" + "/".join(parts[:i + 1]))
        except CephFSError as e:
            if e.rc != -17:  # EEXIST
                raise


async def _dispatch(fs: CephFS, args) -> int:
    cmd = args.cmd
    if cmd == "ls":
        for name, inode in sorted(
                (await fs.readdir(args.path)).items()):
            kind = {"dir": "d", "symlink": "l"}.get(
                inode.get("type"), "-")
            print(f"{kind} {inode.get('size', 0):>10} {name}")
        return 0
    if cmd == "stat":
        print(json.dumps(await fs.stat(args.path), sort_keys=True))
        return 0
    if cmd == "mkdir":
        if args.parents:
            await _mkdirs(fs, args.path)
        else:
            await fs.mkdir(args.path)
        return 0
    if cmd == "rmdir":
        await fs.rmdir(args.path)
        return 0
    if cmd == "rm":
        await fs.unlink(args.path)
        return 0
    if cmd == "mv":
        await fs.rename(args.src, args.dst)
        return 0
    if cmd == "put":
        data = await fileio.read_stdin() if args.local == "-" else \
            await fileio.read_bytes(args.local)
        await fs.write_file(args.path, data)
        return 0
    if cmd in ("get", "cat"):
        data = await fs.read_file(args.path)
        if cmd == "cat" or args.local == "-":
            sys.stdout.buffer.write(data)
        else:
            await fileio.write_bytes(args.local, data)
        return 0
    if cmd == "snap":
        if args.verb == "create":
            snapid = await fs.mksnap(args.path, args.name)
            print(json.dumps({"snapid": snapid}))
        elif args.verb == "ls":
            for s in await fs.lssnap(args.path):
                print(json.dumps(s))
        elif args.verb == "rm":
            await fs.rmsnap(args.path, args.name)
        return 0
    if cmd == "subvolume":
        from ceph_tpu.cephfs.volumes import VolumeClient

        vc = VolumeClient(fs)
        if args.verb == "create":
            path = await vc.create(args.name, group=args.group,
                                   size=args.size)
            print(json.dumps({"path": path}))
        elif args.verb == "ls":
            print(json.dumps(await vc.ls(group=args.group)))
        elif args.verb == "rm":
            await vc.rm(args.name, group=args.group)
        elif args.verb == "getpath":
            print(await vc.getpath(args.name, group=args.group))
        elif args.verb == "info":
            print(json.dumps(await vc.info(args.name,
                                           group=args.group),
                             sort_keys=True))
        elif args.verb == "resize":
            if args.size is None:
                print("resize needs --size", file=sys.stderr)
                return 22
            print(json.dumps(await vc.resize(args.name, args.size,
                                             group=args.group)))
        return 0
    print(f"unknown command {cmd}", file=sys.stderr)
    return 22


if __name__ == "__main__":
    sys.exit(main())

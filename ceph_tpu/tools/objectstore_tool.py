"""ceph-objectstore-tool parity: offline object-store surgery.

Reference: /root/reference/src/tools/ceph_objectstore_tool.cc — open a
stopped OSD's store directly and list/extract/remove objects, dump
attrs/omap, list PGs.  The daemon must NOT be running on the store
(single-writer mount, like the reference's fsck-style open).

Usage:
  python -m ceph_tpu.tools.objectstore_tool --data-path DIR op
    where op: list-pgs | list [--cid CID] | info --cid C --obj O |
    get-bytes --cid C --obj O [--file F] | dump-omap --cid C --obj O |
    get-attrs --cid C --obj O | remove --cid C --obj O | fsck
"""

from __future__ import annotations

import argparse
import json
import sys

from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.os.tpustore import TPUStore


def _out(obj) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="objectstore-tool")
    ap.add_argument("--data-path", required=True)
    sub = ap.add_subparsers(dest="op", required=True)
    sub.add_parser("list-pgs")
    ls = sub.add_parser("list")
    ls.add_argument("--cid", default="")
    for name in ("info", "get-bytes", "dump-omap", "get-attrs",
                 "remove"):
        p = sub.add_parser(name)
        p.add_argument("--cid", required=True)
        p.add_argument("--obj", required=True)
        if name == "get-bytes":
            p.add_argument("--file", default="-")
    sub.add_parser("fsck")
    args = ap.parse_args(argv)

    store = TPUStore(args.data_path)
    store.mount()
    try:
        return _dispatch(store, args)
    finally:
        store.umount()


def _dispatch(store: TPUStore, args) -> int:
    if args.op == "list-pgs":
        # pg collections are "<pool>.<ps hex>[s<shard>]_head"
        for cid in sorted(store.list_collections()):
            if cid.endswith("_head"):
                print(cid)
        return 0
    if args.op == "list":
        cids = [args.cid] if args.cid else \
            sorted(store.list_collections())
        for cid in cids:
            for oid in sorted(str(o) for o in store.list_objects(cid)):
                print(json.dumps([cid, oid]))
        return 0
    if args.op == "fsck":
        # walk everything; broken reads surface as errors
        problems = []
        n_objects = 0
        for cid in store.list_collections():
            for obj in store.list_objects(cid):
                n_objects += 1
                try:
                    store.read(cid, obj)
                    store.getattrs(cid, obj)
                except Exception as e:
                    problems.append([cid, str(obj), repr(e)])
        _out({"objects": n_objects, "errors": problems})
        return 0 if not problems else 1
    oid = ObjectId(args.obj)
    if args.op == "info":
        data = store.read(args.cid, oid)
        attrs = store.getattrs(args.cid, oid)
        _out({"cid": args.cid, "oid": args.obj, "size": len(data),
              "attrs": {k: v.decode("latin-1")
                        for k, v in sorted(attrs.items())}})
        return 0
    if args.op == "get-bytes":
        data = store.read(args.cid, oid)
        if args.file == "-":
            sys.stdout.buffer.write(data)
        else:
            with open(args.file, "wb") as f:
                f.write(data)
        return 0
    if args.op == "dump-omap":
        _out({k: v.decode("latin-1")
              for k, v in sorted(store.omap_get(args.cid,
                                                oid).items())})
        return 0
    if args.op == "get-attrs":
        _out({k: v.decode("latin-1")
              for k, v in sorted(store.getattrs(args.cid,
                                                oid).items())})
        return 0
    if args.op == "remove":
        t = Transaction()
        t.remove(args.cid, oid)
        store.queue_transaction(t)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())

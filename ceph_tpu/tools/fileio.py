"""Async local-file helpers for the CLI tools.

The tools run their command inside the same event loop that drives the
messenger (heartbeats, replies, watch/notify); a local read/write that
stalls on a slow filesystem would stall all of it.  Every local-disk
touch rides a worker thread instead — this is the fix shape for the
analyzer's `async-blocking` rule.
"""

from __future__ import annotations

import asyncio


def _read_bytes(path: str) -> bytes:
    with open(path, "rb") as fh:
        return fh.read()


def _read_text(path: str) -> str:
    with open(path) as fh:
        return fh.read()


def _write_bytes(path: str, data: bytes) -> None:
    with open(path, "wb") as fh:
        fh.write(data)


async def read_bytes(path: str) -> bytes:
    return await asyncio.to_thread(_read_bytes, path)


async def read_text(path: str) -> str:
    return await asyncio.to_thread(_read_text, path)


async def write_bytes(path: str, data: bytes) -> None:
    await asyncio.to_thread(_write_bytes, path, data)


async def open_file(path: str, mode: str = "r"):
    """open() off-loop; the returned file object's own reads/writes
    should also ride asyncio.to_thread when they can be large."""
    return await asyncio.to_thread(open, path, mode)


async def iter_lines(path: str, batch: int = 1024):
    """Stream a text file line by line without slurping it: `batch`
    lines per worker-thread hop keeps both the event loop and memory
    bounded for multi-GiB traces."""
    import itertools
    fh = await asyncio.to_thread(open, path)
    try:
        while True:
            chunk = await asyncio.to_thread(
                lambda: list(itertools.islice(fh, batch)))
            if not chunk:
                return
            for line in chunk:
                yield line
    finally:
        await asyncio.to_thread(fh.close)


async def read_stdin() -> bytes:
    """Drain stdin off-loop: a slow pipe producer would otherwise
    stall the event loop exactly like a slow local file."""
    import sys
    return await asyncio.to_thread(sys.stdin.buffer.read)

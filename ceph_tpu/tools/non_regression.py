"""ceph_erasure_code_non_regression parity CLI.

Reference: /root/reference/src/test/erasure-code/ceph_erasure_code_non_regression.cc
— archives encoded chunks per (plugin, profile) under a directory named
from the profile, then `--check` re-encodes the stored content and
compares byte-for-byte, plus verifies every 1- and 2-erasure decode
round-trips.  This is the bit-exactness contract across versions and
architectures (chunk layout `<base>/<profile-dir>/{content,<chunk>}`).
"""

from __future__ import annotations

import argparse
import itertools
import os
import random
import sys
from typing import Dict, List

from ceph_tpu.ec.registry import ErasureCodePluginRegistry


def parse_args(argv: List[str]) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="ceph_erasure_code_non_regression")
    p.add_argument("-s", "--stripe-width", type=int, default=4 * 1024,
                   dest="stripe_width")
    p.add_argument("-p", "--plugin", default="jerasure")
    p.add_argument("--base", default=".")
    p.add_argument("-P", "--parameter", action="append", default=[])
    p.add_argument("--create", action="store_true")
    p.add_argument("--check", action="store_true")
    return p.parse_args(argv)


class NonRegression:
    def __init__(self, args: argparse.Namespace):
        self.stripe_width = args.stripe_width
        self.plugin = args.plugin
        self.base = args.base
        self.profile: Dict[str, str] = {"plugin": args.plugin}
        directory = os.path.join(
            self.base,
            f"plugin={args.plugin} stripe-width={args.stripe_width}")
        for param in args.parameter:
            if param.count("=") != 1:
                print(f"--parameter {param} ignored because it does not"
                      " contain exactly one =", file=sys.stderr)
            else:
                key, val = param.split("=")
                self.profile[key] = val
            directory += " " + param
        self.directory = directory

    def codec(self):
        return ErasureCodePluginRegistry.instance().factory(
            self.plugin, dict(self.profile))

    def content_path(self) -> str:
        return os.path.join(self.directory, "content")

    def chunk_path(self, chunk: int) -> str:
        return os.path.join(self.directory, str(chunk))

    def run_create(self) -> int:
        codec = self.codec()
        os.makedirs(self.directory, exist_ok=False)
        payload = bytes(
            ord("a") + random.randrange(26) for _ in range(37))
        reps = -(-self.stripe_width // len(payload))
        content = (payload * reps)[:self.stripe_width]
        with open(self.content_path(), "wb") as f:
            f.write(content)
        want = set(range(codec.get_chunk_count()))
        encoded = codec.encode(want, content)
        for chunk, buf in encoded.items():
            with open(self.chunk_path(chunk), "wb") as f:
                f.write(buf)
        return 0

    def _decode_erasures(self, codec, erasures, chunks) -> int:
        available = {c: b for c, b in chunks.items() if c not in erasures}
        decoded = codec.decode(
            set(erasures), available,
            chunk_size=len(next(iter(available.values()))))
        for erasure in erasures:
            if decoded[erasure] != chunks[erasure]:
                print(f"chunk {erasure} incorrectly recovered",
                      file=sys.stderr)
                return 1
        return 0

    def run_check(self) -> int:
        codec = self.codec()
        with open(self.content_path(), "rb") as f:
            content = f.read()
        want = set(range(codec.get_chunk_count()))
        encoded = codec.encode(want, content)
        for chunk, buf in encoded.items():
            with open(self.chunk_path(chunk), "rb") as f:
                existing = f.read()
            if existing != buf:
                print(f"chunk {chunk} encodes differently than archive",
                      file=sys.stderr)
                return 1
        # decode alone, then two at a time
        for c1 in encoded:
            if self._decode_erasures(codec, {c1}, encoded):
                return 1
        for c1, c2 in itertools.combinations(sorted(encoded), 2):
            if self._decode_erasures(codec, {c1, c2}, encoded):
                return 1
        return 0


def run(argv: List[str]) -> int:
    args = parse_args(argv)
    if not args.create and not args.check:
        print("must specify either --check, or --create", file=sys.stderr)
        return 1
    nr = NonRegression(args)
    if args.create:
        ret = nr.run_create()
        if ret:
            return ret
    if args.check:
        return nr.run_check()
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()

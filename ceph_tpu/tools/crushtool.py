"""crushtool parity CLI.

Reference: /root/reference/src/tools/crushtool.cc + CrushTester
(/root/reference/src/crush/CrushTester.cc): compile (-c) / decompile (-d)
the text crushmap format, `--build` simple hierarchies, and `--test` bulk
placement simulation (--num-rep, --min-x/--max-x, --rule,
--show-mappings, --show-utilization, --show-statistics,
--show-bad-mappings, --weight, --compare) with the same output shapes
(`CRUSH rule R x X [..]`, `device D: stored : N expected : E`).

Deviations: the compiled container is JSON (the reference uses its C wire
encoding); `--test` runs the vmapped straw2 TPU kernel when the rule
compiles to it (millions of inputs per dispatch), falling back to the
exact host mapper.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

import numpy as np

from ceph_tpu.crush import compiler as crush_compiler
from ceph_tpu.crush import mapper as crush_mapper
from ceph_tpu.crush.map import CRUSH_ITEM_NONE, CrushMap
from ceph_tpu.crush.serialize import from_json, to_json


def load_map(path: str) -> CrushMap:
    with open(path) as f:
        content = f.read()
    stripped = content.lstrip()
    if stripped.startswith("{"):
        return from_json(json.loads(content))
    return crush_compiler.compile_text(content)


def run_test(cmap: CrushMap, args: argparse.Namespace) -> int:
    rules = ([args.rule] if args.rule is not None
             else list(range(len(cmap.rules))))
    weights = cmap.full_weight_vector()
    for dev, w in args.weight or []:
        if dev < len(weights):
            weights[dev] = int(float(w) * 0x10000)

    compare_lines: Optional[List[str]] = None
    if args.compare:
        with open(args.compare) as f:
            compare_lines = [ln.rstrip("\n") for ln in f if ln.strip()]
    mismatches = 0
    compare_idx = 0

    xs = np.arange(args.min_x, args.max_x + 1, dtype=np.int64)
    total_weight = sum(
        weights[d] if d < len(weights) else 0
        for d in range(cmap.max_devices)) or 1

    for ruleno in rules:
        if ruleno >= len(cmap.rules):
            print(f"rule {ruleno} dne", file=sys.stderr)
            return 1
        rule = cmap.rules[ruleno]
        num_rep = args.num_rep
        print(f"rule {ruleno} ({rule.name}), x = {args.min_x}..{args.max_x},"
              f" numrep = {num_rep}..{num_rep}", file=sys.stderr)

        results = _bulk_do_rule(cmap, ruleno, xs, num_rep, weights)

        per_device = np.zeros(cmap.max_devices, dtype=np.int64)
        sizes: Dict[int, int] = {}
        placed = 0
        for row_i, x in enumerate(xs):
            out = [int(v) for v in results[row_i] if int(v) != CRUSH_ITEM_NONE]
            line = f"CRUSH rule {ruleno} x {int(x)} {_fmt_vec(out)}"
            if args.show_mappings:
                print(line)
            if compare_lines is not None:
                if (compare_idx >= len(compare_lines)
                        or compare_lines[compare_idx] != line):
                    mismatches += 1
                compare_idx += 1
            if args.show_bad_mappings and len(out) != num_rep:
                print(f"bad mapping rule {ruleno} x {int(x)} num_rep"
                      f" {num_rep} result {_fmt_vec(out)}", file=sys.stderr)
            for dev in out:
                if 0 <= dev < cmap.max_devices:
                    per_device[dev] += 1
                    placed += 1
            sizes[len(out)] = sizes.get(len(out), 0) + 1

        if args.show_utilization:
            for dev in range(cmap.max_devices):
                w = weights[dev] if dev < len(weights) else 0
                expected = placed * w / total_weight
                print(f"  device {dev}:\t\t stored : {per_device[dev]}"
                      f"\t expected : {expected:.6g}")
        if args.show_statistics:
            for size, count in sorted(sizes.items()):
                print(f"rule {ruleno} ({rule.name}) num_rep {num_rep}"
                      f" result size == {size}:\t{count}/{len(xs)}")

    if compare_lines is not None:
        # reference lines never reached are mismatches too
        mismatches += max(0, len(compare_lines) - compare_idx)
        print(f"compared {max(compare_idx, len(compare_lines))} mappings,"
              f" {mismatches} mismatches")
        return 1 if mismatches else 0
    return 0


def _fmt_vec(out: List[int]) -> str:
    return "[" + ",".join(str(v) for v in out) + "]"


def _bulk_do_rule(cmap: CrushMap, ruleno: int, xs: np.ndarray,
                  num_rep: int, weights: List[int]) -> np.ndarray:
    """All xs through one rule: TPU kernel when compilable, host otherwise."""
    from ceph_tpu.ops import gf

    try:
        if not gf.backend_available():
            raise NotImplementedError("no jax backend")
        from ceph_tpu.crush import kernel as ck

        run = ck.compile_rule(cmap, ruleno, result_max=num_rep,
                              weight=weights)
        return run(xs)
    except NotImplementedError:
        rows = np.full((len(xs), num_rep), CRUSH_ITEM_NONE, dtype=np.int64)
        for i, x in enumerate(xs):
            out = crush_mapper.crush_do_rule(
                cmap, ruleno, int(x), num_rep, weights)
            for j, v in enumerate(out[:num_rep]):
                rows[i, j] = v
        return rows


def run(argv: List[str]) -> int:
    p = argparse.ArgumentParser(prog="crushtool")
    p.add_argument("-c", "--compile", dest="compile_src", metavar="SRC",
                   help="compile text SRC to a map container")
    p.add_argument("-d", "--decompile", dest="decompile_src", metavar="MAP",
                   help="decompile MAP to text")
    p.add_argument("-o", "--outfn", help="output file")
    p.add_argument("-i", "--infn", help="input map for --test")
    p.add_argument("--test", action="store_true")
    p.add_argument("--num-rep", type=int, default=1, dest="num_rep")
    p.add_argument("--min-x", type=int, default=0, dest="min_x")
    p.add_argument("--max-x", type=int, default=1023, dest="max_x")
    p.add_argument("--rule", type=int, default=None)
    p.add_argument("--show-mappings", action="store_true")
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("--weight", nargs=2, action="append", metavar=("DEV", "W"),
                   type=str, default=[])
    p.add_argument("--compare", metavar="FILE",
                   help="compare mappings with FILE (from --show-mappings)")
    args = p.parse_args(argv)
    args.weight = [(int(d), w) for d, w in args.weight]

    if args.compile_src:
        cmap = load_map(args.compile_src)
        out = json.dumps(to_json(cmap), indent=1)
        _write(args.outfn or "crushmap", out)
        return 0
    if args.decompile_src:
        cmap = load_map(args.decompile_src)
        _write(args.outfn, crush_compiler.decompile(cmap))
        return 0
    if args.test:
        if not args.infn:
            print("--test requires -i <map>", file=sys.stderr)
            return 1
        return run_test(load_map(args.infn), args)
    p.print_usage(sys.stderr)
    return 1


def _write(path: Optional[str], content: str) -> None:
    if path:
        with open(path, "w") as f:
            f.write(content)
    else:
        sys.stdout.write(content)


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()

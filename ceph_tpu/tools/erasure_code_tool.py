"""ceph-erasure-code-tool parity CLI.

Reference: /root/reference/src/tools/erasure-code/ceph-erasure-code-tool.cc
— same subcommands and file conventions:

    test-plugin-exists <plugin>
    validate-profile <profile> [<display-param> ...]
    calc-chunk-size <profile> <object_size>
    encode <profile> <stripe_unit> <want_to_encode> <fname>
    decode <profile> <stripe_unit> <want_to_decode> <fname>

profile is a comma-separated key=value list; encode reads {fname} and
writes {fname}.{shard}; decode reads {fname}.{shard} and writes {fname}.
"""

from __future__ import annotations

import sys
from typing import Dict, List

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import ErasureCodePluginRegistry
from ceph_tpu.osd import ec_util

USAGE = """\
usage: ceph-erasure-code-tool test-plugin-exists <plugin>
       ceph-erasure-code-tool validate-profile <profile> [<display-param> ...]
       ceph-erasure-code-tool calc-chunk-size <profile> <object_size>
       ceph-erasure-code-tool encode <profile> <stripe_unit> <want_to_encode> <fname>
       ceph-erasure-code-tool decode <profile> <stripe_unit> <want_to_decode> <fname>
"""

DISPLAY_PARAMS = ("chunk_count", "data_chunk_count", "coding_chunk_count")


def usage(message: str = "") -> int:
    if message:
        print(message, file=sys.stderr)
    print(USAGE, file=sys.stderr)
    return 1


def parse_profile(profile_str: str) -> Dict[str, str]:
    profile: Dict[str, str] = {}
    for opt in profile_str.replace(" ", ",").split(","):
        if not opt:
            continue
        if "=" not in opt:
            raise ValueError(f"invalid profile entry {opt!r}")
        key, val = opt.split("=", 1)
        profile[key] = val
    if "plugin" not in profile:
        raise ValueError("invalid profile: plugin not specified")
    return profile


def make_codec(profile_str: str):
    profile = parse_profile(profile_str)
    return ErasureCodePluginRegistry.instance().factory(
        profile["plugin"], profile)


def make_sinfo(codec, stripe_unit_str: str) -> ec_util.StripeInfo:
    stripe_unit = int(stripe_unit_str)
    if stripe_unit <= 0:
        raise ValueError("invalid stripe unit")
    k = codec.get_data_chunk_count()
    return ec_util.StripeInfo(k, k * stripe_unit)


def do_test_plugin_exists(args: List[str]) -> int:
    if len(args) < 1:
        return usage("not enough arguments")
    try:
        ErasureCodePluginRegistry.instance().load(args[0])
        return 0
    except ErasureCodeError as e:
        print(e, file=sys.stderr)
        return e.errno


def do_validate_profile(args: List[str]) -> int:
    if len(args) < 1:
        return usage("not enough arguments")
    try:
        codec = make_codec(args[0])
    except (ValueError, ErasureCodeError) as e:
        return usage(f"invalid profile: {e}")
    values = {
        "chunk_count": codec.get_chunk_count(),
        "data_chunk_count": codec.get_data_chunk_count(),
        "coding_chunk_count": codec.get_coding_chunk_count(),
    }
    if len(args) == 1:
        for name in DISPLAY_PARAMS:
            print(f"{name}={values[name]}")
    else:
        for name in args[1:]:
            if name not in values:
                return usage(f"unknown display-param {name}")
            print(values[name])
    return 0


def do_calc_chunk_size(args: List[str]) -> int:
    if len(args) < 2:
        return usage("not enough arguments")
    codec = make_codec(args[0])
    print(codec.get_chunk_size(int(args[1])))
    return 0


def do_encode(args: List[str]) -> int:
    if len(args) < 4:
        return usage("not enough arguments")
    codec = make_codec(args[0])
    sinfo = make_sinfo(codec, args[1])
    want = {int(s) for s in args[2].split(",")}
    fname = args[3]
    try:
        with open(fname, "rb") as f:
            data = f.read()
    except OSError as e:
        print(f"failed to read {fname}: {e}", file=sys.stderr)
        return 1
    width = sinfo.get_stripe_width()
    if len(data) % width:
        data += bytes(width - len(data) % width)
    encoded = ec_util.encode(sinfo, codec, data, want)
    for shard, buf in encoded.items():
        name = f"{fname}.{shard}"
        try:
            with open(name, "wb") as f:
                f.write(buf)
        except OSError as e:
            print(f"failed to write {name}: {e}", file=sys.stderr)
            return 1
    return 0


def do_decode(args: List[str]) -> int:
    if len(args) < 4:
        return usage("not enough arguments")
    codec = make_codec(args[0])
    sinfo = make_sinfo(codec, args[1])
    shards = [int(s) for s in args[2].split(",")]
    fname = args[3]
    encoded: Dict[int, bytes] = {}
    for shard in shards:
        name = f"{fname}.{shard}"
        try:
            with open(name, "rb") as f:
                encoded[shard] = f.read()
        except OSError as e:
            print(f"failed to read {name}: {e}", file=sys.stderr)
            return 1
    try:
        decoded = ec_util.decode(sinfo, codec, encoded)
    except ErasureCodeError as e:
        print(f"failed to decode: {e}", file=sys.stderr)
        return 1
    try:
        with open(fname, "wb") as f:
            f.write(decoded)
    except OSError as e:
        print(f"failed to write {fname}: {e}", file=sys.stderr)
        return 1
    return 0


def run(argv: List[str]) -> int:
    if not argv:
        return usage()
    cmd, args = argv[0], argv[1:]
    handlers = {
        "test-plugin-exists": do_test_plugin_exists,
        "validate-profile": do_validate_profile,
        "calc-chunk-size": do_calc_chunk_size,
        "encode": do_encode,
        "decode": do_decode,
    }
    handler = handlers.get(cmd)
    if handler is None:
        return usage(f"unknown command {cmd!r}")
    try:
        return handler(args)
    except (ValueError, ErasureCodeError) as e:
        return usage(str(e))


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()

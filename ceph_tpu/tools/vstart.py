"""vstart: one-shot dev/test cluster launcher (src/vstart.sh role).

Spawns real processes — N mons (Paxos quorum when >1), M OSDs
(TPUStore-backed under --data-dir), optional MDS pair and S3 gateway —
wires them together, waits for health, and prints a ready-to-source
environment block.  `--stop` tears down a running cluster by pidfile.

Usage:
  python -m ceph_tpu.tools.vstart --data-dir /tmp/vstart \
      --mons 3 --osds 4 [--mds] [--rgw] [--secret auto] [--secure]
  python -m ceph_tpu.tools.vstart --data-dir /tmp/vstart --stop
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time


def _spawn(data_dir: str, tag: str, args, env_extra=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra or {})
    logf = open(os.path.join(data_dir, f"{tag}.log"), "w")
    proc = subprocess.Popen([sys.executable, "-u", "-m", *args],
                            stdout=subprocess.PIPE, stderr=logf,
                            text=True, env=env)
    return proc


def _read_tag(proc, tag: str, timeout: float = 90.0) -> str:
    import select

    deadline = time.monotonic() + timeout
    buf = ""
    while time.monotonic() < deadline:
        # poll the pipe so a wedged (silent, non-exiting) daemon cannot
        # block readline forever
        ready, _, _ = select.select([proc.stdout], [], [], 1.0)
        if not ready:
            continue
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(f"daemon exited rc={proc.poll()}")
        buf = line
        if line.startswith(tag):
            return line.split()[1]
    raise TimeoutError(f"no {tag} line (last: {buf!r})")


def _free_ports(n: int):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _pids_path(data_dir: str) -> str:
    return os.path.join(data_dir, "vstart.pids")


def stop(data_dir: str) -> int:
    path = _pids_path(data_dir)
    if not os.path.exists(path):
        print(f"no running cluster under {data_dir}")
        return 1
    with open(path) as f:
        pids = [int(x) for x in f.read().split()]
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
    time.sleep(1.0)
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    os.remove(path)
    print(f"stopped {len(pids)} daemons")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="vstart")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--mons", type=int, default=1)
    ap.add_argument("--osds", type=int, default=3)
    ap.add_argument("--mds", action="store_true",
                    help="also start an active+standby MDS pair"
                         " (creates cephfs.meta/cephfs.data pools)")
    ap.add_argument("--rgw", action="store_true",
                    help="also start the S3 gateway (creates rgw"
                         " pools; access key 'vstart'/'vstartsecret')")
    ap.add_argument("--secret", default="",
                    help="cephx keyring hex, or 'auto' to generate")
    ap.add_argument("--secure", action="store_true",
                    help="on-wire encryption (needs --secret)")
    ap.add_argument("--memstore", action="store_true",
                    help="MemStore OSDs (no durable data dir)")
    ap.add_argument("--stop", action="store_true")
    args = ap.parse_args(argv)

    if args.stop:
        return stop(args.data_dir)

    os.makedirs(args.data_dir, exist_ok=True)
    secret = args.secret
    if secret == "auto":
        from ceph_tpu.common import auth

        secret = auth.generate_secret()
        with open(os.path.join(args.data_dir, "keyring"), "w") as f:
            f.write(secret + "\n")
    base_cfg = {"mon_osd_min_down_reporters": 1}
    if secret:
        base_cfg["auth_secret"] = secret
    if args.secure:
        base_cfg["auth_secure"] = True

    procs = []

    def _bail(exc):
        # a daemon failed to come up: kill everything already spawned
        # so a botched start never strands orphans with no pidfile
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise exc

    # mons (static monmap so multi-mon quorum forms).  NOTE: the port
    # probe is a TOCTOU (freed before the mons bind) — acceptable for
    # a dev/test launcher; a lost race surfaces as a clean bail here.
    ports = _free_ports(args.mons)
    monmap = ",".join(f"127.0.0.1:{p}" for p in ports)
    for rank in range(args.mons):
        p = _spawn(args.data_dir, f"mon.{rank}", [
            "ceph_tpu.mon", "--num-osds", str(args.osds),
            "--osds-per-host", "1", "--rank", str(rank),
            "--mon-addrs", monmap,
            "--store-path",
            os.path.join(args.data_dir, f"mon.{rank}.db"),
            "--config", json.dumps(base_cfg)])
        procs.append(p)
    try:
        for p in procs:
            _read_tag(p, "MON_ADDR")
    except Exception as e:
        _bail(e)
    # osds
    for i in range(args.osds):
        osd_args = ["ceph_tpu.osd", "--id", str(i), "--mon", monmap,
                    "--config", json.dumps(base_cfg)]
        if not args.memstore:
            osd_args += ["--store-path",
                         os.path.join(args.data_dir, f"osd.{i}")]
        p = _spawn(args.data_dir, f"osd.{i}", osd_args)
        procs.append(p)
    try:
        for p in procs[args.mons:]:
            _read_tag(p, "OSD_ADDR")
    except Exception as e:
        _bail(e)

    async def finish():
        from ceph_tpu.rados.client import RadosClient

        client = RadosClient(monmap, secret=secret or None,
                             secure=args.secure)
        await client.connect()
        try:
            if args.mds:
                await client.create_replicated_pool(
                    "cephfs.meta", size=min(2, args.osds), pg_num=8)
                await client.create_replicated_pool(
                    "cephfs.data", size=min(2, args.osds), pg_num=8)
            if args.rgw:
                await client.create_replicated_pool(
                    "rgw.meta", size=min(2, args.osds), pg_num=8)
                await client.create_replicated_pool(
                    "rgw.data", size=min(2, args.osds), pg_num=8)
            rc, out = await client.mon_command({"prefix": "status"})
            return out
        finally:
            await client.shutdown()

    try:
        status = asyncio.run(finish())
    except Exception as e:
        _bail(e)

    if args.mds:
        for name in ("a", "b"):
            p = _spawn(args.data_dir, f"mds.{name}", [
                "ceph_tpu.mds", "--name", name, "--mon", monmap,
                "--metadata-pool", "cephfs.meta",
                "--data-pool", "cephfs.data"]
                + (["--secret", secret] if secret else [])
                + (["--secure"] if args.secure else []))
            procs.append(p)
            try:
                _read_tag(p, "MDS_ADDR")
            except Exception as e:
                _bail(e)
    rgw_addr = ""
    if args.rgw:
        rgw_ports = _free_ports(1)
        p = _spawn(args.data_dir, "rgw", [
            "ceph_tpu.rgw", "--mon", monmap,
            "--port", str(rgw_ports[0]),
            "--access-key", "vstart", "--secret-key", "vstartsecret"]
            + (["--secret", secret] if secret else [])
            + (["--secure"] if args.secure else []))
        procs.append(p)
        try:
            rgw_addr = _read_tag(p, "RGW_ADDR")
        except Exception as e:
            _bail(e)

    with open(_pids_path(args.data_dir), "w") as f:
        f.write(" ".join(str(p.pid) for p in procs))

    print(f"CLUSTER_UP mons={args.mons} osds={args.osds}"
          f" up={status.get('num_up_osds')}")
    print(f"export CEPH_TPU_MON={monmap}")
    if secret:
        print(f"export CEPH_TPU_SECRET={secret}")
    if rgw_addr:
        print(f"export CEPH_TPU_RGW=http://{rgw_addr}")
    print(f"# stop: python -m ceph_tpu.tools.vstart"
          f" --data-dir {args.data_dir} --stop")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""`rbd` CLI parity: block-image admin against a live cluster.

Reference: /root/reference/src/tools/rbd/ — the block-storage
workhorse CLI: create/ls/info/rm, resize, snapshot management
(create/ls/protect/unprotect/rollback/rm), clone/flatten/children,
export/import, and mirroring control.  One process, one command.

Usage examples:
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd create img --size 64M
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd ls
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd info img
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd snap create img@s1
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd snap protect img@s1
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd clone img@s1 child
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd flatten child
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd export img ./img.bin
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd import ./img.bin img2
  python -m ceph_tpu.tools.rbd -m HOST:PORT -p rbd mirror img --dst-pool backup
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.rados.client import RadosClient, RadosError
from ceph_tpu.rbd import RBD, Image
from ceph_tpu.tools import fileio


def _size(text: str) -> int:
    """64M / 1G / 4096 -> bytes."""
    text = text.strip().upper()
    mult = 1
    for suffix, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30),
                      ("T", 1 << 40)):
        if text.endswith(suffix):
            mult, text = m, text[:-1]
            break
    return int(float(text) * mult)


def _img_snap(spec: str):
    """img[@snap] -> (img, snap|None)."""
    name, _, snap = spec.partition("@")
    return name, (snap or None)


async def _run(args) -> int:
    client = RadosClient(args.mon, secret=args.secret or None)
    await client.connect()
    try:
        ioctx = client.open_ioctx(args.pool)
        rbd = RBD()
        return await _dispatch(client, ioctx, rbd, args)
    finally:
        await client.shutdown()


async def _dispatch(client, ioctx, rbd: RBD, args) -> int:
    cmd = args.cmd
    if cmd == "create":
        await rbd.create(ioctx, args.image, _size(args.size),
                         order=args.order,
                         data_pool=args.data_pool,
                         exclusive_lock=args.exclusive_lock
                         or args.object_map or args.journaling,
                         object_map=args.object_map,
                         journaling=args.journaling)
        return 0
    if cmd == "ls":
        for name in await rbd.list(ioctx):
            print(name)
        return 0
    if cmd == "rm":
        await rbd.remove(ioctx, args.image)
        return 0
    if cmd == "info":
        img = await rbd.open(ioctx, args.image)
        meta = img.meta
        doc = {"name": args.image, "id": img.id,
               "size": meta["size"], "order": meta["order"],
               "object_size": img.object_size,
               "features": meta.get("features", []),
               "data_pool": meta.get("data_pool"),
               "snapshots": sorted(meta.get("snaps", {})),
               }
        if meta.get("parent"):
            p = meta["parent"]
            doc["parent"] = (f"pool{p['pool_id']}/"
                             f"{p['image_id']}@{p['snap_name']}")
        await img.close()
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    if cmd == "resize":
        img = await rbd.open(ioctx, args.image)
        await img.resize(_size(args.size))
        await img.close()
        return 0
    if cmd == "snap":
        return await _snap(ioctx, rbd, args)
    if cmd == "clone":
        parent, snap = _img_snap(args.parent)
        if snap is None:
            print("clone needs parent@snap", file=sys.stderr)
            return 22
        await rbd.clone(ioctx, parent, snap, ioctx, args.child,
                        data_pool=args.data_pool)
        return 0
    if cmd == "flatten":
        img = await rbd.open(ioctx, args.image)
        await img.flatten()
        await img.close()
        return 0
    if cmd == "children":
        img = await rbd.open(ioctx, args.image)
        for child in img.meta.get("children", []):
            print(f"pool{child['pool_id']}/{child['image_id']}"
                  f"@{child['snap_name']}")
        await img.close()
        return 0
    if cmd == "export":
        name, snap = _img_snap(args.image)
        img = await rbd.open(ioctx, name)
        if snap:
            img.snap_set(snap)
        out = sys.stdout.buffer if args.path == "-" \
            else await fileio.open_file(args.path, "wb")
        try:
            step = img.object_size
            total = img.size()
            for off in range(0, total, step):
                chunk = await img.read(off, min(step, total - off))
                await asyncio.to_thread(out.write, chunk)
        finally:
            if out is not sys.stdout.buffer:
                await asyncio.to_thread(out.close)  # flush off-loop
            await img.close()
        return 0
    if cmd == "import":
        data = await fileio.read_stdin() if args.path == "-" \
            else await fileio.read_bytes(args.path)
        await rbd.create(ioctx, args.image, len(data),
                         order=args.order)
        img = await rbd.open(ioctx, args.image)
        step = img.object_size
        for off in range(0, len(data), step):
            await img.write(off, data[off:off + step])
        await img.close()
        return 0
    if cmd == "mirror":
        from ceph_tpu.rbd.mirror import MirrorReplayer

        dst_io = client.open_ioctx(args.dst_pool)
        m = MirrorReplayer(ioctx, dst_io, args.image)
        await m.bootstrap()
        applied = await m.replay_once()
        print(json.dumps({"bootstrapped": True,
                          "events_replayed": applied}))
        return 0
    if cmd == "deep-cp":
        from ceph_tpu.rbd.migrate import deep_copy

        dst_io = client.open_ioctx(args.dest_pool) \
            if args.dest_pool else ioctx
        new_id = await deep_copy(ioctx, args.image, dst_io,
                                 args.dest, data_pool=args.data_pool)
        print(json.dumps({"id": new_id}))
        return 0
    if cmd == "migration":
        from ceph_tpu.rbd import migrate as _mg

        dst_io = client.open_ioctx(args.dest_pool) \
            if args.dest_pool else ioctx
        if args.verb == "prepare":
            if not args.dest:
                print("migration prepare needs a dest image",
                      file=sys.stderr)
                return 22
            new_id = await _mg.migration_prepare(
                ioctx, args.image, dst_io, args.dest,
                data_pool=args.data_pool)
            print(json.dumps({"id": new_id, "state": "prepared"}))
            return 0
        fn = {"execute": _mg.migration_execute,
              "commit": _mg.migration_commit,
              "abort": _mg.migration_abort}[args.verb]
        await fn(dst_io, args.image)
        print(json.dumps({"state": args.verb}))
        return 0
    if cmd == "bench":
        return await _bench(ioctx, rbd, args)
    if cmd == "replay":
        from ceph_tpu.rbd.replay import replay_trace

        img = await rbd.open(ioctx, args.image)
        # stream the trace off-loop in bounded batches: traces can be
        # multi-GiB, and a sync file handle would block the loop
        stats = await replay_trace(fileio.iter_lines(args.trace), img,
                                   speed=args.speed)
        await img.close()
        print(json.dumps(stats))
        return 0
    if cmd == "trash":
        if args.verb == "mv":
            image_id = await rbd.trash_mv(ioctx, args.target,
                                          delay=args.delay)
            print(json.dumps({"id": image_id}))
        elif args.verb == "ls":
            for e in await rbd.trash_ls(ioctx):
                print(json.dumps(e))
        elif args.verb == "restore":
            name = await rbd.trash_restore(ioctx, args.target,
                                           new_name=args.name)
            print(json.dumps({"name": name}))
        elif args.verb == "rm":
            await rbd.trash_rm(ioctx, args.target, force=args.force)
        elif args.verb == "purge":
            n = await rbd.trash_purge(ioctx)
            print(json.dumps({"removed": n}))
        return 0
    print(f"unknown command {cmd}", file=sys.stderr)
    return 22


async def _snap(ioctx, rbd: RBD, args) -> int:
    name, snap = _img_snap(args.spec)
    img = await rbd.open(ioctx, name)
    try:
        verb = args.verb
        if verb == "ls":
            for s in await img.snap_list():
                print(json.dumps(s))
            return 0
        if snap is None:
            print("need image@snap", file=sys.stderr)
            return 22
        if verb == "create":
            await img.snap_create(snap)
        elif verb == "rm":
            await img.snap_remove(snap)
        elif verb == "protect":
            await img.snap_protect(snap)
        elif verb == "unprotect":
            await img.snap_unprotect(snap)
        elif verb == "rollback":
            await img.snap_rollback(snap)
        else:
            print(f"unknown snap verb {verb}", file=sys.stderr)
            return 22
        return 0
    finally:
        await img.close()


async def _bench(ioctx, rbd: RBD, args) -> int:
    """`rbd bench` (tools/rbd/action/Bench.cc role): drive the image
    with N concurrent sequential/random IOs and report ops/s, MB/s."""
    import time as _time

    io_size = _size(args.io_size)
    total = _size(args.io_total)
    img = await rbd.open(ioctx, args.image)
    if img.size() < io_size:
        print("image smaller than --io-size", file=sys.stderr)
        return 22
    ops = max(1, total // io_size)
    span = img.size() - io_size
    # deterministic LCG offsets for rand (no retry loops, replayable)
    state = 0x5DEECE66D

    def offsets():
        nonlocal state
        pos = 0
        for _ in range(ops):
            if args.io_pattern == "rand":
                state = (state * 6364136223846793005 + 1442695040888963407) \
                    & ((1 << 64) - 1)
                yield (state >> 16) % (span + 1) if span else 0
            else:
                yield pos
                pos = (pos + io_size) % (span + 1 if span else 1)

    payload = bytes(io_size)
    sem = asyncio.Semaphore(args.io_threads)
    did = {"read": 0, "write": 0}
    target = img
    trace_fh = None
    if getattr(args, "trace", None):
        from ceph_tpu.rbd.replay import ImageTracer

        trace_fh = open(args.trace, "w")
        target = ImageTracer(img, trace_fh)

    async def one(i: int, off: int) -> None:
        async with sem:
            write = args.io_type == "write" or (
                args.io_type == "readwrite" and i % 2 == 0)
            if write:
                await target.write(off, payload)
                did["write"] += 1
            else:
                await target.read(off, io_size)
                did["read"] += 1

    t0 = _time.perf_counter()
    await asyncio.gather(*(one(i, off)
                           for i, off in enumerate(offsets())))
    dt = _time.perf_counter() - t0
    await target.close()
    if trace_fh is not None:
        trace_fh.close()
    print(json.dumps({
        "io_type": args.io_type, "io_size": io_size, "ops": ops,
        "reads": did["read"], "writes": did["write"],
        "elapsed_s": round(dt, 4),
        "ops_per_sec": round(ops / dt, 2),
        "mb_per_sec": round(ops * io_size / dt / (1 << 20), 2)}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rbd")
    ap.add_argument("-m", "--mon", required=True)
    ap.add_argument("-p", "--pool", default="rbd")
    ap.add_argument("--secret", default="")
    sub = ap.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("create")
    c.add_argument("image")
    c.add_argument("--size", required=True)
    c.add_argument("--order", type=int, default=22)
    c.add_argument("--data-pool", default=None)
    c.add_argument("--exclusive-lock", action="store_true")
    c.add_argument("--object-map", action="store_true")
    c.add_argument("--journaling", action="store_true")

    sub.add_parser("ls")
    for name in ("rm", "info", "flatten", "children"):
        sp = sub.add_parser(name)
        sp.add_argument("image")
    r = sub.add_parser("resize")
    r.add_argument("image")
    r.add_argument("--size", required=True)
    s = sub.add_parser("snap")
    s.add_argument("verb",
                   choices=["create", "ls", "rm", "protect",
                            "unprotect", "rollback"])
    s.add_argument("spec", help="image or image@snap")
    cl = sub.add_parser("clone")
    cl.add_argument("parent", help="image@snap")
    cl.add_argument("child")
    cl.add_argument("--data-pool", default=None)
    e = sub.add_parser("export")
    e.add_argument("image", help="image or image@snap")
    e.add_argument("path")
    i = sub.add_parser("import")
    i.add_argument("path")
    i.add_argument("image")
    i.add_argument("--order", type=int, default=22)
    mi = sub.add_parser("mirror")
    mi.add_argument("image")
    mi.add_argument("--dst-pool", required=True)
    dc = sub.add_parser("deep-cp")
    dc.add_argument("image")
    dc.add_argument("dest")
    dc.add_argument("--dest-pool", default=None)
    dc.add_argument("--data-pool", default=None)
    mg = sub.add_parser("migration")
    mg.add_argument("verb", choices=["prepare", "execute",
                                     "commit", "abort"])
    mg.add_argument("image")
    mg.add_argument("dest", nargs="?", default=None,
                    help="dest image (prepare only)")
    mg.add_argument("--dest-pool", default=None)
    mg.add_argument("--data-pool", default=None)
    be = sub.add_parser("bench")
    be.add_argument("image")
    be.add_argument("--io-type", choices=["write", "read",
                                          "readwrite"],
                    default="write")
    be.add_argument("--io-size", default="4K")
    be.add_argument("--io-total", default="16M")
    be.add_argument("--io-pattern", choices=["seq", "rand"],
                    default="seq")
    be.add_argument("--io-threads", type=int, default=16)
    be.add_argument("--trace", default=None,
                    help="record the workload as a JSONL trace")
    rp = sub.add_parser("replay")
    rp.add_argument("trace")
    rp.add_argument("image")
    rp.add_argument("--speed", type=float, default=1.0,
                    help="pacing multiplier (0 = full speed)")
    tr = sub.add_parser("trash")
    tr.add_argument("verb", choices=["mv", "ls", "restore", "rm",
                                     "purge"])
    tr.add_argument("target", nargs="?", default="",
                    help="image name (mv) or image id (restore/rm)")
    tr.add_argument("--delay", type=float, default=0.0)
    tr.add_argument("--force", action="store_true")
    tr.add_argument("--name", default=None,
                    help="restore under a different name")

    args = ap.parse_args(argv)
    try:
        return asyncio.run(_run(args))
    except RadosError as e:
        print(f"rbd: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())

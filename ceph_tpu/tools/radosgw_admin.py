"""radosgw-admin: RGW user administration.

Reference parity: the radosgw-admin `user` command family
(/root/reference/src/rgw/rgw_admin.cc) — durable user records with
S3 key pairs, listed/suspended/removed; the gateway authenticates
them from the same table (short-TTL cached).

    python -m ceph_tpu.tools.radosgw_admin -m MON user create \\
        --uid alice --display-name "Alice"
    ... user ls | user info --uid alice | user suspend --uid alice
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.rados.client import RadosClient
from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.gateway import RGWError


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="radosgw-admin")
    ap.add_argument("-m", "--mon", required=True)
    ap.add_argument("--data-pool", default="rgw.data")
    ap.add_argument("--meta-pool", default="rgw.meta")
    ap.add_argument("--secret", default="")
    sub = ap.add_subparsers(dest="cmd", required=True)
    us = sub.add_parser("user")
    us.add_argument("verb", choices=["create", "ls", "info", "rm",
                                     "suspend", "enable"])
    us.add_argument("--uid", default="")
    us.add_argument("--display-name", default="")
    us.add_argument("--access-key", default=None)
    us.add_argument("--secret-key", default=None)

    args = ap.parse_args(argv)
    try:
        return asyncio.run(_run(args))
    except RGWError as e:
        print(f"radosgw-admin: {e}", file=sys.stderr)
        return 1


async def _run(args) -> int:
    # no fixed entity name: repeated CLI runs must not collide in the
    # OSDs' (client, tid) reqid dedup cache (client.py's uniqueness
    # invariant) — the default per-process uuid keeps runs distinct
    client = RadosClient(args.mon, secret=args.secret or None)
    await client.connect()
    try:
        rgw = RGWLite(client, args.data_pool, args.meta_pool)
        verb = args.verb
        if verb != "ls" and not args.uid:
            print("--uid required", file=sys.stderr)
            return 22
        if verb == "create":
            doc = await rgw.user_create(
                args.uid, display_name=args.display_name,
                access_key=args.access_key,
                secret_key=args.secret_key)
            print(json.dumps(doc, indent=2, sort_keys=True))
        elif verb == "ls":
            print(json.dumps(await rgw.user_list()))
        elif verb == "info":
            print(json.dumps(await rgw.user_info(args.uid),
                             indent=2, sort_keys=True))
        elif verb == "rm":
            await rgw.user_rm(args.uid)
        elif verb in ("suspend", "enable"):
            await rgw.user_set_suspended(args.uid,
                                         verb == "suspend")
        return 0
    finally:
        await client.shutdown()


if __name__ == "__main__":
    sys.exit(main())

"""radosgw-admin: RGW user administration.

Reference parity: the radosgw-admin `user` command family
(/root/reference/src/rgw/rgw_admin.cc) — durable user records with
S3 key pairs, listed/suspended/removed; the gateway authenticates
them from the same table (short-TTL cached).

    python -m ceph_tpu.tools.radosgw_admin -m MON user create \\
        --uid alice --display-name "Alice"
    ... user ls | user info --uid alice | user suspend --uid alice
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.rados.client import RadosClient
from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.gateway import RGWError


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="radosgw-admin")
    ap.add_argument("-m", "--mon", required=True)
    ap.add_argument("--data-pool", default="rgw.data")
    ap.add_argument("--meta-pool", default="rgw.meta")
    ap.add_argument("--secret", default="")
    sub = ap.add_subparsers(dest="cmd", required=True)
    us = sub.add_parser("user")
    us.add_argument("verb", choices=["create", "ls", "info", "rm",
                                     "suspend", "enable"])
    us.add_argument("--uid", default="")
    us.add_argument("--display-name", default="")
    us.add_argument("--access-key", default=None)
    us.add_argument("--secret-key", default=None)
    sy = sub.add_parser("sync")
    sy.add_argument("verb", choices=["full", "run", "trim"])
    sy.add_argument("--dest-mon", required=True,
                    help="destination zone's mon address")
    sy.add_argument("--zone", default="master",
                    help="this (source) zone's name")
    sy.add_argument("--dest-zone", default="secondary")
    sy.add_argument("--dest-secret", default="")

    args = ap.parse_args(argv)
    try:
        return asyncio.run(_run(args))
    except (RGWError, ValueError, KeyError) as e:
        # ValueError: e.g. sync with identical zone names;
        # KeyError: a named pool does not exist on that cluster
        print(f"radosgw-admin: {e}", file=sys.stderr)
        return 1


async def _run(args) -> int:
    # no fixed entity name: repeated CLI runs must not collide in the
    # OSDs' (client, tid) reqid dedup cache (client.py's uniqueness
    # invariant) — the default per-process uuid keeps runs distinct
    client = RadosClient(args.mon, secret=args.secret or None)
    await client.connect()
    try:
        if args.cmd == "sync":
            return await _sync(client, args)
        rgw = RGWLite(client, args.data_pool, args.meta_pool)
        verb = args.verb
        if verb != "ls" and not args.uid:
            print("--uid required", file=sys.stderr)
            return 22
        if verb == "create":
            doc = await rgw.user_create(
                args.uid, display_name=args.display_name,
                access_key=args.access_key,
                secret_key=args.secret_key)
            print(json.dumps(doc, indent=2, sort_keys=True))
        elif verb == "ls":
            print(json.dumps(await rgw.user_list()))
        elif verb == "info":
            print(json.dumps(await rgw.user_info(args.uid),
                             indent=2, sort_keys=True))
        elif verb == "rm":
            await rgw.user_rm(args.uid)
        elif verb in ("suspend", "enable"):
            await rgw.user_set_suspended(args.uid,
                                         verb == "suspend")
        return 0
    finally:
        await client.shutdown()


async def _sync(src_client, args) -> int:
    """One-shot multisite sync pass src -> dest (the radosgw-admin
    `data sync run` role; continuous replication embeds RGWSyncAgent
    instead)."""
    from ceph_tpu.rgw.multisite import RGWSyncAgent

    dst_client = RadosClient(args.dest_mon,
                             secret=args.dest_secret or None)
    await dst_client.connect()
    try:
        src = RGWLite(src_client, args.data_pool, args.meta_pool,
                      zone=args.zone)
        dst = RGWLite(dst_client, args.data_pool, args.meta_pool,
                      zone=args.dest_zone)
        agent = RGWSyncAgent(src, dst)
        if args.verb == "full":
            n = await agent.full_sync()
            print(json.dumps({"keys_reconciled": n}))
        elif args.verb == "run":
            applied = await agent.sync_once()
            print(json.dumps({
                "entries_applied": applied,
                "objects_copied": agent.objects_copied,
                "entries_skipped": agent.entries_skipped}))
        elif args.verb == "trim":
            print(json.dumps(
                {"trimmed": await agent.trim_source_log()}))
        return 0
    finally:
        await dst_client.shutdown()


if __name__ == "__main__":
    sys.exit(main())

"""Pallas TPU kernels for GF(2^8) matrix x data — the hot EC path.

Formulation: packed-word xtime.  Each int32 lane carries 4 data bytes.
Multiplying a whole word by x (the GF(2^8) doubling step, polynomial
0x11d) is 6 bitwise lane-ops with cross-byte contamination masked off:

    t   = v & 0x80808080        # bit 7 of every byte
    u   = (v << 1) & 0xfefefefe # shift, drop cross-byte carry-in
    out = u ^ ((t >> 7) * 0x1d) # reduce by p(x) per byte

A coefficient c contributes the XOR of the xtime-powers selected by its
set bits, so `parity = M (*) data` is a short XOR network over 8 power
ladders — ~13 VPU lane-ops per data byte, HBM traffic exactly
data-in + parity-out.  Measured on a v5e chip: ~360 GiB/s of data for
RS k=8,m=3 (vs ~19 GiB/s for the XLA bit-decomposition path, whose bf16
bit-plane materialization is HBM-bound).

Two kernels share the ladder:

* specialized: the coefficient matrix is baked in at trace time and the
  XOR network unrolls to straight-line VPU code.  Fastest, but Mosaic
  pays a large one-time compile per matrix — so it is reserved for
  *registered* encode matrices (the codec registers its generator at
  init; see `register_matrix`).
* generic: the coefficient matrix is a runtime SMEM operand; one compile
  per (r, k, geometry) covers every erasure pattern.  This is the decode
  path — Reed-Solomon decode matrices vary per erasure signature and
  per-pattern recompiles (~1 min each through the AOT helper) would
  stall recovery.

Layout contract (the part that makes or breaks performance): the device
representation of EC buffers is int32 *words*, shape (B, K, S//512, 128)
— full (sublane, lane) tiles.  uint8 device arrays are NOT accepted:
a device-side uint8<->int32 bitcast is a lane-regrouping relayout that
costs more than the entire encode (measured: ~30 ms per 64 MiB, which
is what previously capped this kernel at 2 GiB/s).  Host bytes view as
words for free (`words_from_bytes`).

The xtime identity is textbook GF(2^8) arithmetic; the reference's SIMD
equivalents live in /root/reference/src/erasure-code/ (jerasure/
gf-complete PSHUFB tables, isa-l; e.g. ErasureCodeIsa.cc:119-131).
"""

from __future__ import annotations

import functools
import os

import numpy as np
from ceph_tpu.common import flags

try:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

# Rows of 128 int32 lanes per tile.  TS=64 measured fastest on v5e
# (364 GiB/s vs 339 at TS=128 for RS 8+3).
_TS = 64

_M80 = int(0x80808080) - (1 << 32)  # as signed int32 literals
_MFE = int(0xFEFEFEFE) - (1 << 32)

# Encode matrices registered by codecs: these (and only these) get the
# unrolled specialized kernel; everything else uses the generic one.
_registered: set = set()

# Test hook: force interpret-mode pallas (runs on CPU) regardless of
# platform, so the kernel logic is exercised in the CPU test tier.
FORCE_INTERPRET = False


def _coeff_key(matrix: np.ndarray) -> tuple:
    m = np.asarray(matrix, dtype=np.uint8)
    return tuple(tuple(int(c) for c in row) for row in m)


def register_matrix(matrix: np.ndarray) -> None:
    """Mark a generator matrix as hot: it will be compiled into the
    specialized unrolled kernel on first use (compile cost amortized
    across the lifetime of the codec)."""
    if len(_registered) < 64:
        _registered.add(_coeff_key(matrix))


def words_from_bytes(data: np.ndarray) -> np.ndarray:
    """(..., S) uint8 host array -> (..., S//512, 128) int32 view (free)."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    s = data.shape[-1]
    assert s % 512 == 0, s
    return data.view(np.int32).reshape(*data.shape[:-1], s // 512, 128)


def bytes_from_words(words: np.ndarray) -> np.ndarray:
    """(..., R4, 128) int32 host array -> (..., R4*512) uint8 view (free)."""
    words = np.ascontiguousarray(words, dtype=np.int32)
    r4 = words.shape[-2]
    return words.view(np.uint8).reshape(*words.shape[:-2], r4 * 512)


def supported(data_shape, platform: str | None = None) -> bool:
    """True when the words kernel can run: a TPU backend (or forced
    interpret mode) and S a multiple of 512 bytes (one (1,128) int32
    row).  CEPH_TPU_PALLAS=0 is the kill switch."""
    if not flags.enabled("CEPH_TPU_PALLAS"):
        return False
    if not HAVE_JAX:
        return False
    if not FORCE_INTERPRET:
        try:
            plat = platform or jax.devices()[0].platform
        except Exception:
            return False
        if plat != "tpu":
            return False
    s = data_shape[-1]
    return s % 512 == 0 and s > 0


if HAVE_JAX:

    def _xtime(v):
        """Multiply every packed byte by x in GF(2^8)/0x11d (6 lane-ops).

        The >>7 must be a LOGICAL shift: int32 arithmetic shift would
        smear the sign across the top byte's reduction mask."""
        t = v & jnp.int32(_M80)
        u = (v << 1) & jnp.int32(_MFE)
        hi = jax.lax.shift_right_logical(t, jnp.int32(7))
        return u ^ (hi * jnp.int32(0x1D))

    def _spec_kernel(d_ref, o_ref, *, coeffs, k: int, r: int):
        """Coefficients static: the double loop unrolls at trace time
        into straight-line vector code (XOR network over the ladder)."""
        v = d_ref[0]                       # (K, TS, 128) int32
        acc = [None] * r
        u = [v[i] for i in range(k)]
        for s in range(8):
            for j in range(r):
                for i in range(k):
                    if (coeffs[j][i] >> s) & 1:
                        acc[j] = u[i] if acc[j] is None else acc[j] ^ u[i]
            if s != 7:
                u = [_xtime(x) for x in u]
        zero = None
        for j in range(r):
            if acc[j] is None:
                if zero is None:
                    zero = jnp.zeros_like(v[0])
                acc[j] = zero
            o_ref[0, j] = acc[j]

    def _gen_kernel(m_ref, d_ref, o_ref, *, k: int, r: int):
        """Coefficients from SMEM: mask = -bit broadcasts a scalar into
        an AND, so one compile covers every matrix of this shape."""
        v = d_ref[0]
        u = [v[i] for i in range(k)]
        pows = [u]
        for _ in range(7):
            u = [_xtime(x) for x in u]
            pows.append(u)
        for j in range(r):
            acc = None
            for i in range(k):
                c = m_ref[j, i]
                for s in range(8):
                    term = pows[s][i] & (-((c >> s) & 1))
                    acc = term if acc is None else acc ^ term
            o_ref[0, j] = acc

    @functools.lru_cache(maxsize=128)
    def _spec_call(coeffs, b: int, r4: int, ts: int):
        r, k = len(coeffs), len(coeffs[0])
        kern = functools.partial(_spec_kernel, coeffs=coeffs, k=k, r=r)
        return pl.pallas_call(
            kern,
            grid=(b, r4 // ts),
            in_specs=[pl.BlockSpec((1, k, ts, 128),
                                   lambda bi, ti: (bi, 0, ti, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((1, r, ts, 128),
                                   lambda bi, ti: (bi, 0, ti, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((b, r, r4, 128), jnp.int32),
            interpret=FORCE_INTERPRET,
        )

    @functools.lru_cache(maxsize=64)
    def _gen_call(r: int, k: int, b: int, r4: int, ts: int):
        kern = functools.partial(_gen_kernel, k=k, r=r)
        return pl.pallas_call(
            kern,
            grid=(b, r4 // ts),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec((1, k, ts, 128),
                                   lambda bi, ti: (bi, 0, ti, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((1, r, ts, 128),
                                   lambda bi, ti: (bi, 0, ti, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((b, r, r4, 128), jnp.int32),
            interpret=FORCE_INTERPRET,
        )

    def _pick_ts(r4: int) -> int:
        ts = min(_TS, r4)
        while r4 % ts:
            ts //= 2
        return ts

    def gf_matmul_words(matrix: np.ndarray, words):
        """(R,K) GF(2^8) matrix x (B,K,R4,128) int32 device words ->
        (B,R,R4,128) int32 device words.  Dispatches the specialized
        kernel for registered matrices, the generic one otherwise."""
        key = _coeff_key(matrix)
        r, k = len(key), len(key[0])
        b, kk, r4, lanes = words.shape
        assert kk == k and lanes == 128, (words.shape, matrix.shape)
        ts = _pick_ts(r4)
        if key in _registered:
            return _spec_call(key, b, r4, ts)(words)
        mwords = jnp.asarray(np.asarray(matrix, np.uint8).astype(np.int32))
        return _gen_call(r, k, b, r4, ts)(mwords, words)

    def gf_matmul_words_runtime(mwords, words):
        """Traceable words-kernel entry: the (R,K) coefficient matrix is
        a RUNTIME int32 operand (the generic SMEM kernel), so one
        compile per shape covers every matrix — the decode path's
        contract (per-erasure-signature matrices must not retrace)."""
        b, k, r4, lanes = words.shape
        r = mwords.shape[0]
        assert lanes == 128
        return _gen_call(r, k, b, r4, _pick_ts(r4))(mwords, words)

    def gf_matmul_pallas(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
        """Host entry: (..., K, S) uint8 numpy -> (..., R, S) uint8 numpy
        (leading dims flattened into the kernel batch axis).

        Host<->word conversions are numpy views (free); the transfer and
        the kernel are the only real costs."""
        data = np.asarray(data)
        lead = data.shape[:-2]
        k, s = data.shape[-2:]
        data = data.reshape((-1, k, s) if lead else (1, k, s))
        w = jnp.asarray(words_from_bytes(data))
        out = np.asarray(gf_matmul_words(matrix, w))
        res = bytes_from_words(out)
        return res.reshape(*lead, res.shape[-2], s) if lead else res[0]

"""Pallas TPU kernel for GF(2^8) matrix x data — the hot EC kernel.

Two device formulations exist for `parity = M (*) data` over GF(2^8):

1. Bit-decomposition on the MXU (gf.gf2_matmul_bytes): exact, but every
   data byte must be unpacked into 8 one-bit lane elements before the
   matmul.  Whether XLA materializes the expansion in HBM or a kernel
   does it in VMEM, the VPU pays ~8 lane-ops per byte at one *bit* per
   lane — measured ceiling ~19 GiB/s on a v5e regardless of tiling.

2. This kernel: the xtime/XOR formulation on *packed words*.  Each int32
   lane carries 4 data bytes.  Multiplying a whole row by x (aka xtime,
   the GF(2^8) doubling step) is 6 bitwise lane-ops with all cross-byte
   contamination masked off:

       t   = v & 0x80808080        # bit 7 of every byte
       u   = (v << 1) & 0xfefefefe # shift, drop cross-byte carry-in
       out = u ^ ((t >> 7) * 0x1d) # reduce by p(x) = 0x11d per byte

   A coefficient c then contributes XOR of the xtime-powers selected by
   c's set bits.  The matrix is static at trace time, so the kernel
   unrolls to straight-line VPU code: ~12 lane-ops per data byte at 4
   bytes per lane — ~4x less VPU work than bit-decomposition, and HBM
   sees only data-in + parity-out.

The xtime identity is textbook GF(2^8) arithmetic (any AES or
Reed-Solomon text); the reference's SIMD equivalents live in
/root/reference/src/erasure-code/ (jerasure/gf-complete, isa-l).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Inner tile per data row: (TS, 128) int32 lanes = TS*512 data bytes.
# At TS=32 a K=8 tile holds 128 KiB of data resident in VMEM.
_TS = 32

_M80 = int(0x80808080) - (1 << 32)  # as signed int32 literals
_MFE = int(0xFEFEFEFE) - (1 << 32)


def _xtime(v):
    """Multiply every packed byte by x in GF(2^8)/0x11d (6 lane-ops).

    The >>7 must be a LOGICAL shift: int32 arithmetic shift would smear
    the sign across the top byte's reduction mask."""
    t = v & jnp.int32(_M80)
    u = (v << 1) & jnp.int32(_MFE)
    hi = jax.lax.shift_right_logical(t, jnp.int32(7))
    return u ^ (hi * jnp.int32(0x1D))


def _kernel(d_ref, out_ref, *, coeffs, k: int, r: int):
    """One (batch, column tile): acc_j = XOR_i c_ji (*) d_i, unrolled.

    coeffs is a static (r, k) tuple-of-tuples of python ints, so the
    double loop below unrolls at trace time into pure vector code.
    Every array the VPU touches is (TS, 128) — full sublane x lane
    tiles; per-row slices of a (K, T) layout would run at 1/8 VPU
    utilization."""
    v = d_ref[0]                      # (K, TS, 128) int32, 4 bytes/lane
    acc = [None] * r
    u = [v[i] for i in range(k)]      # K x (TS, 128)
    for s in range(8):                # xtime power s of every input row
        for j in range(r):
            for i in range(k):
                if (coeffs[j][i] >> s) & 1:
                    acc[j] = u[i] if acc[j] is None else acc[j] ^ u[i]
        if s != 7:
            u = [_xtime(x) for x in u]
    zero = jnp.zeros_like(v[0])
    out_ref[0] = jnp.stack(
        [a if a is not None else zero for a in acc])


@functools.partial(jax.jit, static_argnames=("coeffs", "ts"))
def _matmul_words(d4, coeffs, ts: int):
    r, k = len(coeffs), len(coeffs[0])
    g = d4.shape[0]
    kern = functools.partial(_kernel, coeffs=coeffs, k=k, r=r)
    return pl.pallas_call(
        kern,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((1, k, ts, 128),
                         lambda gi: (gi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, ts, 128),
                               lambda gi: (gi, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((g, r, ts, 128), jnp.int32),
    )(d4)


def supported(data_shape) -> bool:
    """Handles (..., K, S) uint8 with S a multiple of 2048 on a TPU
    backend (2048 bytes = one (4, 128) int32 tile row minimum).

    Gated by CEPH_TPU_PALLAS until validated on real TPU hardware (set
    CEPH_TPU_PALLAS=0 to force the XLA path)."""
    import os

    if os.environ.get("CEPH_TPU_PALLAS", "0") != "1":
        return False
    try:
        if jax.devices()[0].platform != "tpu":
            return False
    except Exception:
        return False
    s = data_shape[-1]
    return s % 2048 == 0 and s > 0


def gf_matmul_words_pallas(matrix: np.ndarray, data):
    """matrix (R,K) uint8 x data (..., K, S) uint8 -> (..., R, S) uint8
    via the packed-word xtime kernel.  data may be a device array."""
    m = np.asarray(matrix, dtype=np.uint8)
    r, k = m.shape
    coeffs = tuple(tuple(int(c) for c in row) for row in m)
    data = jnp.asarray(data, dtype=jnp.uint8)
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    lead = data.shape[:-2]
    b = int(np.prod(lead)) if lead else 1
    s = data.shape[-1]
    s4 = s // 4
    ts = _TS
    while ts > 4 and s4 % (ts * 128):
        ts //= 2
    nt = s4 // (ts * 128)
    # grid = (b*nt,): fold batch and column tiles into one axis so every
    # block is a plain 4-D (1, K, TS, 128) — the transpose that brings K
    # next to the tile is one extra device pass, far cheaper than the
    # expansion it replaces
    d5 = jax.lax.bitcast_convert_type(
        data.reshape(b, k, s4, 4), jnp.int32).reshape(
        b, k, nt, ts, 128)
    d4 = jnp.moveaxis(d5, 2, 1).reshape(b * nt, k, ts, 128)
    out4 = _matmul_words(d4, coeffs, ts)
    out = jnp.moveaxis(out4.reshape(b, nt, r, ts, 128), 1, 2)
    out = jax.lax.bitcast_convert_type(
        out.reshape(b, r, s4), jnp.uint8).reshape(*lead, r, s)
    return out[0] if squeeze else out

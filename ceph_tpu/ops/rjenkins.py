"""Robert Jenkins' 32-bit mix hash — CRUSH's hash family, as tensor kernels.

Reference: /root/reference/src/crush/hash.c (crush_hash32_rjenkins1_{1..5},
seed 1315423911).  The algorithm is Jenkins' public-domain evahash mix.  Two
implementations with identical results:

- numpy (uint32 wraparound) for the exact host mapper;
- jax (uint32) for the vmapped bulk-placement kernel — every op is
  elementwise int32-lane work, so millions of inputs hash in one dispatch.
"""

from __future__ import annotations

import numpy as np

CRUSH_HASH_SEED = np.uint32(1315423911)
CRUSH_HASH_RJENKINS1 = 0


def _mix(a, b, c, xp):
    """One Jenkins mix round; xp is the array namespace (numpy or jax.numpy).

    uint32 wraparound is the defined behavior; the errstate guard silences
    numpy's overflow warnings for 0-d operands (no-op under jax).
    """
    u32 = lambda v: v.astype(xp.uint32) if hasattr(v, "astype") else xp.uint32(v)
    a, b, c = u32(a), u32(b), u32(c)
    with np.errstate(over="ignore"):
        a = a - b; a = a - c; a = a ^ (c >> 13)
        b = b - c; b = b - a; b = b ^ (a << 8)
        c = c - a; c = c - b; c = c ^ (b >> 13)
        a = a - b; a = a - c; a = a ^ (c >> 12)
        b = b - c; b = b - a; b = b ^ (a << 16)
        c = c - a; c = c - b; c = c ^ (b >> 5)
        a = a - b; a = a - c; a = a ^ (c >> 3)
        b = b - c; b = b - a; b = b ^ (a << 10)
        c = c - a; c = c - b; c = c ^ (b >> 15)
    return a, b, c


def _as_u32(xp, *vals):
    return tuple(xp.asarray(v).astype(xp.uint32) for v in vals)


def hash32(a, xp=np):
    (a,) = _as_u32(xp, a)
    h = CRUSH_HASH_SEED ^ a
    b, x, y = a, xp.uint32(231232), xp.uint32(1232)
    b, x, h = _mix(b, x, h, xp)
    y, a, h = _mix(y, a, h, xp)
    return h


def hash32_2(a, b, xp=np):
    a, b = _as_u32(xp, a, b)
    h = CRUSH_HASH_SEED ^ a ^ b
    x, y = xp.uint32(231232), xp.uint32(1232)
    a, b, h = _mix(a, b, h, xp)
    x, a, h = _mix(x, a, h, xp)
    b, y, h = _mix(b, y, h, xp)
    return h


def hash32_3(a, b, c, xp=np):
    a, b, c = _as_u32(xp, a, b, c)
    h = CRUSH_HASH_SEED ^ a ^ b ^ c
    x, y = xp.uint32(231232), xp.uint32(1232)
    a, b, h = _mix(a, b, h, xp)
    c, x, h = _mix(c, x, h, xp)
    y, a, h = _mix(y, a, h, xp)
    b, x, h = _mix(b, x, h, xp)
    y, c, h = _mix(y, c, h, xp)
    return h


def hash32_4(a, b, c, d, xp=np):
    a, b, c, d = _as_u32(xp, a, b, c, d)
    h = CRUSH_HASH_SEED ^ a ^ b ^ c ^ d
    x, y = xp.uint32(231232), xp.uint32(1232)
    a, b, h = _mix(a, b, h, xp)
    c, d, h = _mix(c, d, h, xp)
    a, x, h = _mix(a, x, h, xp)
    y, b, h = _mix(y, b, h, xp)
    c, x, h = _mix(c, x, h, xp)
    y, d, h = _mix(y, d, h, xp)
    return h


def hash32_5(a, b, c, d, e, xp=np):
    a, b, c, d, e = _as_u32(xp, a, b, c, d, e)
    h = CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e
    x, y = xp.uint32(231232), xp.uint32(1232)
    a, b, h = _mix(a, b, h, xp)
    c, d, h = _mix(c, d, h, xp)
    e, x, h = _mix(e, x, h, xp)
    y, a, h = _mix(y, a, h, xp)
    b, x, h = _mix(b, x, h, xp)
    y, c, h = _mix(y, c, h, xp)
    d, x, h = _mix(d, x, h, xp)
    y, e, h = _mix(y, e, h, xp)
    return h


def ceph_str_hash_rjenkins(data: bytes) -> int:
    """Jenkins lookup2 string hash (reference src/common/ceph_hash.cc:21-78)
    — hashes object names onto PG seeds (hobject_t::get_hash)."""
    M = 0xFFFFFFFF

    def mix(a, b, c):
        a = (a - b - c) & M; a ^= c >> 13
        b = (b - c - a) & M; b ^= (a << 8) & M
        c = (c - a - b) & M; c ^= b >> 13
        a = (a - b - c) & M; a ^= c >> 12
        b = (b - c - a) & M; b ^= (a << 16) & M
        c = (c - a - b) & M; c ^= b >> 5
        a = (a - b - c) & M; a ^= c >> 3
        b = (b - c - a) & M; b ^= (a << 10) & M
        c = (c - a - b) & M; c ^= b >> 15
        return a, b, c

    length = len(data)
    a = b = 0x9E3779B9
    c = 0
    i = 0
    while length - i >= 12:
        a = (a + int.from_bytes(data[i:i + 4], "little")) & M
        b = (b + int.from_bytes(data[i + 4:i + 8], "little")) & M
        c = (c + int.from_bytes(data[i + 8:i + 12], "little")) & M
        a, b, c = mix(a, b, c)
        i += 12
    c = (c + length) & M
    tail = data[i:]
    n = len(tail)
    if n >= 11: c = (c + (tail[10] << 24)) & M
    if n >= 10: c = (c + (tail[9] << 16)) & M
    if n >= 9:  c = (c + (tail[8] << 8)) & M
    if n >= 8:  b = (b + (tail[7] << 24)) & M
    if n >= 7:  b = (b + (tail[6] << 16)) & M
    if n >= 6:  b = (b + (tail[5] << 8)) & M
    if n >= 5:  b = (b + tail[4]) & M
    if n >= 4:  a = (a + (tail[3] << 24)) & M
    if n >= 3:  a = (a + (tail[2] << 16)) & M
    if n >= 2:  a = (a + (tail[1] << 8)) & M
    if n >= 1:  a = (a + tail[0]) & M
    _a, _b, c = mix(a, b, c)
    return c

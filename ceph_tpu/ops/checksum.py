"""Checksums: crc32c / xxhash, host-native and TPU-batched.

Reference parity:
  - `ceph_crc32c(seed, data, len)` — Castagnoli CRC, no pre/post inversion,
    NULL data = zero run (/root/reference/src/include/crc32c.h:43-50).
  - `ceph_crc32c_zeros` O(log n) zero-run folding
    (/root/reference/src/common/crc32c.cc:216-239).
  - xxhash32/64 (vendored xxHash submodule in the reference).

TPU design: a CRC over GF(2) is linear in the message bits —
`crc(seed, msg) = Z_len(seed) XOR f(msg)` with `f` linear.  So a batch of
B equal-length blocks becomes:

  1. split each block into 64-byte cells, unpack to 512 bit-planes;
  2. one (512 -> 32) GF(2) matmul per cell computes per-cell partial CRCs
     — a (B*n, 512) x (512, 32) bf16 matmul on the MXU;
  3. a log-depth tree combine folds cells: left' = A_span @ left XOR right,
     where A_span is the 32x32 zero-run advance matrix (the same math the
     reference tabulates in crc_turbo_table);
  4. the seed's zero-run advance Z_len(seed) is a host scalar XORed in.

Blocks are front-padded with zeros to a power-of-two cell count — leading
zeros are a no-op for the zero-seeded linear part `f`, so padding does not
change the result.
"""

from __future__ import annotations

import functools

import numpy as np

from ceph_tpu import native

try:
    import jax
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

CASTAGNOLI_POLY_REFLECTED = 0x82F63B78

# ---------------------------------------------------------------------------
# Host path: native C++ with pure-python fallback
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _py_table() -> np.ndarray:
    table = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (CASTAGNOLI_POLY_REFLECTED ^ (c >> 1)) if (c & 1) else (c >> 1)
        table[i] = c
    return table


def _py_crc32c(crc: int, data: bytes) -> int:
    table = _py_table()
    for byte in data:
        crc = int(table[(crc ^ byte) & 0xFF]) ^ (crc >> 8)
    return crc


def _np_u8(data) -> np.ndarray:
    if isinstance(data, np.ndarray):
        return np.ascontiguousarray(data, dtype=np.uint8)
    return np.frombuffer(bytes(data), dtype=np.uint8)


def _as_ptr(arr: np.ndarray):
    import ctypes

    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


@functools.lru_cache(maxsize=1)
def _fast_crc():
    """Direct c_char_p prototype bound to the native symbol: bytes
    pass straight through with no per-call cast (the cast dominated the
    messenger's per-frame crcs at ~20us/call)."""
    lib = native.get_lib()
    if lib is None:
        return None
    import ctypes

    proto = ctypes.CFUNCTYPE(ctypes.c_uint32, ctypes.c_uint32,
                             ctypes.c_char_p, ctypes.c_uint64)
    return proto(("ceph_tpu_crc32c", lib))


_fast_crc_fn = None


def crc32c(crc: int, data, length: int | None = None) -> int:
    """ceph_crc32c: data=None means `length` zero bytes."""
    global _fast_crc_fn
    if data is None:
        return crc32c_zeros(crc, length or 0)
    if isinstance(data, bytes):
        # module-global binding: this is the messenger's per-frame hot
        # path, and even an lru_cache lookup per call shows up
        fast = _fast_crc_fn
        if fast is None:
            fast = _fast_crc_fn = _fast_crc()
        if fast is not None:
            return fast(crc & 0xFFFFFFFF, data, len(data))
    lib = native.get_lib()
    if lib is not None:
        if isinstance(data, (bytearray, memoryview)):
            arr = np.frombuffer(data, dtype=np.uint8)  # zero-copy view
        else:
            arr = _np_u8(data)
        return lib.ceph_tpu_crc32c(crc & 0xFFFFFFFF, _as_ptr(arr), arr.size)
    return _py_crc32c(crc & 0xFFFFFFFF, _np_u8(data).tobytes())


@functools.lru_cache(maxsize=None)
def _py_zero_mats() -> list:
    # mats[r] advances a crc through 2^r zero bytes; GF(2) column form.
    table = _py_table()
    one = [int(table[(1 << b) & 0xFF]) ^ ((1 << b) >> 8) for b in range(32)]
    mats = [one]
    for _ in range(1, 64):
        prev = mats[-1]
        mats.append([_py_mat_vec(prev, col) for col in prev])
    return mats


def _py_mat_vec(mat: list, v: int) -> int:
    out = 0
    b = 0
    while v:
        if v & 1:
            out ^= mat[b]
        v >>= 1
        b += 1
    return out


def crc32c_zeros(crc: int, length: int) -> int:
    """Advance crc through `length` zero bytes in O(log length)."""
    lib = native.get_lib()
    if lib is not None:
        return lib.ceph_tpu_crc32c_zeros(crc & 0xFFFFFFFF, length)
    mats = _py_zero_mats()
    r = 0
    crc &= 0xFFFFFFFF
    while length:
        if length & 1:
            crc = _py_mat_vec(mats[r], crc)
        length >>= 1
        r += 1
    return crc


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """crc(A||B) from crc(A)=crc_a and the zero-seeded crc(B)=crc_b."""
    return crc32c_zeros(crc_a, len_b) ^ crc_b


def crc32c_blocks(data, block_size: int, init: int = 0xFFFFFFFF) -> np.ndarray:
    """Per-block crc32c over uniform blocks (host loop, native inner)."""
    arr = _np_u8(data)
    assert arr.size % block_size == 0
    n = arr.size // block_size
    lib = native.get_lib()
    if lib is not None:
        import ctypes

        out = np.empty(n, dtype=np.uint32)
        lib.ceph_tpu_crc32c_blocks(
            _as_ptr(arr), n, block_size, init & 0xFFFFFFFF,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return out
    return np.array(
        [_py_crc32c(init & 0xFFFFFFFF,
                    arr[i * block_size:(i + 1) * block_size].tobytes())
         for i in range(n)], dtype=np.uint32)


def xxh32(data, seed: int = 0) -> int:
    lib = native.get_lib()
    arr = _np_u8(data)
    if lib is not None:
        return lib.ceph_tpu_xxh32(_as_ptr(arr), arr.size, seed & 0xFFFFFFFF)
    return _py_xxh32(arr.tobytes(), seed & 0xFFFFFFFF)


def xxh64(data, seed: int = 0) -> int:
    lib = native.get_lib()
    arr = _np_u8(data)
    if lib is not None:
        return lib.ceph_tpu_xxh64(_as_ptr(arr), arr.size,
                                  seed & 0xFFFFFFFFFFFFFFFF)
    return _py_xxh64(arr.tobytes(), seed & 0xFFFFFFFFFFFFFFFF)


# Pure-python xxhash mirrors (independent of the C++ for cross-checking).

_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF
_P32 = (2654435761, 2246822519, 3266489917, 668265263, 374761393)
_P64 = (11400714785074694791, 14029467366897019727, 1609587929392839161,
        9650029242287828579, 2870177450012600261)


def _rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & _M32


def _rotl64(x, r):
    return ((x << r) | (x >> (64 - r))) & _M64


def _py_xxh32(data: bytes, seed: int) -> int:
    p1, p2, p3, p4, p5 = _P32
    n = len(data)
    i = 0
    if n >= 16:
        v = [(seed + p1 + p2) & _M32, (seed + p2) & _M32, seed,
             (seed - p1) & _M32]
        while i + 16 <= n:
            for lane in range(4):
                w = int.from_bytes(data[i:i + 4], "little")
                v[lane] = (_rotl32((v[lane] + w * p2) & _M32, 13) * p1) & _M32
                i += 4
        h = (_rotl32(v[0], 1) + _rotl32(v[1], 7) + _rotl32(v[2], 12)
             + _rotl32(v[3], 18)) & _M32
    else:
        h = (seed + p5) & _M32
    h = (h + n) & _M32
    while i + 4 <= n:
        w = int.from_bytes(data[i:i + 4], "little")
        h = (_rotl32((h + w * p3) & _M32, 17) * p4) & _M32
        i += 4
    while i < n:
        h = (_rotl32((h + data[i] * p5) & _M32, 11) * p1) & _M32
        i += 1
    h ^= h >> 15
    h = (h * p2) & _M32
    h ^= h >> 13
    h = (h * p3) & _M32
    h ^= h >> 16
    return h


def _py_xxh64_round(acc, inp):
    return (_rotl64((acc + inp * _P64[1]) & _M64, 31) * _P64[0]) & _M64


def _py_xxh64(data: bytes, seed: int) -> int:
    p1, p2, p3, p4, p5 = _P64
    n = len(data)
    i = 0
    if n >= 32:
        v = [(seed + p1 + p2) & _M64, (seed + p2) & _M64, seed,
             (seed - p1) & _M64]
        while i + 32 <= n:
            for lane in range(4):
                w = int.from_bytes(data[i:i + 8], "little")
                v[lane] = _py_xxh64_round(v[lane], w)
                i += 8
        h = (_rotl64(v[0], 1) + _rotl64(v[1], 7) + _rotl64(v[2], 12)
             + _rotl64(v[3], 18)) & _M64
        for lane in range(4):
            h = ((h ^ _py_xxh64_round(0, v[lane])) * p1 + p4) & _M64
    else:
        h = (seed + p5) & _M64
    h = (h + n) & _M64
    while i + 8 <= n:
        w = int.from_bytes(data[i:i + 8], "little")
        h = (_rotl64(h ^ _py_xxh64_round(0, w), 27) * p1 + p4) & _M64
        i += 8
    if i + 4 <= n:
        w = int.from_bytes(data[i:i + 4], "little")
        h = (_rotl64(h ^ ((w * p1) & _M64), 23) * p2 + p3) & _M64
        i += 4
    while i < n:
        h = (_rotl64(h ^ ((data[i] * p5) & _M64), 11) * p1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * p2) & _M64
    h ^= h >> 29
    h = (h * p3) & _M64
    h ^= h >> 32
    return h


# ---------------------------------------------------------------------------
# TPU batched crc32c
# ---------------------------------------------------------------------------

_CELL = 64  # bytes per matmul cell


@functools.lru_cache(maxsize=None)
def _zero_advance_matrix(length: int) -> np.ndarray:
    """32x32 GF(2) 0/1 matrix advancing a crc through `length` zero bytes."""
    cols = []
    for b in range(32):
        v = crc32c_zeros(1 << b, length)
        cols.append([(v >> o) & 1 for o in range(32)])
    return np.array(cols, dtype=np.uint8).T  # (out_bit, in_bit)


@functools.lru_cache(maxsize=1)
def _cell_matrix() -> np.ndarray:
    """32x512 GF(2) matrix: zero-seeded crc of one 64-byte cell."""
    cols = []
    buf = np.zeros(_CELL, dtype=np.uint8)
    for i in range(_CELL):
        for b in range(8):
            buf[:] = 0
            buf[i] = 1 << b
            v = crc32c(0, buf)
            cols.append([(v >> o) & 1 for o in range(32)])
    return np.array(cols, dtype=np.uint8).T  # (32, 512)


if HAVE_JAX:

    def _mod2_matmul(bits, mat_t):
        """(..., N) 0/1 x (N, 32) -> (..., 32) over GF(2), on the MXU."""
        prod = jnp.einsum(
            "...n,nk->...k",
            bits.astype(jnp.bfloat16),
            mat_t.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return prod.astype(jnp.int32) & 1

    def make_crc_consts(length: int):
        """Device constants for crc32c_partial_bits over `length`-byte rows."""
        ncells = max(1, -(-length // _CELL))
        levels = max(0, (ncells - 1).bit_length())
        return {
            "length": length,
            "levels": levels,
            "cell_mat_t": jnp.asarray(_cell_matrix().T),
            "advances": tuple(
                jnp.asarray(_zero_advance_matrix(_CELL * (1 << lvl)).T)
                for lvl in range(levels)),
        }

    def crc32c_partial_bits(data, consts):
        """Traceable: (..., L) uint8 -> (..., 32) int32 zero-seeded crc bits.

        L = consts["length"]; rows are front-padded with zeros to a
        power-of-two cell count inside the trace (a no-op for the
        zero-seeded linear part of the CRC).
        """
        length = consts["length"]
        levels = consts["levels"]
        ncells = 1 << levels
        lead = ncells * _CELL - length
        if lead:
            pad = [(0, 0)] * (data.ndim - 1) + [(lead, 0)]
            data = jnp.pad(data, pad)
        cells = data.reshape(*data.shape[:-1], ncells, _CELL)
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((cells[..., :, None] >> shifts) & 1).reshape(
            *data.shape[:-1], ncells, _CELL * 8)
        part = _mod2_matmul(bits, consts["cell_mat_t"])  # (..., n, 32)
        for lvl in range(levels):
            pairs = part.reshape(*part.shape[:-2], part.shape[-2] // 2, 2, 32)
            left = _mod2_matmul(pairs[..., 0, :], consts["advances"][lvl])
            part = left ^ pairs[..., 1, :]
        return part[..., 0, :]

    def crc32c_partial_bits_words(words, consts):
        """crc32c_partial_bits over the device-native int32 WORD layout
        (..., L//4): bit k of a little-endian word is bit k%8 of byte
        k//8, so a 0..31 shift unpack yields exactly the byte-then-bit
        order the cell matrix expects — words stay words, no uint8
        relayout (that relayout costs more than the whole crc)."""
        length = consts["length"]
        levels = consts["levels"]
        ncells = 1 << levels
        lead = (ncells * _CELL - length) // 4
        if lead:
            pad = [(0, 0)] * (words.ndim - 1) + [(lead, 0)]
            words = jnp.pad(words, pad)
        cells = words.reshape(*words.shape[:-1], ncells, _CELL // 4)
        shifts = jnp.arange(32, dtype=jnp.int32)
        bits = ((cells[..., :, None] >> shifts) & 1).reshape(
            *words.shape[:-1], ncells, _CELL * 8)
        part = _mod2_matmul(bits, consts["cell_mat_t"])
        for lvl in range(levels):
            pairs = part.reshape(*part.shape[:-2],
                                 part.shape[-2] // 2, 2, 32)
            left = _mod2_matmul(pairs[..., 0, :],
                                consts["advances"][lvl])
            part = left ^ pairs[..., 1, :]
        return part[..., 0, :]

    def crc32c_pack_bits(bits):
        """(..., 32) 0/1 int32 -> (...,) uint32."""
        return jnp.sum(bits.astype(jnp.uint32)
                       << jnp.arange(32, dtype=jnp.uint32),
                       axis=-1, dtype=jnp.uint32)

    def crc32c_combine_bits(left_bits, right_bits, advance_t):
        """GF(2) combine: crc(A||B) bits from zero-seeded partials.

        advance_t is the transposed 32x32 zero-run matrix for len(B)
        (from make_combine_advance).
        """
        return _mod2_matmul(left_bits, advance_t) ^ right_bits

    def make_combine_advance(length: int):
        """Transposed 32x32 advance matrix for combining over `length` bytes."""
        return jnp.asarray(_zero_advance_matrix(length).T)

    @functools.lru_cache(maxsize=None)
    def _crc_batch_kernel(length: int):
        consts = make_crc_consts(length)

        @jax.jit
        def kernel(data):
            return crc32c_pack_bits(crc32c_partial_bits(data, consts))

        return kernel

    def crc32c_batch_tpu(blocks: np.ndarray, init: int = 0xFFFFFFFF):
        """crc32c of each row of a (B, L) uint8 array, on device.

        Returns a (B,) uint32 device array: cell matmul + tree combine for
        the zero-seeded linear part, XOR the host-folded seed advance.
        """
        blocks = np.asarray(blocks, dtype=np.uint8)
        assert blocks.ndim == 2
        _, length = blocks.shape
        f = _crc_batch_kernel(length)(jnp.asarray(blocks))
        seed_adv = crc32c_zeros(init & 0xFFFFFFFF, length)
        return f ^ jnp.uint32(seed_adv)

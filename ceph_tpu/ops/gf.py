"""GF(2^8) algebra for erasure coding, designed TPU-first.

The reference executes Reed-Solomon GF(2^8) products with per-byte table
lookups and SSE/AVX shuffles (jerasure/gf-complete, isa-l; see
/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:158-175 and
/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:119-131).  A TPU has no
byte-shuffle unit but has a 128x128 systolic MXU — so we map GF(2^8) linear
algebra onto it by *bit-decomposition*:

  multiplication by a constant c in GF(2^8) is linear over GF(2); it is an
  8x8 0/1 matrix B(c) with column b = bits(c * x^b mod p(x)).  A full
  (m x k) GF(2^8) code matrix therefore becomes an (8m x 8k) GF(2) matrix,
  and `parity = M (*) data` becomes

      parity_bits = (M_bits @ data_bits) mod 2

  — a plain integer matmul followed by a parity reduction, which XLA tiles
  straight onto the MXU.  Sums are bounded by 8k (<= 256 for k <= 32) so the
  accumulation is exact in bf16/int32.

Field: GF(2^8) with primitive polynomial 0x11d and generator x (= 2), the
same field jerasure/gf-complete and isa-l use for w=8, so encoded chunks are
bit-identical with the reference's `reed_sol_van` output.

Host-side (numpy) mirrors of each op serve as the independent reference
implementation for tests and for small/latency-sensitive calls.
"""

from __future__ import annotations

import numpy as np

try:  # JAX is the TPU execution path; numpy path works without it.
    import jax
    import jax.numpy as jnp

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

_backend_ok = None


def backend_available() -> bool:
    """True when a jax backend actually initializes.

    `import jax` succeeding does not guarantee a usable backend (e.g.
    JAX_PLATFORMS names a plugin that fails to load outside its home
    directory); everything that device-dispatches must gate on this and
    fall back to the host path."""
    global _backend_ok
    if _backend_ok is None:
        if not HAVE_JAX:
            _backend_ok = False
        else:
            try:
                jax.devices()
                _backend_ok = True
            except Exception:
                _backend_ok = False
    return _backend_ok

# ---------------------------------------------------------------------------
# Field tables (host, numpy)
# ---------------------------------------------------------------------------

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, jerasure/gf-complete w=8 default
GF_ORDER = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)  # doubled to skip the mod-255 on reads
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a, b):
    """Elementwise GF(2^8) product of uint8 arrays (numpy)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = GF_EXP[GF_LOG[a] + GF_LOG[b]]
    return np.where((a == 0) | (b == 0), 0, out).astype(np.uint8)


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] - GF_LOG[b]) % 255])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def gf_matmul_ref(m: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Reference GF(2^8) matmul on host: (R,K) x (K,S) -> (R,S), XOR-accumulate.

    Independent oracle for the TPU kernels; also the small-input host path.
    """
    m = np.asarray(m, dtype=np.uint8)
    d = np.asarray(d, dtype=np.uint8)
    r, k = m.shape
    out = np.zeros((r, d.shape[1]), dtype=np.uint8)
    for j in range(r):
        acc = np.zeros(d.shape[1], dtype=np.uint8)
        for i in range(k):
            c = int(m[j, i])
            if c == 0:
                continue
            if c == 1:
                acc ^= d[i]
            else:
                acc ^= gf_mul(np.full((), c, np.uint8), d[i])
        out[j] = acc
    return out


_mul_table_cache = None  # bounded LRU, built lazily (avoids an import
#                          cycle: ec.dispatch imports this module)


def _table_cache():
    global _mul_table_cache
    if _mul_table_cache is None:
        from ceph_tpu.ec.dispatch import LruCache

        _mul_table_cache = LruCache(cap=64)
    return _mul_table_cache


def gf_mul_tables(m: np.ndarray) -> np.ndarray:
    """(R,K) GF matrix -> (R*K, 256) per-coefficient multiply tables
    (the jerasure/isa-l table form consumed by the native region ops).
    LRU-cached: a decode-heavy workload cycling >64 matrices evicts
    the coldest table, never the whole cache."""
    m = np.asarray(m, dtype=np.uint8)
    key = (m.shape, m.tobytes())

    def compute() -> np.ndarray:
        r, k = m.shape
        idx = np.arange(256, dtype=np.uint8)
        tables = np.zeros((r * k, 256), dtype=np.uint8)
        for j in range(r):
            for i in range(k):
                tables[j * k + i] = gf_mul(
                    np.full(256, m[j, i], np.uint8), idx)
        return tables

    return _table_cache().get_or_compute(key, compute)


def gf_matmul_host(m: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Host GF(2^8) matmul through the native SIMD kernel when built
    (AVX2/SSSE3 split-table shuffle — the isa-l/jerasure speed tier,
    ceph_tpu/native/src/gf_simd.cc); numpy reference otherwise."""
    from ceph_tpu import native

    lib = native.get_lib()
    if lib is None or not hasattr(lib, "ceph_tpu_gf_matmul_simd"):
        return gf_matmul_ref(m, d)
    import ctypes

    m = np.asarray(m, dtype=np.uint8)
    d = np.ascontiguousarray(d, dtype=np.uint8)
    r, k = m.shape
    s = d.shape[1]
    tables = gf_mul_tables(m)
    out = np.empty((r, s), dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ceph_tpu_gf_matmul_simd(
        tables.ctypes.data_as(u8p), r, k,
        d.ctypes.data_as(u8p), s, out.ctypes.data_as(u8p))
    return out


def gf_invert_matrix(a: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination (host).

    Decode-table construction runs here (k <= 32 — microseconds); the big
    matmul it parameterizes runs on TPU.  Mirrors the role of isa-l's
    gf_invert_matrix (/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:275).
    """
    a = np.array(a, dtype=np.uint8)
    n = a.shape[0]
    assert a.shape == (n, n)
    aug = np.concatenate([a, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = gf_inv(int(aug[col, col]))
        aug[col] = gf_mul(aug[col], np.full((), inv, np.uint8))
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] ^= gf_mul(aug[col], np.full((), aug[row, col], np.uint8))
    return aug[:, n:]


# ---------------------------------------------------------------------------
# Bit-decomposition: GF(2^8) matrix -> GF(2) matrix
# ---------------------------------------------------------------------------


def gf_const_to_bits(c: int) -> np.ndarray:
    """8x8 GF(2) matrix of 'multiply by c': column b = bits(c * x^b)."""
    cols = []
    for b in range(8):
        v = gf_mul(np.full((), c, np.uint8), np.full((), 1 << b, np.uint8))
        cols.append([(int(v) >> o) & 1 for o in range(8)])
    return np.array(cols, dtype=np.uint8).T  # (out_bit, in_bit)


def gf_matrix_to_bits(m: np.ndarray) -> np.ndarray:
    """(R,K) GF(2^8) matrix -> (8R, 8K) GF(2) 0/1 matrix.

    Row j*8+o, col i*8+b is bit o of (m[j,i] * x^b): output bit (j,o) is the
    XOR over data bits (i,b) selected by this matrix.
    """
    m = np.asarray(m, dtype=np.uint8)
    r, k = m.shape
    out = np.zeros((8 * r, 8 * k), dtype=np.uint8)
    for j in range(r):
        for i in range(k):
            out[j * 8 : j * 8 + 8, i * 8 : i * 8 + 8] = gf_const_to_bits(int(m[j, i]))
    return out


# ---------------------------------------------------------------------------
# TPU kernels (JAX)
# ---------------------------------------------------------------------------

if HAVE_JAX:

    def _unpack_bits(data):
        """(..., K, S) uint8 -> (..., 8K, S) bit planes (LSB-first per byte)."""
        k, s = data.shape[-2], data.shape[-1]
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = (data[..., :, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
        return bits.reshape(*data.shape[:-2], k * 8, s)

    def _pack_bits(bits):
        """(..., 8R, S) bits -> (..., R, S) uint8 (LSB-first per byte).

        Bit weighting runs in int32 (TPU-native lane width): the 0/1
        planes times powers-of-two stay exact, and no uint8 `<<`/`*`
        can wrap if a weight or plane is ever wrong upstream.
        """
        r8, s = bits.shape[-2], bits.shape[-1]
        r = r8 // 8
        b = bits.reshape(*bits.shape[:-2], r, 8, s).astype(jnp.int32)
        weights = (jnp.int32(1) << jnp.arange(8, dtype=jnp.int32))[None, :, None]
        return jnp.sum(b * weights, axis=-2).astype(jnp.uint8)

    def _gf2_matmul_bytes_impl(mbits, data):
        """GF(2^8) matmul on the MXU: mbits (8R,8K) 0/1, data (..., K, S) uint8.

        Returns (..., R, S) uint8.  The contraction runs as a bf16 matmul
        (exact: sums <= 8K <= 256 < 2^8 representable in bf16's 8-bit
        mantissa... bf16 integers are exact up to 256), then reduced mod 2.

        Untraced body: ec/plan.py jits it per bucketed shape (with
        donation on TPU); the module-level `gf2_matmul_bytes` below is
        the fixed-shape compat wrapper for direct/shard_map callers.
        """
        bits = _unpack_bits(data).astype(jnp.bfloat16)
        mb = mbits.astype(jnp.bfloat16)
        prod = jax.lax.dot_general(
            mb,
            bits,
            (((1,), (bits.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dot_general with no batch dims puts mb's row axis first:
        # (8R, ..., S) -> move to (..., 8R, S)
        if bits.ndim > 2:
            prod = jnp.moveaxis(prod, 0, -2)
        par = prod.astype(jnp.int32) & 1
        return _pack_bits(par)

    # Shape-polymorphic jit kept for direct and shard_map callers (an
    # inner jit is inlined under shard_map); plan-cached dispatch goes
    # through ec/plan.py, which jits _gf2_matmul_bytes_impl itself.
    gf2_matmul_bytes = jax.jit(_gf2_matmul_bytes_impl)

    def gf_matmul_device(m: np.ndarray, data):
        """(R,K) GF(2^8) matrix x (..., K, S) uint8 through the fastest
        device path: the packed-word xtime Pallas kernel on TPU for
        host-side (numpy) inputs (ops/gf_pallas.py — word-layout entry,
        ~360 GiB/s on a v5e), then schedule-vs-matmul by measured op
        count — a sparse bit expansion whose compiled XOR schedule
        (ec/xsched.py) beats the dense contraction runs as the XOR
        program (ec/plan.xor_sched_direct), everything else as the XLA
        bit-decomposition matmul (a device-side uint8->int32 relayout
        would cost more than the encode)."""
        from ceph_tpu.ops import gf_pallas

        if isinstance(data, np.ndarray) and gf_pallas.supported(
                np.shape(data)):
            return gf_pallas.gf_matmul_pallas(m, data)
        from ceph_tpu.ec import plan  # lazy: plan imports this module

        jfn = plan.xor_sched_direct(m)
        if jfn is not None:
            return jfn(jnp.asarray(data, dtype=jnp.uint8))
        mbits = jnp.asarray(gf_matrix_to_bits(m))
        return gf2_matmul_bytes(mbits, jnp.asarray(data, dtype=jnp.uint8))

    def gf_matmul_tpu(m: np.ndarray, data):
        """(R,K) GF(2^8) matrix x (..., K, S) uint8 chunks on TPU."""
        return gf_matmul_device(m, data)

    def gf_mul_jax(a, b):
        """Elementwise GF(2^8) product via log/antilog gathers (uint8 arrays)."""
        exp = jnp.asarray(GF_EXP)
        log = jnp.asarray(GF_LOG)
        a = jnp.asarray(a, dtype=jnp.uint8)
        b = jnp.asarray(b, dtype=jnp.uint8)
        out = exp[log[a] + log[b]]
        return jnp.where((a == 0) | (b == 0), jnp.uint8(0), out)

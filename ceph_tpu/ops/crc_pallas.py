"""Pallas TPU kernel for batched crc32c on the packed-word layout.

crc32c is GF(2)-linear: the zero-seeded crc of an L-byte block is a
(32 x 8L) 0/1 matrix applied to the block's bits.  Fold the per-cell
matrices and the tree of zero-advance combines (ops/checksum.py's
formulation) into ONE precomputed (8L, 32) matrix M, and the crc of a
whole block is a single GF(2) matmul:

    crc_bits = block_bits @ M   (mod 2)

~256 MACs per data byte — MXU work, not VPU work.  The XLA path
(checksum.crc32c_partial_bits_words) materializes the 8x bit expansion
in HBM between the unpack and the matmul, which caps it at ~8 GiB/s;
here the unpack happens per-tile in VMEM and never touches HBM, so
traffic is data-in + 32 bits out.

Bit-index bookkeeping: the kernel never reshapes bits.  For each bit
position k in 0..31 it extracts the (B, W) 0/1 plane of bit k of every
int32 word and multiplies by M_k = M[k::32] — mathematically identical
to the flat (B, 8L) @ (8L, 32) product, but expressible as 32 clean
(B, W) x (W, 128) MXU dots with no in-kernel relayout.  Accumulation
is exact in int32 (int8 x int8 -> int32 MXU dots, sums bounded by 8L);
mod-2 happens once at the end.

Input layout matches ops/gf_pallas.py: int32 words, bit k of word w =
bit k%8 of byte 4w + k//8 (little-endian view of the byte stream) —
device EC buffers are already in this form, so hinfo/BlueStore-style
per-block checksums of encoded chunks run straight off the encode
kernel's output with no relayout.

Role parity: batched data-path crc32c — src/common/crc32c* (the
reference's asm tier) and the per-4KiB-block checksumming of
BlueStore writes (Checksummer, BlueStore.cc:13642).
"""

from __future__ import annotations

import functools

import numpy as np
from ceph_tpu.common import flags

from ceph_tpu.ops import checksum as cks

try:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_JAX = True
except Exception:  # pragma: no cover
    HAVE_JAX = False

# block-tile rows per grid step
_BT = 128

# VMEM budget for the (32, W, 128) int8 matrix stack (~4 MiB at
# W=1024, i.e. 4 KiB csum blocks); beyond this the XLA path is used
_MAX_W = 2048

# Test hook, mirroring gf_pallas.FORCE_INTERPRET
FORCE_INTERPRET = False


@functools.lru_cache(maxsize=8)
def _mk_stack(length: int) -> np.ndarray:
    """(32, W, 128) 0/1 stack of per-bit-position matrices.

    M (8L, 32) maps zero-seeded block bits to crc bits: bit (32w + k)
    of the block (bit k of word w) contributes column vector
    M[32w + k].  Built from the cell matrix and zero-advance matrices
    exactly as the XLA tree-fold would compose them.
    """
    assert length % cks._CELL == 0
    ncells = length // cks._CELL
    cell = cks._cell_matrix()                      # (32, 512)
    rows = []
    for j in range(ncells):
        adv = cks._zero_advance_matrix(cks._CELL * (ncells - 1 - j))
        mj = (adv.astype(np.uint32) @ cell.astype(np.uint32)) & 1
        rows.append(mj.T.astype(np.uint8))         # (512, 32)
    big = np.concatenate(rows, axis=0)             # (8L, 32)
    w = length // 4
    mk = np.zeros((32, w, 128), dtype=np.uint8)
    for k in range(32):
        mk[k, :, :32] = big[k::32]
    return mk


def supported(length: int, n_blocks: int,
              platform: str | None = None) -> bool:
    if not flags.enabled("CEPH_TPU_PALLAS"):
        return False  # same kill switch as gf_pallas
    if not HAVE_JAX:
        return False
    if length % cks._CELL or length // 4 > _MAX_W:
        return False
    if not FORCE_INTERPRET:
        try:
            plat = platform or jax.devices()[0].platform
        except Exception:
            return False
        if plat != "tpu":
            return False
    return n_blocks > 0


if HAVE_JAX:

    def _crc_kernel(w_ref, m_ref, o_ref):
        # int8 x int8 -> int32 MXU dots: exact (operands are 0/1, sums
        # bounded by 8L), and measured ~4x the bf16 rate on v5e
        acc = None
        w = w_ref[...]                             # (BT, W) int32
        for k in range(32):
            bits = ((jax.lax.shift_right_logical(w, jnp.int32(k))
                     & jnp.int32(1))).astype(jnp.int8)
            d = jax.lax.dot_general(
                bits, m_ref[k],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            acc = d if acc is None else acc + d
        o_ref[...] = acc & 1

    @functools.lru_cache(maxsize=8)
    def _crc_call(n_tiles: int, w: int):
        return pl.pallas_call(
            _crc_kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((_BT, w), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((32, w, 128), lambda i: (0, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((_BT, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((n_tiles * _BT, 128),
                                           jnp.int32),
            interpret=FORCE_INTERPRET,
        )

    def crc32c_blocks_words(words, length: int, init: int = 0xFFFFFFFF):
        """crc32c of every `length`-byte block, blocks given as int32
        words (n_blocks, length//4) in the device layout.  Returns an
        (n_blocks,) uint32 device array.

        The seed enters via linearity: crc(seed, B) =
        crc(0, B) ^ advance(seed, len) — the advance is one host
        constant XORed into every lane.
        """
        n_blocks, w = words.shape
        assert w == length // 4, (words.shape, length)
        mk = jnp.asarray(_mk_stack(length), dtype=jnp.int8)
        pad = -n_blocks % _BT
        if pad:
            words = jnp.pad(words, ((0, pad), (0, 0)))
        bits = _crc_call((n_blocks + pad) // _BT, w)(words, mk)
        crcs = jnp.sum(
            bits[:n_blocks, :32].astype(jnp.uint32)
            << jnp.arange(32, dtype=jnp.uint32),
            axis=-1, dtype=jnp.uint32)
        seed_adv = cks.crc32c_zeros(init & 0xFFFFFFFF, length)
        return crcs ^ jnp.uint32(seed_adv)

"""Tensor kernels: GF(2^8) algebra, hashes, checksums."""
